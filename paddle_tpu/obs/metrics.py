"""Process-wide metrics registry (the obs subsystem's numbers half).

One table of named metric families — counters, gauges, histograms —
shared by every subsystem that previously kept a private tally
(framework/syncs host-sync count, compilation/counters XLA compiles,
the engine's tick/admit integers, the router's stats_counters dict).
The ad-hoc counters stay (their delta-reader contracts are load-bearing
in tests); this registry is the EXPORTED view: Prometheus-style text on
``/metrics`` (inference/serve.py, inference/router.py), scrapeable and
aggregatable across a replica tier.

Design rules:

* **Bounded label sets.** A family declares its label NAMES once; the
  number of label-value series is capped (``max_series``, default 64).
  Past the cap, new label values fold into one ``_other`` series —
  per-replica forward latency over months of rolling restarts
  (r1..r4096) must not grow the registry without bound.
* **Lock-guarded, ~zero-cost when untouched.** Each family serializes
  its mutations on one lock (an observe is a few dict/list ops — the
  lock cost is nil next to the XLA program the hot path just ran). A
  family that nothing created costs nothing: the registry is a dict
  that starts empty.
* **Monotonic freshness token.** Every mutation bumps a process-global
  sequence (a GIL-guarded int, the framework/syncs idiom) surfaced as
  ``metrics_seq`` in ``/healthz`` — a router can tell a live replica
  whose numbers move from a wedged one re-serving stale text.

The text format is the Prometheus exposition subset the in-repo parser
(``parse_text``) understands: ``# TYPE`` comments, ``name{l="v"} value``
samples, ``_bucket``/``_sum``/``_count`` histogram triads with
cumulative ``le`` buckets. Percentiles are estimated from the buckets
by linear interpolation (``percentile_from_cum``) — what
tools/bench_serving.py reports as phase percentiles.
"""
from __future__ import annotations

import math
import re
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from . import locks as _locks

__all__ = [
    "Counter", "Gauge", "Histogram", "HistSnap", "Registry", "registry",
    "DEFAULT_BUCKETS_MS", "OVERFLOW_LABEL",
    "parse_text", "samples_to_hist", "percentile_from_cum",
    "render_tier",
]

# latency buckets in milliseconds: sub-ms CPU ticks up to minute-class
# compiles all land in a resolvable bucket
DEFAULT_BUCKETS_MS = (0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
                      250.0, 500.0, 1000.0, 2500.0, 5000.0, 15000.0,
                      60000.0)

# where label values past a family's series cap fold (bounded label sets)
OVERFLOW_LABEL = "_other"


def _escape(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"') \
        .replace("\n", r"\n")


def _fmt_labels(names: Tuple[str, ...], values: Tuple[str, ...],
                extra: Tuple[Tuple[str, str], ...] = ()) -> str:
    pairs = [f'{n}="{_escape(v)}"' for n, v in zip(names, values)]
    pairs += [f'{n}="{_escape(v)}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


class _Family:
    """Base: one named metric family with a fixed label-name tuple and
    a bounded set of label-value series."""

    kind = "untyped"

    def __init__(self, reg: "Registry", name: str, help_: str,
                 label_names: Tuple[str, ...], max_series: int = 64):
        self._reg = reg
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self.max_series = int(max_series)
        self._series: Dict[Tuple[str, ...], object] = {}
        # ONE shared site name for every family: bounded label set
        self._lock = _locks.make_lock("metrics.family")

    def _key_of(self, labels: dict) -> Tuple[str, ...]:
        """Exact label-values key (validated). Readers use this raw —
        a never-written series must read as absent, not as the
        overflow series; the ``_other`` fold is a WRITE policy only."""
        if len(labels) != len(self.label_names) or any(
                n not in labels for n in self.label_names):
            raise ValueError(
                f"{self.name} takes exactly labels {self.label_names}; "
                f"got {sorted(labels)}")
        return tuple(str(labels[n]) for n in self.label_names)

    def _zero(self):
        raise NotImplementedError

    def _get_locked(self, labels: dict):
        # *_locked convention (tpurace-checked): caller holds self._lock
        # — the membership test + overflow fallback + insert below are
        # one atomic step only under it
        key = self._key_of(labels)
        if key not in self._series and len(self._series) >= \
                self.max_series:
            # bounded label set: overflow series, never unbounded growth
            key = (OVERFLOW_LABEL,) * len(self.label_names)
        s = self._series.get(key)
        if s is None:
            s = self._series[key] = self._zero()
        return s

    def remove(self, **labels) -> None:
        """Drop one series (exact match). For label values whose
        subject is GONE — a retired replica's breaker gauge must not
        read 1 forever, nor hold a slot against the series cap."""
        with self._lock:
            self._series.pop(self._key_of(labels), None)
        self._reg._bump()

    def series(self) -> Dict[Tuple[str, ...], object]:
        with self._lock:
            return dict(self._series)


class Counter(_Family):
    kind = "counter"

    def _zero(self):
        return [0.0]

    def inc(self, n: float = 1, **labels) -> None:
        with self._lock:
            self._get_locked(labels)[0] += n
        self._reg._bump()

    def value(self, **labels) -> float:
        with self._lock:
            s = self._series.get(self._key_of(labels))
            return float(s[0]) if s else 0.0

    def render(self, out: List[str]) -> None:
        with self._lock:
            items = sorted(self._series.items())
        out.append(f"# TYPE {self.name} counter")
        for key, s in items:
            out.append(f"{self.name}"
                       f"{_fmt_labels(self.label_names, key)} {s[0]:g}")


class Gauge(_Family):
    kind = "gauge"

    def _zero(self):
        return [0.0]

    def set(self, v: float, **labels) -> None:
        with self._lock:
            self._get_locked(labels)[0] = float(v)
        self._reg._bump()

    def inc(self, n: float = 1, **labels) -> None:
        with self._lock:
            self._get_locked(labels)[0] += n
        self._reg._bump()

    def value(self, **labels) -> float:
        with self._lock:
            s = self._series.get(self._key_of(labels))
            return float(s[0]) if s else 0.0

    def render(self, out: List[str]) -> None:
        with self._lock:
            items = sorted(self._series.items())
        out.append(f"# TYPE {self.name} gauge")
        for key, s in items:
            out.append(f"{self.name}"
                       f"{_fmt_labels(self.label_names, key)} {s[0]:g}")


class HistSnap:
    """Point-in-time copy of one histogram series — subtractable so a
    bench can report percentiles over exactly its measured phase."""

    __slots__ = ("edges", "counts", "sum", "count")

    def __init__(self, edges, counts, sum_, count):
        self.edges = tuple(edges)
        self.counts = list(counts)          # per-bucket, NOT cumulative
        self.sum = float(sum_)
        self.count = int(count)

    def minus(self, earlier: "HistSnap") -> "HistSnap":
        return HistSnap(self.edges,
                        [a - b for a, b in zip(self.counts,
                                               earlier.counts)],
                        self.sum - earlier.sum,
                        self.count - earlier.count)

    def percentile(self, q: float) -> float:
        cum, acc = [], 0.0
        for c in self.counts:
            acc += c
            cum.append(acc)
        return percentile_from_cum(self.edges, cum, q)


class Histogram(_Family):
    kind = "histogram"

    def __init__(self, reg, name, help_, label_names,
                 buckets: Optional[Sequence[float]] = None,
                 max_series: int = 64):
        super().__init__(reg, name, help_, label_names, max_series)
        self.buckets = tuple(sorted(buckets if buckets is not None
                                    else DEFAULT_BUCKETS_MS))

    def _zero(self):
        # [per-bucket counts..., +Inf count, sum, count]
        return [[0] * (len(self.buckets) + 1), 0.0, 0]

    def observe(self, v: float, **labels) -> None:
        v = float(v)
        with self._lock:
            s = self._get_locked(labels)
            i = len(self.buckets)
            for j, edge in enumerate(self.buckets):
                if v <= edge:
                    i = j
                    break
            s[0][i] += 1
            s[1] += v
            s[2] += 1
        self._reg._bump()

    def snap(self, **labels) -> HistSnap:
        key_labels = labels or {}
        with self._lock:
            s = self._series.get(self._key_of(key_labels))
            if s is None:
                return HistSnap(self.buckets,
                                [0] * (len(self.buckets) + 1), 0.0, 0)
            return HistSnap(self.buckets, list(s[0]), s[1], s[2])

    def render(self, out: List[str]) -> None:
        with self._lock:
            items = sorted((k, (list(s[0]), s[1], s[2]))
                           for k, s in self._series.items())
        out.append(f"# TYPE {self.name} histogram")
        for key, (counts, sum_, count) in items:
            acc = 0
            for edge, c in zip(self.buckets, counts):
                acc += c
                out.append(
                    f"{self.name}_bucket"
                    f"{_fmt_labels(self.label_names, key, (('le', f'{edge:g}'),))}"
                    f" {acc}")
            out.append(
                f"{self.name}_bucket"
                f"{_fmt_labels(self.label_names, key, (('le', '+Inf'),))}"
                f" {count}")
            lbl = _fmt_labels(self.label_names, key)
            out.append(f"{self.name}_sum{lbl} {sum_:g}")
            out.append(f"{self.name}_count{lbl} {count}")


class Registry:
    """Get-or-create table of metric families; ONE per process
    (module-level ``registry``). A second create with the same name
    returns the existing family (kind mismatches raise — two
    subsystems silently sharing a name under different types is a
    corruption, not a convenience)."""

    def __init__(self):
        self._lock = _locks.make_rlock("metrics.registry")
        self._families: Dict[str, _Family] = {}
        self._seq = 0

    def _bump(self):
        # freshness token only: a plain GIL-guarded int (syncs.py idiom)
        self._seq += 1

    def seq(self) -> int:
        return self._seq

    def _get_or_create(self, cls, name, help_, labels, **kw):
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = cls(self, name, help_, tuple(labels), **kw)
                self._families[name] = fam
            elif not isinstance(fam, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{fam.kind}, requested {cls.kind}")
            return fam

    def counter(self, name: str, help_: str = "",
                labels: Sequence[str] = (), max_series: int = 64
                ) -> Counter:
        return self._get_or_create(Counter, name, help_, labels,
                                   max_series=max_series)

    def gauge(self, name: str, help_: str = "",
              labels: Sequence[str] = (), max_series: int = 64) -> Gauge:
        return self._get_or_create(Gauge, name, help_, labels,
                                   max_series=max_series)

    def histogram(self, name: str, help_: str = "",
                  labels: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None,
                  max_series: int = 64) -> Histogram:
        return self._get_or_create(Histogram, name, help_, labels,
                                   buckets=buckets,
                                   max_series=max_series)

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)

    def render(self) -> str:
        with self._lock:
            fams = sorted(self._families.values(),
                          key=lambda f: f.name)
        out: List[str] = []
        for fam in fams:
            if fam.help:
                out.append(f"# HELP {fam.name} {fam.help}")
            fam.render(out)
        return "\n".join(out) + ("\n" if out else "")


#: the ONE process-wide registry every instrumented site writes to
registry = Registry()


# ---------------------------------------------------------------------------
# text parsing + aggregation (router tier scrape, bench percentiles)
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^([A-Za-z_:][A-Za-z0-9_:]*)(?:\{(.*)\})?\s+([^\s]+)$")
_LABEL_RE = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_text(text: str) -> List[Tuple[str, Dict[str, str], float]]:
    """Parse exposition text into ``(name, labels, value)`` samples.
    Tolerant of comment/blank lines; malformed lines are skipped (a
    scrape of a half-dead replica must degrade, not raise)."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, raw_labels, raw_v = m.groups()
        try:
            v = float(raw_v)
        except ValueError:
            continue
        labels = {k: val.replace(r'\"', '"').replace(r"\\", "\\")
                  for k, val in _LABEL_RE.findall(raw_labels or "")}
        out.append((name, labels, v))
    return out


def percentile_from_cum(edges: Sequence[float], cum: Sequence[float],
                        q: float) -> float:
    """Estimate the q-quantile (q in [0,1]) from cumulative bucket
    counts ``cum`` over upper ``edges`` (+Inf implied as the last cum
    entry when ``len(cum) == len(edges) + 1``). Linear interpolation
    inside the winning bucket; the +Inf bucket clamps to the last
    finite edge (the estimate cannot exceed what the buckets resolve)."""
    if not cum or not edges:
        return 0.0
    total = cum[-1]
    if total <= 0:
        return 0.0
    target = q * total
    prev = 0.0
    for i, c in enumerate(cum):
        if c >= target and c > prev:
            lo = edges[i - 1] if i > 0 else 0.0
            hi = edges[i] if i < len(edges) else edges[-1]
            if hi <= lo or not math.isfinite(hi):
                return float(lo)
            frac = (target - prev) / (c - prev)
            return float(lo + (hi - lo) * min(max(frac, 0.0), 1.0))
        prev = max(prev, c)
    return float(edges[-1])


def samples_to_hist(samples: Iterable[Tuple[str, Dict[str, str], float]],
                    name: str, **match_labels
                    ) -> Tuple[List[float], List[float]]:
    """Collect one histogram's ``_bucket`` samples (summed across any
    non-``le`` label splits that match ``match_labels``) into
    ``(edges, cumulative_counts)`` ready for ``percentile_from_cum``."""
    by_le: Dict[float, float] = {}
    inf = 0.0
    for n, labels, v in samples:
        if n != f"{name}_bucket":
            continue
        if any(labels.get(k) != str(val)
               for k, val in match_labels.items()):
            continue
        le = labels.get("le", "")
        if le in ("+Inf", "inf", "Inf"):
            inf += v
        else:
            try:
                by_le[float(le)] = by_le.get(float(le), 0.0) + v
            except ValueError:
                continue
    edges = sorted(by_le)
    cum = [by_le[e] for e in edges] + [max(inf, by_le[edges[-1]]
                                           if edges else inf)]
    return edges, cum


def render_tier(own_text: str, replica_texts: Dict[str, str],
                prefix: str = "ptpu_", tier_prefix: str = "ptpu_tier_"
                ) -> str:
    """The router's /metrics body: its own series verbatim, every
    scraped replica's samples re-labeled ``replica="rN"``, and
    tier-level aggregates — each ``ptpu_*`` sample summed across
    replicas under ``ptpu_tier_*`` (counters and cumulative histogram
    buckets sum exactly; summed gauges read as tier totals, e.g.
    aggregate slot occupancy)."""
    out = [own_text.rstrip("\n")] if own_text.strip() else []
    agg: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    for rname, text in sorted(replica_texts.items()):
        for name, labels, v in parse_text(text):
            items = tuple(sorted(labels.items()))
            lbl_txt = "{" + ",".join(
                [f'{k}="{_escape(val)}"' for k, val in items]
                + [f'replica="{_escape(rname)}"']) + "}"
            out.append(f"{name}{lbl_txt} {v:g}")
            if name.startswith(prefix):
                key = (tier_prefix + name[len(prefix):], items)
                agg[key] = agg.get(key, 0.0) + v
    for (name, items), v in sorted(agg.items()):
        lbl_txt = ("{" + ",".join(f'{k}="{_escape(val)}"'
                                  for k, val in items) + "}"
                   if items else "")
        out.append(f"{name}{lbl_txt} {v:g}")
    return "\n".join(out) + ("\n" if out else "")
