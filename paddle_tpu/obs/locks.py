"""tpurace runtime half: the lock sanitizer.

The static lint (analysis/concurrency.py) proves discipline the AST
can see; this module watches the discipline the SCHEDULE exercises.
``make_lock``/``make_rlock``/``make_condition`` are drop-in factories
adopted at the tier's hottest lock sites (engine cv, router lock,
request journals, metrics registry + families, compilation store).
With ``PADDLE_TPU_LOCK_SAN`` unset they return PLAIN ``threading``
primitives — the zero-overhead-when-off contract the obs package made
in PR 8, and what keeps the decode tick inside the
``bench_obs_overhead`` <= 1.02 gate. With the sanitizer on, every
acquire/release is measured and modeled:

* wait + hold times land in the ``ptpu_lock_wait_ms`` /
  ``ptpu_lock_hold_ms`` histograms (label ``lock=<site name>``) — the
  alerting surface for "a lock got slow" long before it deadlocks;
* acquisition ORDER edges (lock A held while taking lock B) build a
  runtime lock-order graph, checked inline: the first edge that closes
  a cycle dumps a ``lock_order_cycle`` flight artifact naming the
  cycle — you learn two sites disagree on order the first time EITHER
  interleaving runs, not the unlucky night both run at once;
* a watchdog thread walks the waits-for graph (thread -> lock it is
  blocked on -> holders) and dumps a ``lock_deadlock`` artifact naming
  both locks AND the holder stacks (``sys._current_frames``) when a
  cycle persists across two scans.

Instance names are SITE names, shared across instances of the same
class (every request journal is ``journal.cond``): the graph and the
histogram label set stay bounded no matter how many requests flow.
Edges between two instances of one name are therefore ignored — two
journals locked in either order is not an order inversion.

Fault site: ``resilience`` ``lock_hold`` (a wedge-type site) fires
INSIDE ``release()`` while the lock is still held, spiking hold time
artificially so the ``ptpu_lock_wait_ms`` alerting path and the
watchdog are testable without a real wedge. Reached via
``sys.modules`` — this module keeps the obs stdlib-only import
contract, and a resilience module nobody imported can have no armed
faults.

Like the rest of obs, stdlib-only; ``metrics`` is imported lazily at
first record (it imports this module for its own family locks — the
lazy import plus a per-thread reentrancy guard breaks the cycle).
"""
from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional, Set, Tuple

__all__ = ["lock_san_enabled", "set_lock_san", "make_lock",
           "make_rlock", "make_condition", "InstrumentedLock",
           "sanitizer", "LockSanitizer"]

_san_override = None          # set_lock_san() tri-state; None -> env
_san_env = None               # cached env read


def lock_san_enabled() -> bool:
    """Is the lock sanitizer on? One cached read of
    ``PADDLE_TPU_LOCK_SAN`` (default OFF — the factories must cost
    nothing on the serving hot path unless asked); ``set_lock_san``
    overrides for tests and race_hunt."""
    global _san_env
    if _san_override is not None:
        return _san_override
    if _san_env is None:
        raw = os.environ.get("PADDLE_TPU_LOCK_SAN")
        _san_env = (raw is not None
                    and raw.strip().lower() not in ("0", "false", "off",
                                                    ""))
    return _san_env


def set_lock_san(on) -> None:
    """Force the sanitizer on/off (``None`` re-reads the env). Affects
    locks built AFTER the call — existing plain locks stay plain."""
    global _san_override, _san_env
    _san_override = None if on is None else bool(on)
    _san_env = None


# ---------------------------------------------------------------------------
# sanitizer core
# ---------------------------------------------------------------------------

# buckets tuned for lock times: microseconds to wedge-class seconds
_LOCK_BUCKETS_MS = (0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 50.0,
                    100.0, 500.0, 1000.0, 5000.0)


class LockSanitizer:
    """Process-wide sanitizer state. ONE instance (module singleton);
    its own bookkeeping is guarded by a PLAIN lock — instrumenting the
    instrument would recurse."""

    def __init__(self, watchdog_interval_s: float = 2.0):
        self._lock = threading.Lock()
        self._tl = threading.local()
        # name-level order graph: (a, b) -> hit count
        self.order_edges: Dict[Tuple[str, str], int] = {}
        self._adj: Dict[str, Set[str]] = {}
        self._cycles_dumped: Set[frozenset] = set()
        self.cycle_artifacts: List[str] = []
        self.deadlock_artifacts: List[str] = []
        # instance-level live state for the watchdog
        self._holders: Dict[int, Tuple[str, Set[int]]] = {}
        self._waiting: Dict[int, Tuple[int, str]] = {}  # tid -> (lockid, name)
        self._watchdog: Optional[threading.Thread] = None
        self._watchdog_stop = threading.Event()
        self._watchdog_interval = watchdog_interval_s
        self._suspect: Optional[frozenset] = None
        self._deadlocks_dumped: Set[frozenset] = set()

    # -- thread-local plumbing ------------------------------------------
    def _held_stack(self) -> List[list]:
        st = getattr(self._tl, "held", None)
        if st is None:
            st = self._tl.held = []
        return st

    # -- acquire / release events ---------------------------------------
    def note_wait_start(self, lock: "InstrumentedLock") -> None:
        tid = threading.get_ident()
        with self._lock:
            self._waiting[tid] = (id(lock), lock.name)
        self._ensure_watchdog()

    def note_wait_end(self, lock: "InstrumentedLock") -> None:
        tid = threading.get_ident()
        with self._lock:
            self._waiting.pop(tid, None)

    def note_acquired(self, lock: "InstrumentedLock",
                      wait_s: float) -> None:
        tid = threading.get_ident()
        stack = self._held_stack()
        for entry in stack:
            if entry[0] is lock:         # reentrant re-acquire
                entry[2] += 1
                return
        new_edges = []
        for entry in stack:
            if entry[0].name != lock.name:
                new_edges.append((entry[0].name, lock.name))
        stack.append([lock, time.perf_counter(), 1])
        with self._lock:
            self._holders.setdefault(id(lock),
                                     (lock.name, set()))[1].add(tid)
            fresh = []
            for e in new_edges:
                n = self.order_edges.get(e, 0)
                self.order_edges[e] = n + 1
                if n == 0:
                    self._adj.setdefault(e[0], set()).add(e[1])
                    fresh.append(e)
            cycles = [self._cycle_through_locked(e) for e in fresh]
        self._observe("ptpu_lock_wait_ms", lock.name, wait_s * 1e3)
        for cyc in cycles:
            if cyc:
                self._dump_cycle(cyc)

    def note_release(self, lock: "InstrumentedLock") -> Optional[float]:
        """Called BEFORE the inner release — the ``lock_hold`` fault,
        if armed, fires while still held. Returns the hold time in ms
        (the CALLER records it, after the real release: recording
        takes a metrics family lock, and doing that while this lock is
        still held would put instrumentation edges — or worse, a
        same-instance re-acquire — into the graph being measured)."""
        stack = self._held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] is lock:
                stack[i][2] -= 1
                if stack[i][2] > 0:
                    return None                 # still reentrantly held
                t0 = stack[i][1]
                del stack[i]
                break
        else:
            return None     # release of a lock we never saw acquired
        resil = sys.modules.get("paddle_tpu.distributed.resilience")
        if resil is not None:
            try:
                resil.maybe_inject("lock_hold")
            except Exception:   # noqa: BLE001 — injection must not wedge
                pass            # the release path itself
        tid = threading.get_ident()
        with self._lock:
            h = self._holders.get(id(lock))
            if h is not None:
                h[1].discard(tid)
                if not h[1]:
                    self._holders.pop(id(lock), None)
        return (time.perf_counter() - t0) * 1e3

    def in_record(self) -> bool:
        """True while THIS thread is inside a sanitizer->metrics
        record. Instrumented locks bypass all bookkeeping under it —
        the family locks the recording itself takes must not feed
        back into the graph (or deadlock re-acquiring themselves)."""
        return getattr(self._tl, "in_record", False)

    # -- metrics (lazy, reentrancy-guarded) ------------------------------
    def _observe(self, hist_name: str, lock_name: str, ms: float) -> None:
        if getattr(self._tl, "in_record", False):
            return
        if lock_name.startswith("metrics."):
            # the metrics locks guard the histograms that would hold
            # their own timings — self-referential; the order graph
            # and watchdog still cover them
            return
        self._tl.in_record = True
        try:
            from . import metrics as _m
            _m.registry.histogram(
                hist_name, "lock sanitizer timing", labels=("lock",),
                buckets=_LOCK_BUCKETS_MS).observe(ms, lock=lock_name)
        except Exception:   # noqa: BLE001 — telemetry must never
            pass            # break the lock it measures
        finally:
            self._tl.in_record = False

    # -- static-order cycle check (inline, on new edge) ------------------
    def _cycle_through_locked(self, edge: Tuple[str, str]
                              ) -> Optional[List[str]]:
        """Path edge[1] ->* edge[0] in the name graph closes a cycle
        through the new edge. Caller holds self._lock."""
        a, b = edge
        path = self._find_path_locked(b, a)
        if path is None:
            return None
        cyc = path                      # b ... a; edge a->b closes it
        key = frozenset(cyc)
        if key in self._cycles_dumped:
            return None
        self._cycles_dumped.add(key)
        return cyc

    def _find_path_locked(self, src: str,
                          dst: str) -> Optional[List[str]]:
        seen = {src}
        stack = [(src, [src])]
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._adj.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def _dump_cycle(self, cyc: List[str]) -> None:
        with self._lock:
            edges = {f"{a}->{b}": n
                     for (a, b), n in sorted(self.order_edges.items())}
        try:
            from .trace import dump_flight
            path = dump_flight("lock_order_cycle", extra={
                "locks": cyc,
                "cycle": "->".join(cyc + [cyc[0]]),
                "thread": threading.current_thread().name,
                "stack": traceback.format_stack()[-12:],
                "edges": edges,
            })
            self.cycle_artifacts.append(path)
        except Exception:   # noqa: BLE001
            pass

    # -- deadlock watchdog ----------------------------------------------
    def _ensure_watchdog(self) -> None:
        # intentional double-checked fast path: a stale read only costs
        # one trip into the locked re-check below
        w = self._watchdog  # tpurace: disable=race-unguarded-attr
        if w is not None and w.is_alive():
            return
        with self._lock:
            if self._watchdog is not None and self._watchdog.is_alive():
                return
            self._watchdog = threading.Thread(
                target=self._watch, name="ptpu-lock-watchdog",
                daemon=True)
            self._watchdog.start()

    def _scan(self) -> Optional[Tuple[frozenset, dict]]:
        """One waits-for pass: thread -> lock it waits on -> holder
        threads. A thread-cycle is a deadlock candidate."""
        with self._lock:
            waits = dict(self._waiting)
            holders = {lid: (name, set(tids))
                       for lid, (name, tids) in self._holders.items()}
        # tid -> set of tids it waits on (via the lock's holders)
        graph: Dict[int, Set[int]] = {}
        via: Dict[int, str] = {}
        for tid, (lid, name) in waits.items():
            h = holders.get(lid)
            if not h:
                continue
            graph[tid] = set(h[1]) - {tid}
            via[tid] = name
        # cycle over thread ids
        for start in graph:
            stack = [(start, [start])]
            seen = {start}
            while stack:
                node, path = stack.pop()
                for nxt in graph.get(node, ()):
                    if nxt == start and len(path) > 1:
                        cyc = frozenset(path)
                        return cyc, {
                            "threads": sorted(path),
                            "locks": sorted({via[t] for t in path
                                             if t in via})}
                    if nxt not in seen and nxt in graph:
                        seen.add(nxt)
                        stack.append((nxt, path + [nxt]))
        return None

    def _watch(self) -> None:
        while not self._watchdog_stop.wait(self._watchdog_interval):
            hit = self._scan()
            if hit is None:
                self._suspect = None
                continue
            cyc, info = hit
            # _suspect is touched only by this watchdog thread
            if self._suspect != cyc:  # tpurace: disable=race-check-then-act
                self._suspect = cyc     # confirm on the NEXT scan: a
                continue                # slow critical section is not
            self._suspect = None        # a deadlock
            if cyc in self._deadlocks_dumped:
                continue        # one artifact per distinct wait cycle
            self._deadlocks_dumped.add(cyc)
            frames = sys._current_frames()
            stacks = {
                str(t): "".join(traceback.format_stack(frames[t]))
                for t in cyc if t in frames}
            try:
                from .trace import dump_flight
                path = dump_flight("lock_deadlock", extra=dict(
                    info, holder_stacks=stacks))
                self.deadlock_artifacts.append(path)
            except Exception:   # noqa: BLE001
                pass

    def stop_watchdog(self) -> None:
        self._watchdog_stop.set()
        with self._lock:
            w = self._watchdog
            self._watchdog = None
        if w is not None and w.is_alive():
            # join OUTSIDE self._lock: the watchdog's scan takes it
            w.join(timeout=2 * self._watchdog_interval + 1)
        self._watchdog_stop = threading.Event()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "edges": {f"{a}->{b}": n
                          for (a, b), n in sorted(self.order_edges.items())},
                "cycle_artifacts": list(self.cycle_artifacts),
                "deadlock_artifacts": list(self.deadlock_artifacts),
            }


_sanitizer: Optional[LockSanitizer] = None
_sanitizer_guard = threading.Lock()


def sanitizer() -> LockSanitizer:
    """The process-wide sanitizer (created on first instrumented
    lock)."""
    global _sanitizer
    if _sanitizer is None:
        with _sanitizer_guard:
            if _sanitizer is None:
                _sanitizer = LockSanitizer()
    return _sanitizer


def reset_sanitizer() -> LockSanitizer:
    """Fresh sanitizer state (tests / race_hunt runs). Locks made
    before the reset keep reporting — into the NEW state."""
    global _sanitizer
    with _sanitizer_guard:
        if _sanitizer is not None:
            _sanitizer.stop_watchdog()
        _sanitizer = LockSanitizer()
    return _sanitizer


# ---------------------------------------------------------------------------
# the instrumented primitive + factories
# ---------------------------------------------------------------------------

class InstrumentedLock:
    """Drop-in for ``threading.Lock``/``RLock`` that reports to the
    sanitizer. Also speaks the ``Condition`` inner-lock protocol
    (``_release_save``/``_acquire_restore``/``_is_owned``) when built
    on an RLock, so ``make_condition`` can wrap one."""

    def __init__(self, name: str, reentrant: bool = False):
        self.name = name
        self.reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        san = sanitizer()
        if san.in_record():
            return self._inner.acquire(blocking, timeout)
        t0 = time.perf_counter()
        san.note_wait_start(self)
        try:
            got = self._inner.acquire(blocking, timeout)
        finally:
            san.note_wait_end(self)
        if got:
            san.note_acquired(self, time.perf_counter() - t0)
        return got

    def release(self) -> None:
        san = sanitizer()
        if san.in_record():
            self._inner.release()
            return
        hold_ms = san.note_release(self)
        self._inner.release()
        if hold_ms is not None:
            san._observe("ptpu_lock_hold_ms", self.name, hold_ms)

    def locked(self) -> bool:
        inner = self._inner
        if hasattr(inner, "locked"):
            return inner.locked()
        # RLock pre-3.12 has no locked(): probe without blocking
        if inner.acquire(blocking=False):
            inner.release()
            return False
        return True

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    # -- threading.Condition inner-lock protocol (RLock-backed) ---------
    def _release_save(self):
        # cond.wait(): the lock is FULLY released however deep the
        # reentry — collapse the sanitizer's depth so the hold ends too
        san = sanitizer()
        if san.in_record():
            return self._inner._release_save()
        for entry in san._held_stack():
            if entry[0] is self:
                entry[2] = 1
                break
        hold_ms = san.note_release(self)
        state = self._inner._release_save()
        if hold_ms is not None:
            san._observe("ptpu_lock_hold_ms", self.name, hold_ms)
        return state

    def _acquire_restore(self, state) -> None:
        san = sanitizer()
        if san.in_record():
            self._inner._acquire_restore(state)
            return
        t0 = time.perf_counter()
        san.note_wait_start(self)
        try:
            self._inner._acquire_restore(state)
        finally:
            san.note_wait_end(self)
        san.note_acquired(self, time.perf_counter() - t0)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


def make_lock(name: str):
    """A mutex for the named site: plain ``threading.Lock`` unless the
    sanitizer is on."""
    if not lock_san_enabled():
        return threading.Lock()
    return InstrumentedLock(name)


def make_rlock(name: str):
    if not lock_san_enabled():
        return threading.RLock()
    return InstrumentedLock(name, reentrant=True)


def make_condition(name: str):
    """A condition variable whose inner lock is instrumented (RLock
    semantics, matching ``threading.Condition()``'s default)."""
    if not lock_san_enabled():
        return threading.Condition()
    return threading.Condition(InstrumentedLock(name, reentrant=True))
