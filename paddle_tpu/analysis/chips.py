"""Accelerator roofline constants — the ONE table.

Deliberately dependency-free (stdlib dataclasses only) so tools that
need three numbers — `tools/northstar_model.py` is a pure-arithmetic
planning script that must run on machines without jax — can load this
file standalone via importlib without paying (or requiring) the full
paddle_tpu/jax import. Everything else imports it through
`paddle_tpu.analysis.hlo_cost`, which re-exports the table for the
tpucost roofline.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict

__all__ = ["ChipSpec", "CHIP_SPECS", "DEFAULT_CHIP"]


@dataclass(frozen=True)
class ChipSpec:
    """Roofline constants for one accelerator generation (public specs).
    `peak_flops` is bf16; `hbm_bandwidth` is bytes/s."""
    name: str
    peak_flops: float
    hbm_bandwidth: float
    hbm_capacity: float
    ici_gbps: float = 0.0    # aggregate inter-chip Gbit/s (0 = n/a)


CHIP_SPECS: Dict[str, ChipSpec] = {
    # v5-lite (v5e): the chip the landed 33.6%-MFU 125M anchor ran on
    "v5lite": ChipSpec("v5lite", peak_flops=197e12, hbm_bandwidth=819e9,
                       hbm_capacity=16 * 2**30, ici_gbps=1600),
    # v5p: the north-star pod chip (tools/northstar_model.py)
    "v5p": ChipSpec("v5p", peak_flops=459e12, hbm_bandwidth=2765e9,
                    hbm_capacity=95 * 2**30, ici_gbps=4800),
}
DEFAULT_CHIP = "v5lite"
