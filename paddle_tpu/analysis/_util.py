"""Shared helpers for the analyzers.

Leaf labels are part of finding SITE identity (baseline keys must stay
stable across analyzers and releases), so there is exactly one
implementation: ``argN`` plus jax's keystr path inside that argument.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax

__all__ = ["leaf_labels"]


def leaf_labels(args: Tuple, kwargs: Optional[dict] = None,
                static_argnums: Sequence[int] = ()) -> List[str]:
    """Stable labels for the flattened (args, kwargs) leaves, in jax
    tree_flatten order: positional args first (static ones skipped),
    then kwargs sorted by key."""
    static = set(static_argnums)
    labels: List[str] = []
    for i, a in enumerate(args):
        if i in static:
            continue
        for path, _ in jax.tree_util.tree_flatten_with_path(a)[0]:
            labels.append(f"arg{i}{jax.tree_util.keystr(path)}")
    for k, v in sorted((kwargs or {}).items()):
        for path, _ in jax.tree_util.tree_flatten_with_path(v)[0]:
            labels.append(f"kw:{k}{jax.tree_util.keystr(path)}")
    return labels
