"""Shared report-artifact emission for the analysis CLIs.

tools/tpulint.py and tools/tpucost.py share one output contract:

- `--json <path>` writes the FULL findings/inventory record atomically
  (.part + rename, so a mid-write kill never leaves a truncated file
  that tools/_have_result.py would have to reject byte-wise);
- the LAST stdout line is always one JSON record — the
  tools/_have_result.py terminal-record predicate tpu_suite2.sh's
  self-skip and tpu_watch2.sh's give-up logic both key on. A failing
  gate is a GOOD record with "gate": "fail" (the measurement landed;
  CI failing is the point), an analyzer crash is {"error": ...}.

One definition here instead of a copy per CLI — the suite/watcher
protocol only works if every tool agrees on what a landed record is.
"""
from __future__ import annotations

import json
import os
from typing import Optional, Sequence

__all__ = ["write_report_artifact", "terminal_record"]


def write_report_artifact(path: Optional[str], record: dict) -> None:
    """Atomically write `record` to `path` (no-op when path is None)."""
    if not path:
        return
    with open(path + ".part", "w") as fh:
        json.dump(record, fh, indent=1)
        fh.write("\n")
    os.replace(path + ".part", path)


def terminal_record(record: dict,
                    keys: Sequence[str] = ()) -> str:
    """The one-line terminal JSON (print as the LAST stdout line).
    `keys` selects a summary subset of `record`; empty = whole record."""
    if keys:
        record = {k: record[k] for k in keys if k in record}
    return json.dumps(record)
