"""tpulint default manifest: the real programs every perf PR rides on.

Four production programs are rebuilt exactly as their owners build them
and handed to the program linter — trace + lower only (the parallel
step additionally compiles for its collective inventory):

- gpt_decode:     the continuous-batching engine's ONE batched decode
                  program (inference/engine.py) over GPT-tiny — the
                  program whose scatter-free one-hot cache writes and
                  cache donation PR 2's speedups depend on.
- llama_prefill:  the generate() prefill program (models/generation.py
                  build_generate_programs) over LLaMA-tiny.
- train_step:     jit.training.TrainStep's fused whole-step program
                  (donated params/buffers/opt state) over GPT-tiny.
- train_step_scan: the fused K-STEP training window (PR 4,
                  TrainStep.scan_steps: lax.scan over a stacked
                  [K, B, S] super-batch, K optimizer steps in one
                  donated program, per-step PRNG keys folded in-program
                  from an argument base key) at K=4 over GPT-tiny.
- parallel_train_step: distributed.ParallelTrainStep under a fake
                  4-device mesh (dp2 x sharding2, ZeRO-2) — compiled,
                  so the GSPMD-inserted collectives are inventoried.

Plus two static recompile-hazard reports: the sequential generate()
path's per-(prompt-len) program key, the hazard the engine's prefill
buckets exist to close (PR 2), and the fused train loop's pinned
2-program signature (scanned window + trailing per-step, PR 4).

Everything is tiny-config and CPU-safe; no program is executed.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .findings import Finding
from .program_lint import lint_program
from .recompile import recompile_report

__all__ = ["ProgramSpec", "default_manifest", "run_manifest",
           "MANIFEST_PROGRAMS"]

MANIFEST_PROGRAMS = ("gpt_decode", "llama_prefill", "train_step",
                     "train_step_scan", "parallel_train_step",
                     "generate_prompt_drift", "train_scan_window_drift")


@dataclass
class ProgramSpec:
    name: str
    build: Callable[[], Tuple[Any, tuple, Optional[Callable]]]
    compile_collectives: bool = False


def _gpt_tiny_model():
    from ..models.gpt import GPTConfig, GPTForCausalLM
    from ..framework import random as _rng
    _rng.seed(0)
    return GPTForCausalLM(GPTConfig(vocab_size=256, hidden_size=64,
                                    num_layers=2, num_heads=4,
                                    max_seq_len=128))


def _build_gpt_decode():
    from ..inference.engine import ContinuousBatchingEngine
    model = _gpt_tiny_model()
    eng = ContinuousBatchingEngine(model, slots=4, max_len=64,
                                   cache_dtype="float32", tick_tokens=4)
    prog = eng._get_decode_prog()
    N = eng.slots
    args = (eng._params, eng._buffers, eng._caches,
            np.zeros(N, np.int32), np.zeros(N, np.int32),
            np.ones(N, bool), np.full(N, -1, np.int32),
            np.zeros((N, 2), np.uint32))
    return prog, args, eng.stop


def _build_llama_prefill():
    from ..models.llama import LlamaConfig, LlamaForCausalLM
    from ..models.generation import build_generate_programs
    from ..jit.functional import raw_state
    from ..framework import random as _rng
    _rng.seed(0)
    model = LlamaForCausalLM(LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=176,
        num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128))
    model.eval()
    P, new = 16, 8
    prefill, _ = build_generate_programs(model, P, new, eos=None,
                                         do_sample=False,
                                         temperature=1.0, top_k=0,
                                         top_p=1.0)
    params, buffers = raw_state(model)
    caches = model.new_cache(1, P + new, "float32")
    args = (params, buffers, np.zeros((1, P), np.int64), caches,
            jax.random.PRNGKey(0))
    return prefill, args, None


def _train_step_parts(model):
    from ..optimizer import AdamW
    from ..models.gpt import GPTForCausalLM
    from ..framework import random as _rng
    opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
    return GPTForCausalLM.loss_fn, opt, _rng


def _build_train_step():
    from ..jit.training import TrainStep
    model = _gpt_tiny_model()
    loss_fn, opt, _rng = _train_step_parts(model)
    step = TrainStep(model, loss_fn, opt)
    step._build()
    ids = np.zeros((2, 32), np.int64)
    args = (step.params, step.buffers, step.opt_state,
            jnp.asarray(1e-3, jnp.float32), jnp.asarray(1, jnp.float32),
            _rng.default_generator().fold_in(1), ids, ids)
    return step._jitted, args, None


def _build_train_step_scan():
    """The fused K-step window exactly as Model.fit dispatches it:
    TrainStep.scan_steps' jitted program at K=4 — super-batch + state
    donated, the PRNG base key an ARGUMENT (per-step keys fold in-
    program), no host callback anywhere in the window."""
    from ..jit.training import TrainStep
    model = _gpt_tiny_model()
    loss_fn, opt, _rng = _train_step_parts(model)
    step = TrainStep(model, loss_fn, opt)
    K = 4
    prog = step._get_scan_prog(K, 2)
    ids = np.zeros((K, 2, 32), np.int64)
    args = (step.params, step.buffers, step.opt_state,
            _rng.get_rng_state(),
            np.full((K,), 1e-3, np.float32),
            np.arange(1, K + 1, dtype=np.float32),
            np.arange(1, K + 1, dtype=np.int32), ids, ids)
    return prog, args, None


def _build_parallel_train_step():
    from ..distributed import mesh as mesh_mod
    from ..distributed.parallel_step import ParallelTrainStep
    prev = mesh_mod.get_mesh(create_default=False)
    devs = jax.devices()
    if len(devs) < 4:
        raise RuntimeError(
            f"parallel_train_step needs >= 4 devices, have {len(devs)} "
            "(run under XLA_FLAGS=--xla_force_host_platform_device_"
            "count=8; tools/tpulint.py sets this up itself)")

    def cleanup():
        mesh_mod.set_mesh(prev)

    try:
        mesh_mod.init_mesh({"dp": 2, "sharding": 2}, devices=devs[:4])
        model = _gpt_tiny_model()
        loss_fn, opt, _rng = _train_step_parts(model)
        step = ParallelTrainStep(model, loss_fn, opt, zero_stage=2)
        ids = np.zeros((4, 32), np.int64)
        raw_batch = (ids, ids)
        step._build(raw_batch)
        args = (step.params, step.buffers, step.opt_state,
                jnp.asarray(1e-3, jnp.float32),
                jnp.asarray(1, jnp.float32),
                _rng.default_generator().fold_in(1)) + raw_batch
    except BaseException:
        # build raised after the global mesh was swapped: restore it
        # here — run_manifest never receives the cleanup on this path
        cleanup()
        raise
    return step._jitted, args, cleanup


def default_manifest() -> List[ProgramSpec]:
    return [
        ProgramSpec("gpt_decode", _build_gpt_decode),
        ProgramSpec("llama_prefill", _build_llama_prefill),
        ProgramSpec("train_step", _build_train_step),
        ProgramSpec("train_step_scan", _build_train_step_scan),
        ProgramSpec("parallel_train_step", _build_parallel_train_step,
                    compile_collectives=True),
    ]


def _generate_prompt_drift_report() -> List[Finding]:
    """Static restatement of PR 2's recompile storm: sequential
    generate() keys one compiled program per exact prompt length, so
    drifting traffic re-traces per request. The engine's bucketed
    prefill is the fix; this report keeps the hazard visible (and the
    analyzer honest) in the baseline."""
    specs = [(np.zeros((1, p), np.int64),) for p in (7, 9, 13)]
    return recompile_report("generate_prompt_drift", specs)


def _train_scan_window_drift_report() -> List[Finding]:
    """The fused train loop's PINNED recompile signature: one drifting-
    length epoch dispatches exactly TWO abstract call shapes — the
    scanned [K, B, S] super-batch window and the trailing per-step
    [B, S] batch (Model._run_epoch_fused's fallback). The baseline pins
    this at 2 programs; a third signature appearing here means the
    fused driver started re-tracing per window length (the hazard
    tests/test_scan_train.py's trace counter also guards at runtime)."""
    specs = [(np.zeros((4, 2, 32), np.int64),
              np.zeros((4, 2, 32), np.int64)),
             (np.zeros((2, 32), np.int64), np.zeros((2, 32), np.int64))]
    return recompile_report("train_scan_window_drift", specs)


def run_manifest(programs: Optional[List[str]] = None,
                 compile_collectives: bool = True
                 ) -> Tuple[List[Finding], List[str]]:
    """Build + lint the manifest. Returns (findings, program names run).
    `programs` filters by name; `compile_collectives=False` skips the
    compile-requiring inventory (trace/lower only — faster gate)."""
    wanted = set(programs) if programs else None
    if wanted is not None:
        unknown = wanted - set(MANIFEST_PROGRAMS)
        if unknown:
            raise ValueError(
                f"unknown manifest program(s) {sorted(unknown)}; "
                f"valid: {list(MANIFEST_PROGRAMS)}")
    findings: List[Finding] = []
    ran: List[str] = []
    for spec in default_manifest():
        if wanted is not None and spec.name not in wanted:
            continue
        fn, args, cleanup = spec.build()
        try:
            findings.extend(lint_program(
                spec.name, fn, args,
                compile_collectives=(spec.compile_collectives
                                     and compile_collectives)))
            ran.append(spec.name)
        finally:
            if cleanup is not None:
                cleanup()
    if wanted is None or "generate_prompt_drift" in wanted:
        findings.extend(_generate_prompt_drift_report())
        ran.append("generate_prompt_drift")
    if wanted is None or "train_scan_window_drift" in wanted:
        findings.extend(_train_scan_window_drift_report())
        ran.append("train_scan_window_drift")
    return findings, ran
