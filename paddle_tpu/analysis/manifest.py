"""tpulint default manifest: the real programs every perf PR rides on.

The program set IS the ProgramRegistry (paddle_tpu.compilation): every
site registered with the "manifest" tag is rebuilt exactly as its owner
builds it (the builders live in compilation/sites.py) and handed to the
program linter — trace + lower only (collective-tagged programs
additionally compile for their collective inventory). One table serves
every consumer: tpulint lints it, `compilation.warmup` prebuilds it,
`tools/warmup.py` persists it to the executable store, and
`tools/bench_cold_start.py` measures it — so a newly registered program
is lint-covered, warmable, and store-cacheable BY DEFAULT, and the
baseline keys (code::program::site) are the registry names.

Current registry population (see compilation/sites.py for each):
gpt_decode, llama_prefill, train_step, train_step_scan,
parallel_train_step (the pre-registry five, order preserved so baseline
keys stay stable), gpt_admit and llama_decode (newly covered by landing
in the registry).

Plus two static recompile-hazard reports that are not program sites:
the sequential generate() path's per-(prompt-len) program key — the
hazard the engine's prefill buckets exist to close (PR 2) — and the
fused train loop's pinned 2-program signature (scanned window +
trailing per-step, PR 4).

Everything is tiny-config and CPU-safe; no program is executed.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from ..compilation import registry as _registry
from .findings import Finding
from .program_lint import lint_program
from .recompile import recompile_report

__all__ = ["ProgramSpec", "default_manifest", "run_manifest",
           "MANIFEST_PROGRAMS", "manifest_names"]

# static analyses that are reports over abstract call specs, not
# registered program sites
STATIC_REPORTS = ("generate_prompt_drift", "train_scan_window_drift")


def manifest_names() -> Tuple[str, ...]:
    """The current program set: registry sites tagged "manifest" (in
    registration order — baseline keys depend on the names only) plus
    the static reports. Computed from the live registry so a program
    registered after import is still covered."""
    return tuple(_registry.names(tag="manifest")) + STATIC_REPORTS


# import-time snapshot for CLI help/validation messages; gate logic
# uses manifest_names() so late registrations are linted by default
MANIFEST_PROGRAMS = manifest_names()


@dataclass
class ProgramSpec:
    name: str
    build: Callable[[], Tuple[Any, tuple, Optional[Callable]]]
    compile_collectives: bool = False


def _adapt(prog: "_registry.RegisteredProgram"):
    """Registry builder (-> BuildResult) to the linter's
    (fn, args, cleanup) triple."""
    def build():
        r = prog.builder()
        return r.fn, r.args, r.cleanup
    return build


def default_manifest() -> List[ProgramSpec]:
    return [ProgramSpec(name, _adapt(_registry.get(name)),
                        _registry.get(name).compile_collectives)
            for name in _registry.names(tag="manifest")]


def _generate_prompt_drift_report() -> List[Finding]:
    """Static restatement of PR 2's recompile storm: sequential
    generate() keys one compiled program per exact prompt length, so
    drifting traffic re-traces per request. The engine's bucketed
    prefill is the fix; this report keeps the hazard visible (and the
    analyzer honest) in the baseline."""
    specs = [(np.zeros((1, p), np.int64),) for p in (7, 9, 13)]
    return recompile_report("generate_prompt_drift", specs)


def _train_scan_window_drift_report() -> List[Finding]:
    """The fused train loop's PINNED recompile signature: one drifting-
    length epoch dispatches exactly TWO abstract call shapes — the
    scanned [K, B, S] super-batch window and the trailing per-step
    [B, S] batch (Model._run_epoch_fused's fallback). The baseline pins
    this at 2 programs; a third signature appearing here means the
    fused driver started re-tracing per window length (the hazard
    tests/test_scan_train.py's trace counter also guards at runtime)."""
    specs = [(np.zeros((4, 2, 32), np.int64),
              np.zeros((4, 2, 32), np.int64)),
             (np.zeros((2, 32), np.int64), np.zeros((2, 32), np.int64))]
    return recompile_report("train_scan_window_drift", specs)


def run_manifest(programs: Optional[List[str]] = None,
                 compile_collectives: bool = True
                 ) -> Tuple[List[Finding], List[str]]:
    """Build + lint the manifest. Returns (findings, program names run).
    `programs` filters by name; `compile_collectives=False` skips the
    compile-requiring inventory (trace/lower only — faster gate)."""
    valid = manifest_names()
    wanted = set(programs) if programs else None
    if wanted is not None:
        unknown = wanted - set(valid)
        if unknown:
            raise ValueError(
                f"unknown manifest program(s) {sorted(unknown)}; "
                f"valid: {list(valid)}")
    findings: List[Finding] = []
    ran: List[str] = []
    for spec in default_manifest():
        if wanted is not None and spec.name not in wanted:
            continue
        fn, args, cleanup = spec.build()
        try:
            findings.extend(lint_program(
                spec.name, fn, args,
                compile_collectives=(spec.compile_collectives
                                     and compile_collectives)))
            ran.append(spec.name)
        finally:
            if cleanup is not None:
                cleanup()
    if wanted is None or "generate_prompt_drift" in wanted:
        findings.extend(_generate_prompt_drift_report())
        ran.append("generate_prompt_drift")
    if wanted is None or "train_scan_window_drift" in wanted:
        findings.extend(_train_scan_window_drift_report())
        ran.append("train_scan_window_drift")
    return findings, ran
