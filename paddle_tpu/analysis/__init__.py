"""tpulint — static analysis over the programs this framework compiles.

The reference stack ships analysis/verification layers over its graph
IR (the pass framework under paddle/fluid/framework/ir/,
FLAGS_check_nan_inf, memory-reuse checkers). Our IR is the jaxpr and
lowered StableHLO of every jitted program; this package is the
systematic way to inspect it BEFORE it reaches hardware:

- program_lint:  walk a program's ClosedJaxpr + StableHLO — dtype
  promotions, scatter/gather, host callbacks, un-donated buffers,
  baked RNG keys, collective inventory.
- recompile:     statically diff abstract call signatures — which arg
  dims will force re-tracing (PR 2's recompile storms, decided without
  compiling anything).
- codebase_lint: AST pass over the tree — retrace-per-call jit idioms,
  traced attribute mutation in Layer.forward (the aux_loss.py class of
  bug), numpy on traced values, stale quarantine entries.
- concurrency:   the tpurace pass — per-class guarded-attribute
  inference over the same AST walk: guarded attrs touched outside
  their lock, blocking calls under a lock, a cross-class static
  lock-order graph with cycle detection, unlocked check-then-act,
  orphan non-daemon threads; `tools/tpurace.py` gates CI on the diff
  against tools/tpurace_baseline.json (runtime half: obs/locks.py +
  tools/race_hunt.py).
- manifest:      the real serving/training programs (engine decode,
  generate prefill, TrainStep, ParallelTrainStep on a fake 4-device
  mesh) rebuilt and linted; `tools/tpulint.py` gates CI on the diff
  against tools/tpulint_baseline.json.
- hlo_cost + fusion: the tpucost pass — compiled HLO parsed into a
  per-program FLOP/HBM/roofline inventory with fusion histogram and
  the ranked unfused-chain report; `tools/tpucost.py` gates CI on
  ratcheted budgets + anchors in tools/tpucost_baseline.json.
- runtime_profile: the tpuprof pass — measured per-kernel device time
  (programmatic jax.profiler, stdlib chrome-trace parser) JOINED with
  hlo_cost's modeled inventory: time-weighted fusion histogram,
  measured-vs-roofline ratios, time-ranked unfused chains;
  `tools/tpuprof.py` gates CI on a noise-tolerant dispatch-time
  ratchet + measured anchors in tools/tpuprof_baseline.json.
- report:        the shared --json artifact + terminal-record contract
  the CLIs emit (tools/_have_result.py predicate).

CLIs: python tools/tpulint.py [--update-baseline] [--json out.json]
      python tools/tpucost.py [--update-baseline] [--json out.json]
      python tools/tpuprof.py [--update-baseline] [--json out.json]
      python tools/tpurace.py [--update-baseline] [--json out.json]
"""
from .findings import (Finding, Severity, count_findings,
                       diff_against_baseline, findings_to_json,
                       load_baseline)
from .program_lint import collective_inventory_from_hlo, lint_program
from .recompile import abstract_signature, recompile_report
from .codebase_lint import (HOT_JIT_FILES, lint_file, lint_quarantine,
                            lint_tree)
from .concurrency import (collect_classes, lint_concurrency_file,
                          lint_concurrency_paths, lint_concurrency_tree)
from .manifest import (MANIFEST_PROGRAMS, ProgramSpec, default_manifest,
                       manifest_names, run_manifest)
from .hlo_cost import (CHIP_SPECS, DEFAULT_CHIP, ChipSpec,
                       analytic_decode_hbm_bytes,
                       analytic_verify_hbm_bytes, check_cost_baseline,
                       collect_kernels, load_cost_baseline,
                       parse_hlo_module, program_cost,
                       updated_cost_baseline)
from .fusion import fusion_histogram, unfused_chains
from .collective_schedule import (diff_schedules, gather_chain_links,
                                  gather_overlap_report,
                                  schedule_events)
from .runtime_profile import (check_profile_baseline, device_op_times,
                              join_measured_modeled,
                              load_profile_baseline, load_trace_events,
                              profile_program, runtime_report,
                              updated_profile_baseline)
from .report import terminal_record, write_report_artifact

__all__ = [
    "Finding", "Severity", "count_findings", "diff_against_baseline",
    "findings_to_json", "load_baseline",
    "lint_program", "collective_inventory_from_hlo",
    "abstract_signature", "recompile_report",
    "lint_tree", "lint_file", "lint_quarantine", "HOT_JIT_FILES",
    "lint_concurrency_tree", "lint_concurrency_file",
    "lint_concurrency_paths", "collect_classes",
    "ProgramSpec", "default_manifest", "run_manifest",
    "MANIFEST_PROGRAMS", "manifest_names",
    "ChipSpec", "CHIP_SPECS", "DEFAULT_CHIP", "parse_hlo_module",
    "program_cost", "collect_kernels", "analytic_decode_hbm_bytes",
    "analytic_verify_hbm_bytes",
    "check_cost_baseline", "load_cost_baseline",
    "updated_cost_baseline", "fusion_histogram", "unfused_chains",
    "schedule_events", "gather_overlap_report", "gather_chain_links",
    "diff_schedules",
    "load_trace_events", "device_op_times", "join_measured_modeled",
    "runtime_report", "profile_program", "check_profile_baseline",
    "load_profile_baseline", "updated_profile_baseline",
    "write_report_artifact", "terminal_record",
]
