"""Recompile-hazard analyzer: statically diff abstract call signatures.

`jax.jit` keys its executable cache on the abstract signature of every
argument — (pytree structure, leaf shapes, dtypes, weak-types) — plus
static-arg values. Any drift re-traces and re-compiles (~1.5 s even at
GPT-tiny scale on this host; minutes at real scale). PR 2's engine
closes the serving side with trace counters asserting ZERO recompiles;
this module closes the loop statically: given the argument specs a
caller intends to pass over time, report exactly which leaves (and
which dims) will force re-tracing, BEFORE anything is compiled.

Usage:

    findings = recompile_report(
        "generate.prefill",
        call_specs=[(params, buffers, ids_7, caches, key),
                    (params, buffers, ids_9, caches, key)])
    # -> [recompile-dim finding: arg2 dim 1 varies {7, 9} -> 2 programs]

Specs may be real arrays, jax.ShapeDtypeStruct avals, or pytrees
thereof — only shapes/dtypes are read, nothing is traced.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax

from ._util import leaf_labels
from .findings import (RECOMPILE_DIM, RECOMPILE_STRUCTURE, Finding,
                       Severity)

__all__ = ["abstract_signature", "recompile_report"]


def _leaf_sig(leaf) -> Tuple:
    shape = tuple(getattr(leaf, "shape", ()) or ())
    dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
    weak = bool(getattr(leaf, "weak_type", False))
    # python scalars are weak-typed literals — every distinct VALUE of a
    # bool/int static-like arg is fine (same aval), but float/int python
    # scalars passed positionally become weak arrays of one signature
    return (shape, dtype, weak)


def abstract_signature(args: Tuple, static_argnums: Sequence[int] = ()):
    """(treedef_repr, leaf signatures) of one call's dynamic args."""
    dyn = tuple(a for i, a in enumerate(args)
                if i not in set(static_argnums))
    leaves, treedef = jax.tree_util.tree_flatten(dyn)
    return repr(treedef), tuple(_leaf_sig(l) for l in leaves)


def recompile_report(name: str, call_specs: Sequence[Tuple],
                     static_argnums: Sequence[int] = ()) -> List[Finding]:
    """Diff the abstract signatures of `call_specs` (each one the arg
    tuple of an intended call) and report every leaf whose signature is
    unstable — each distinct overall signature is one compilation."""
    if len(call_specs) < 2:
        return []
    sigs = [abstract_signature(args, static_argnums)
            for args in call_specs]
    findings: List[Finding] = []

    treedefs = {s[0] for s in sigs}
    if len(treedefs) > 1:
        findings.append(Finding(
            RECOMPILE_STRUCTURE, Severity.WARN, name, "pytree",
            f"{len(treedefs)} distinct argument pytree structures "
            f"across {len(call_specs)} calls — every structure is a "
            "separate trace", {"structures": len(treedefs)}))
        return findings  # leaf alignment is meaningless across structures

    labels = leaf_labels(call_specs[0], static_argnums=static_argnums)
    n_progs = len({s[1] for s in sigs})
    leaf_cols = list(zip(*[s[1] for s in sigs])) if sigs[0][1] else []
    for idx, col in enumerate(leaf_cols):
        distinct = sorted(set(col), key=repr)
        if len(distinct) == 1:
            continue
        label = labels[idx] if idx < len(labels) else f"leaf{idx}"
        shapes = [d[0] for d in distinct]
        ranks = {len(s) for s in shapes}
        varying_dims: List[int] = []
        if len(ranks) == 1:
            r = ranks.pop()
            varying_dims = [d for d in range(r)
                            if len({s[d] for s in shapes}) > 1]
        dtypes = sorted({d[1] for d in distinct})
        detail = []
        if varying_dims:
            detail.append(
                "dim(s) %s vary: %s" % (
                    varying_dims,
                    sorted({tuple(s[d] for d in varying_dims)
                            for s in shapes})))
        elif len(ranks) > 1:
            detail.append(f"rank varies: {sorted(ranks)}")
        if len(dtypes) > 1:
            detail.append(f"dtype varies: {dtypes}")
        if len({d[2] for d in distinct}) > 1:
            detail.append("weak_type varies (mix of python literals "
                          "and arrays)")
        findings.append(Finding(
            RECOMPILE_DIM, Severity.WARN, name, label,
            f"{label} has {len(distinct)} abstract signatures across "
            f"{len(call_specs)} calls ({'; '.join(detail)}) — pad or "
            f"bucket it, or mark it static; this call pattern compiles "
            f"{n_progs} distinct programs",
            {"signatures": [repr(d) for d in distinct],
             "varying_dims": varying_dims,
             "distinct_programs": n_progs}))
    return findings
