"""Codebase lint: AST pass over the tree for trace-hostile idioms.

The program linter sees one compiled program at a time; this pass sees
the SOURCE patterns that produce bad programs — the hazards PR 1 and
PR 2 each burned wall-clock discovering at runtime:

- jit-in-call: ``jax.jit(f, ...)(args)`` — a fresh function object per
  call means a jit cache miss per call: full re-trace + re-compile
  every time (the sequential-generate() recompile storm, PR 2).
- jit-no-donation: a ``jax.jit`` on a known-hot wrapper file with
  neither donate_argnums nor static_argnames/nums — informational; the
  baseline pins accepted sites.
- traced-attr-mutation: ``self.x = <expr>`` inside a Layer ``forward``
  — under whole-step tracing the attribute captures a tracer and leaks
  across steps (the aux_loss.py class of bug; layers must report into
  scopes instead).
- numpy-in-trace: ``np.*(...)`` inside ``forward`` — numpy calls force
  concretization of traced values (TracerArrayConversionError at best,
  silent host constant at worst).
- stale-quarantine: an entry in tools/flaky_quarantine.txt (nodeid or
  -k substring) that no longer matches any test — known failures must
  stay tracked, not rot silently.

Suppression: append ``# tpulint: disable=<code>`` (or a bare
``# tpulint: disable``) on the flagged line.

Sites are (path, qualified symbol) — never line numbers, so baselines
survive unrelated edits.
"""
from __future__ import annotations

import ast
import os
import re
from typing import List, Optional, Set

from .findings import (JIT_IN_CALL, JIT_NO_DONATION, NUMPY_IN_TRACE,
                       STALE_QUARANTINE, TRACED_ATTR_MUTATION, Finding,
                       Severity)

__all__ = ["lint_tree", "lint_file", "lint_quarantine", "HOT_JIT_FILES"]

# wrappers on the jit hot path: a jax.jit here without donation/static
# knobs deserves a look (informational — baseline pins accepted sites)
HOT_JIT_FILES = {
    "paddle_tpu/jit/training.py",
    "paddle_tpu/distributed/parallel_step.py",
    "paddle_tpu/inference/engine.py",
    "paddle_tpu/models/generation.py",
}

_DISABLE_RE = re.compile(r"#\s*tpulint:\s*disable(?:=([\w,-]+))?")

_JIT_KNOBS = {"donate_argnums", "donate_argnames", "static_argnums",
              "static_argnames", "in_shardings", "out_shardings"}


def _disabled_codes(line: str) -> Optional[Set[str]]:
    m = _DISABLE_RE.search(line)
    if not m:
        return None
    if not m.group(1):
        return set()          # bare disable: every code
    return {c.strip() for c in m.group(1).split(",")}


def _is_jax_jit(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == "jit"
            and isinstance(node.value, ast.Name)
            and node.value.id == "jax")


class _Visitor(ast.NodeVisitor):
    def __init__(self, relpath: str, lines: List[str]):
        self.relpath = relpath
        self.lines = lines
        self.findings: List[Finding] = []
        self._scope: List[str] = []       # qualname stack
        self._class_stack: List[ast.ClassDef] = []
        self._in_forward = 0
        self._fn_depth = 0

    # -- helpers -----------------------------------------------------------
    def _qual(self) -> str:
        return ".".join(self._scope) or "<module>"

    def _suppressed(self, node: ast.AST, code: str) -> bool:
        ln = getattr(node, "lineno", 0)
        if 1 <= ln <= len(self.lines):
            dis = _disabled_codes(self.lines[ln - 1])
            if dis is not None and (not dis or code in dis):
                return True
        return False

    def _emit(self, node, code, severity, site, message, data=None):
        if self._suppressed(node, code):
            return
        self.findings.append(Finding(
            code, severity, self.relpath, site, message,
            dict(data or {}, line=getattr(node, "lineno", 0))))

    # -- scope tracking ----------------------------------------------------
    @staticmethod
    def _layer_like(node: ast.ClassDef) -> bool:
        """Only Layer subclasses run under whole-step tracing — host-side
        helpers (Initializer, BaseTransform, ...) mutate state eagerly
        by design and must not be flagged."""
        names = [node.name]
        for b in node.bases:
            if isinstance(b, ast.Attribute):      # nn.Layer
                names.append(b.attr)
            elif isinstance(b, ast.Name):         # Layer
                names.append(b.id)
        return any("Layer" in n for n in names)

    def visit_ClassDef(self, node: ast.ClassDef):
        self._scope.append(node.name)
        self._class_stack.append(node)
        self.generic_visit(node)
        self._class_stack.pop()
        self._scope.pop()

    def _visit_fn(self, node):
        is_forward = (bool(self._class_stack) and self._fn_depth == 0
                      and node.name in ("forward", "__call__")
                      and self._layer_like(self._class_stack[-1]))
        self._scope.append(node.name)
        self._fn_depth += 1
        if is_forward:
            self._in_forward += 1
        self.generic_visit(node)
        if is_forward:
            self._in_forward -= 1
        self._fn_depth -= 1
        self._scope.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    # -- checks ------------------------------------------------------------
    def visit_Call(self, node: ast.Call):
        # jax.jit(...)(...) — immediate invocation: retrace per call
        if isinstance(node.func, ast.Call) and _is_jax_jit(node.func.func):
            self._emit(
                node, JIT_IN_CALL, Severity.WARN,
                f"{self._qual()}",
                "jax.jit(...)(...) builds a fresh jitted function per "
                "call — jit's cache keys on function identity, so every "
                "call re-traces AND re-compiles; hoist/cache the jitted "
                "program")
        if _is_jax_jit(node.func):
            rel = self.relpath.replace(os.sep, "/")
            if rel in HOT_JIT_FILES and not (
                    {kw.arg for kw in node.keywords} & _JIT_KNOBS):
                self._emit(
                    node, JIT_NO_DONATION, Severity.INFO,
                    f"{self._qual()}",
                    "jax.jit on a hot wrapper without donation/static "
                    "knobs — confirm nothing here is donatable or "
                    "shape-polymorphic")
        # numpy on traced values inside forward
        if (self._in_forward and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in ("np", "numpy")):
            self._emit(
                node, NUMPY_IN_TRACE, Severity.WARN,
                f"{self._qual()}.np.{node.func.attr}",
                f"numpy call np.{node.func.attr}(...) inside forward() "
                "— concretizes traced values (TracerArrayConversion "
                "error under jit, silent trace-time constant otherwise)")
        self.generic_visit(node)

    def _check_self_assign(self, node, target):
        if (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"):
            value = getattr(node, "value", None)
            if isinstance(value, ast.Constant):
                return     # plain flag flips are trace-safe
            cls = self._class_stack[-1].name if self._class_stack else "?"
            self._emit(
                node, TRACED_ATTR_MUTATION, Severity.WARN,
                f"{cls}.forward.{target.attr}",
                f"self.{target.attr} assigned inside forward() — under "
                "whole-step jit this captures a tracer on the layer and "
                "leaks it across steps (the aux_loss.py class of bug); "
                "report through a scope or return it instead")

    def visit_Assign(self, node: ast.Assign):
        if self._in_forward:
            for t in node.targets:
                self._check_self_assign(node, t)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        if self._in_forward:
            self._check_self_assign(node, node.target)
        self.generic_visit(node)


def lint_file(path: str, root: str) -> List[Finding]:
    relpath = os.path.relpath(path, root).replace(os.sep, "/")
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding("lint-error", Severity.ERROR, relpath,
                        "parse", f"syntax error: {e}", {})]
    v = _Visitor(relpath, src.splitlines())
    v.visit(tree)
    return v.findings


def lint_tree(root: str, package: str = "paddle_tpu") -> List[Finding]:
    """Lint every .py under <root>/<package>."""
    findings: List[Finding] = []
    pkg_root = os.path.join(root, package)
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                findings.extend(lint_file(os.path.join(dirpath, fname),
                                          root))
    return findings


# -- quarantine / known-failure registry check -----------------------------

_TEST_DEF_RE = re.compile(r"^\s*(?:def|class)\s+((?:test|Test)\w+)",
                          re.MULTILINE)


def _collect_test_names(tests_dir: str):
    names = {}     # test function OR Test class name -> file
    for fname in sorted(os.listdir(tests_dir)):
        if not (fname.startswith("test_") and fname.endswith(".py")):
            continue
        with open(os.path.join(tests_dir, fname),
                  encoding="utf-8") as fh:
            for m in _TEST_DEF_RE.finditer(fh.read()):
                names[m.group(1)] = fname
    return names


def lint_quarantine(root: str,
                    quarantine_path: Optional[str] = None,
                    tests_dir: Optional[str] = None) -> List[Finding]:
    """Machine-check tools/flaky_quarantine.txt: every entry (pytest
    nodeid or -k substring) must still resolve to a live test, so a
    renamed/deleted known-failure can't silently drop off the books."""
    qpath = quarantine_path or os.path.join(root, "tools",
                                            "flaky_quarantine.txt")
    tdir = tests_dir or os.path.join(root, "tests")
    if not os.path.exists(qpath):
        return []
    findings: List[Finding] = []
    test_names = _collect_test_names(tdir) if os.path.isdir(tdir) else {}
    relq = os.path.relpath(qpath, root).replace(os.sep, "/")
    for raw in open(qpath, encoding="utf-8"):
        entry = raw.split("#", 1)[0].strip()
        if not entry:
            continue
        ok = False
        if "::" in entry or entry.endswith(".py"):
            # nodeid: path::test_fn, or class-based path::TestCls::test_fn
            path_part, _, name_part = entry.partition("::")
            fpath = os.path.join(root, path_part)
            if os.path.exists(fpath):
                if not name_part:
                    ok = True
                else:
                    # the terminal component (param brackets stripped)
                    # must exist as a def/class in the file
                    name = name_part.split("::")[-1].split("[", 1)[0]
                    with open(fpath, encoding="utf-8") as fh:
                        ok = re.search(
                            r"\b(?:def|class)\s+%s\b" % re.escape(name),
                            fh.read()) is not None
        else:
            # -k substring: pytest keyword-matches module names too, so
            # "flash_kernel" (whole-module deselect) must resolve
            ok = (any(entry in n for n in test_names)
                  or any(entry in f for f in test_names.values()))
        if not ok:
            findings.append(Finding(
                STALE_QUARANTINE, Severity.WARN, relq, entry,
                f"quarantine entry {entry!r} matches no existing test — "
                "the known failure it tracked was renamed or removed; "
                "update or delete the entry", {}))
    return findings
