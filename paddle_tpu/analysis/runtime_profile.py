"""tpuprof — measured runtime kernel attribution over registry programs.

tpucost (hlo_cost.py) MODELS each registered program — FLOPs, HBM bytes
and a roofline time per kernel — but models drift from machines. This
module is the measurement half the MFU campaign's fusion loop needs
("Operator Fusion in XLA", PAPERS.md 2301.13062, prescribes an
op-TIME-weighted fusion report; MPK-style mega-kernelization, PAPERS.md
2512.22219, needs that report as its target list): run a program under
the programmatic ``jax.profiler``, parse the chrome trace it emits
(stdlib gzip+json — no TensorBoard; the parser generalizes the one that
used to live inside tools/profile_step.py), and JOIN the measured
per-kernel device time against ``hlo_cost.collect_kernels``' modeled
inventory by kernel name. Per program that yields:

- a time-weighted fusion-class histogram (where the *seconds* go, not
  the kernel counts);
- a measured-vs-modeled roofline ratio per kernel and for the whole
  dispatch (how far the program sits above what the chip could do);
- the top unfused chains of PR 6 re-ranked by MEASURED time — the
  bytes-ranked candidate list turned into a seconds-ranked work list.

Degrade contract (the profile_step smoke contract): a CPU backend's
trace has no device plane — only ``/host:CPU`` dispatch events — so the
report keeps the measured wall-time-per-dispatch (median-of-N) and
marks the join unavailable; anchors that need kernel attribution are
SKIPPED with a recorded reason instead of silently passing.

Gate (tools/tpuprof_baseline.json, via tools/tpuprof.py):

- ``budgets``: per-program measured dispatch-time medians. This host
  jitters at seconds scale, so the ratchet is noise-tolerant: a run
  fails only past ``budget * tolerance`` (tolerance lives in the
  baseline); ``--update-baseline`` re-pins the medians (and locks wins
  in) while anchors/notes/tolerance survive.
- ``anchors``: hand-set measured invariants — ``matmul_time_share_floor``
  (train step device time must stay matmul-dominated) and
  ``measured_vs_roofline`` (the decode tick must not drift further from
  its modeled roofline) — evaluated whenever a device plane exists,
  loud-skipped when not.

Pure parsing/join/gate code here has no jax dependency (fixture-driven
tests run with ZERO compiles); the run-under-profiler helpers import
jax lazily.
"""
from __future__ import annotations

import glob
import gzip
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .findings import (PROF_ANCHOR, PROF_BUDGET, STALE_PROF_PROGRAM,
                       Finding, Severity)
from .hlo_cost import CHIP_SPECS, DEFAULT_CHIP, ChipSpec, KernelCost

__all__ = [
    "DeviceProfile", "load_trace_events", "device_op_times",
    "category_of", "normalize_kernel_name",
    "join_measured_modeled", "time_weighted_histogram",
    "time_weighted_chains", "runtime_report",
    "host_example_args", "measure_dispatch", "trace_dispatches",
    "profile_program",
    "load_profile_baseline", "updated_profile_baseline",
    "check_profile_baseline", "DEFAULT_TOLERANCE",
]

# dispatch-time ratchet band: measured_median > budget * tolerance
# fails. 2.5x on a shared 1-core host whose seconds-scale jitter is
# documented in every bench (PERF.md); a real regression (an extra
# compile-per-call, a dropped fusion doubling a tick) clears it easily.
DEFAULT_TOLERANCE = 2.5


# ---------------------------------------------------------------------------
# chrome-trace parsing (device + host lanes)
# ---------------------------------------------------------------------------

@dataclass
class DeviceProfile:
    """Aggregated device-lane view of one chrome trace.

    ``per_op`` maps kernel (HLO instruction) name -> total device us
    across the traced window; ``op_category`` keeps the profiler's own
    ``hlo_category`` label where present. ``had_device`` False means
    the trace came from a backend with no device plane (CPU) and the
    caller must degrade to wall-time-only reporting."""
    per_op: Dict[str, float] = field(default_factory=dict)
    op_category: Dict[str, str] = field(default_factory=dict)
    had_device: bool = False
    host_dispatch_events: int = 0

    @property
    def total_us(self) -> float:
        return sum(self.per_op.values())


def load_trace_events(logdir: str) -> List[dict]:
    """Every traceEvent from the ``*.trace.json[.gz]`` files a
    ``jax.profiler`` session wrote under ``logdir`` (stdlib gzip+json —
    no TensorBoard/XProf dependency)."""
    events: List[dict] = []
    for pattern in ("*.trace.json.gz", "*.trace.json"):
        for path in sorted(glob.glob(
                os.path.join(logdir, "**", pattern), recursive=True)):
            opener = gzip.open if path.endswith(".gz") else open
            with opener(path) as fh:
                doc = json.load(fh)
            events.extend(doc.get("traceEvents", []) or [])
    return events


# host events that mark one executable dispatch (per backend family):
# the CPU client's execute, the PJRT stream executor's launch, and the
# generic RunExecutable — counted so a host-only trace still reports
# how many dispatches the profiled window actually saw
_HOST_DISPATCH_MARKERS = ("ExecuteSharded", "TfrtCpuExecutable::Execute",
                          "PjRtStreamExecutorLoadedExecutable::Execute",
                          "RunExecutable")


def device_op_times(events: Sequence[dict]) -> DeviceProfile:
    """Aggregate per-op durations from the DEVICE lanes of a chrome
    trace. Only the "XLA Ops" lane holds per-op events; the "Steps" /
    "XLA Modules" lanes carry whole-step spans that would double every
    total if summed alongside. Host-only traces (CPU backend) return
    ``had_device=False`` with the dispatch-event count instead."""
    prof = DeviceProfile()
    device_pids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name" and \
                "/device:" in str(e.get("args", {}).get("name", "")):
            device_pids.add(e.get("pid"))
    op_tids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name" and \
                e.get("pid") in device_pids and \
                "XLA Ops" in str(e.get("args", {}).get("name", "")):
            op_tids.add((e.get("pid"), e.get("tid")))
    for e in events:
        if e.get("ph") != "X":
            continue
        name = str(e.get("name", "?"))
        if e.get("pid") in device_pids:
            prof.had_device = True
            if op_tids and (e.get("pid"), e.get("tid")) not in op_tids:
                continue
            prof.per_op[name] = prof.per_op.get(name, 0.0) + \
                float(e.get("dur", 0.0))
            args = e.get("args") or {}
            cat = args.get("hlo_category") or args.get("category")
            if cat:
                prof.op_category[name] = str(cat)
        elif any(m in name for m in _HOST_DISPATCH_MARKERS):
            prof.host_dispatch_events += 1
    return prof


def category_of(name: str, op_cat: Optional[Dict[str, str]] = None) -> str:
    """Display category for one kernel name: the profiler's own
    ``hlo_category`` when recorded, else a name-pattern fallback (the
    table tools/profile_step.py has always printed)."""
    if op_cat and op_cat.get(name):
        return op_cat[name]
    n = name.lower()
    for pat, cat in (("dot", "matmul"), ("conv", "conv"),
                     ("all-reduce", "collective"),
                     ("all-gather", "collective"),
                     ("reduce-scatter", "collective"),
                     ("collective-permute", "collective"),
                     ("custom-call", "custom-call (pallas/lib)"),
                     ("fusion", "fusion"), ("copy", "copy"),
                     ("scatter", "scatter/gather"),
                     ("gather", "scatter/gather"),
                     ("reduce", "reduce"), ("sort", "sort")):
        if pat in n:
            return cat
    return "other"


def normalize_kernel_name(name: str) -> str:
    """Join key between trace event names and HLO instruction names:
    the profiler drops the ``%`` sigil and may append a ``.N`` dedup
    suffix the HLO text lacks (or vice versa) — strip the sigil and
    whitespace, keep the rest verbatim (suffixes are real identity:
    ``fusion.3`` and ``fusion.30`` are different kernels)."""
    return name.strip().lstrip("%")


# ---------------------------------------------------------------------------
# measured <-> modeled join
# ---------------------------------------------------------------------------

def _aggregate_modeled(kernels: Sequence[KernelCost],
                       chip: ChipSpec) -> Dict[str, dict]:
    """Modeled kernels keyed by normalized name. collect_kernels
    multiplies loop bodies by their trip counts already; two kernels
    sharing a name (XLA-deduplicated computations) merge — the join is
    by-name because that is all the trace carries."""
    out: Dict[str, dict] = {}
    for k in kernels:
        key = normalize_kernel_name(k.name)
        m = out.setdefault(key, {
            "name": key, "class": k.klass, "op": k.opcode,
            "flops": 0.0, "matmul_flops": 0.0, "hbm_bytes": 0,
            "roofline_us": 0.0, "trip": 0})
        m["flops"] += k.flops
        m["matmul_flops"] += k.matmul_flops
        m["hbm_bytes"] += k.hbm_bytes
        m["roofline_us"] += k.roofline_seconds(chip) * 1e6
        m["trip"] += k.trip
    return out


def join_measured_modeled(per_op_us: Dict[str, float],
                          kernels: Sequence[KernelCost],
                          chip: "str | ChipSpec" = DEFAULT_CHIP,
                          dispatches: int = 1) -> dict:
    """JOIN measured device time (``per_op_us``, totals over
    ``dispatches`` executions) with the modeled kernel inventory.

    Returns a dict with per-kernel rows (measured us per dispatch,
    modeled roofline us, measured/roofline ratio, class, bytes/flops),
    the TIME-WEIGHTED join rate (what fraction of measured device time
    found a modeled kernel — the honesty number the report leads with),
    and the measured-but-unmodeled / modeled-but-unmeasured leftovers."""
    if isinstance(chip, str):
        chip = CHIP_SPECS[chip]
    dispatches = max(1, int(dispatches))
    modeled = _aggregate_modeled(kernels, chip)
    rows: List[dict] = []
    joined_us = 0.0
    unjoined: List[Tuple[str, float]] = []
    for name, us in per_op_us.items():
        key = normalize_kernel_name(name)
        us_per = us / dispatches
        m = modeled.get(key)
        if m is None:
            unjoined.append((key, us_per))
            continue
        joined_us += us
        ratio = (us_per / m["roofline_us"]) if m["roofline_us"] else None
        rows.append({
            "name": key, "class": m["class"], "op": m["op"],
            "measured_us": round(us_per, 3),
            "roofline_us": round(m["roofline_us"], 3),
            "measured_vs_roofline":
                round(ratio, 3) if ratio is not None else None,
            "flops": m["flops"], "matmul_flops": m["matmul_flops"],
            "hbm_bytes": m["hbm_bytes"],
        })
    rows.sort(key=lambda r: r["measured_us"], reverse=True)
    unjoined.sort(key=lambda x: x[1], reverse=True)
    total_us = sum(per_op_us.values())
    measured_names = {normalize_kernel_name(n) for n in per_op_us}
    unmeasured = sorted(set(modeled) - measured_names)
    return {
        "available": True,
        "rows": rows,
        "join_rate_time_weighted":
            round(joined_us / total_us, 4) if total_us else 0.0,
        "measured_total_us": round(total_us / dispatches, 3),
        "unjoined_us": round((total_us - joined_us) / dispatches, 3),
        "unjoined_top": [{"name": n, "measured_us": round(u, 3)}
                         for n, u in unjoined[:10]],
        "modeled_unmeasured_kernels": len(unmeasured),
    }


def time_weighted_histogram(join: dict) -> Dict[str, float]:
    """Measured device us per dispatch summed by modeled kernel CLASS —
    the op-time-weighted fusion histogram (vs tpucost's count-weighted
    one). Unjoined time lands in ``unattributed`` so the histogram
    always sums to the measured total."""
    hist: Dict[str, float] = {}
    for r in join.get("rows", ()):
        hist[r["class"]] = round(
            hist.get(r["class"], 0.0) + r["measured_us"], 3)
    if join.get("unjoined_us"):
        hist["unattributed"] = join["unjoined_us"]
    return hist


def matmul_time_share(join: dict) -> Optional[float]:
    """Fraction of measured device time spent in kernels whose MODELED
    FLOPs are matmul (standalone dots + fusions containing them). None
    when the join found nothing — the anchor must skip, not pass."""
    total = join.get("measured_total_us") or 0.0
    if not join.get("available") or not total:
        return None
    mm = sum(r["measured_us"] for r in join["rows"]
             if r["matmul_flops"] > 0)
    return round(mm / total, 4)


def time_weighted_chains(join: dict, chains: Sequence[dict],
                         limit: int = 5) -> List[dict]:
    """Re-rank PR 6's bytes-ranked unfused chains by MEASURED time: a
    chain's measured_us is the summed device time of its member
    kernels. Chains none of whose kernels appeared on the device lane
    are dropped (they cost nothing where the seconds are)."""
    by_name = {r["name"]: r["measured_us"] for r in join.get("rows", ())}
    out = []
    for c in chains:
        us = sum(by_name.get(normalize_kernel_name(n), 0.0)
                 for n in c.get("kernels", ()))
        if us <= 0:
            continue
        cc = dict(c)
        cc["measured_us"] = round(us, 3)
        out.append(cc)
    out.sort(key=lambda c: c["measured_us"], reverse=True)
    return out[:limit]


# ---------------------------------------------------------------------------
# per-program report
# ---------------------------------------------------------------------------

def _dispatch_stats(dispatch_s: Sequence[float]) -> dict:
    times = sorted(float(t) for t in dispatch_s)
    if not times:
        return {"n": 0}
    n = len(times)
    med = times[n // 2] if n % 2 else (times[n // 2 - 1]
                                       + times[n // 2]) / 2.0
    return {"n": n,
            "median_ms": round(med * 1e3, 3),
            "mean_ms": round(sum(times) / n * 1e3, 3),
            "min_ms": round(times[0] * 1e3, 3),
            "max_ms": round(times[-1] * 1e3, 3)}


def runtime_report(name: str, *, hlo_text: Optional[str] = None,
                   kernels: Optional[Sequence[KernelCost]] = None,
                   events: Optional[Sequence[dict]] = None,
                   profile: Optional[DeviceProfile] = None,
                   dispatch_s: Sequence[float] = (),
                   dispatches_profiled: int = 1,
                   chip: "str | ChipSpec" = DEFAULT_CHIP,
                   geometry: Optional[dict] = None,
                   top: int = 15) -> dict:
    """Compose ONE program's measured-runtime record: wall dispatch
    stats + (when a device plane exists) the measured<->modeled join,
    time-weighted fusion histogram, per-kernel roofline ratios, and
    the time-ranked unfused chains. Pass either ``hlo_text`` (parsed
    here) or a pre-collected ``kernels`` list, and either raw trace
    ``events`` or a pre-parsed ``profile``."""
    from .fusion import unfused_chains
    from .hlo_cost import collect_kernels, parse_hlo_module
    if isinstance(chip, str):
        chip = CHIP_SPECS[chip]
    if kernels is None:
        kernels = collect_kernels(parse_hlo_module(hlo_text or ""))
    if profile is None:
        profile = device_op_times(events or [])

    modeled_roofline_us = sum(k.roofline_seconds(chip)
                              for k in kernels) * 1e6
    rec = {
        "program": name,
        "chip": chip.name,
        "dispatch": _dispatch_stats(dispatch_s),
        "had_device_plane": profile.had_device,
        "host_dispatch_events": profile.host_dispatch_events,
        "modeled": {
            "kernel_count": sum(1 for k in kernels
                                if k.klass != "scalar"),
            "flops": sum(k.flops for k in kernels),
            "hbm_bytes": sum(k.hbm_bytes for k in kernels),
            "matmul_flop_share": round(
                sum(k.matmul_flops for k in kernels)
                / max(sum(k.flops for k in kernels), 1e-30), 6),
            "roofline_us": round(modeled_roofline_us, 3),
            # the program's kernels by modeled roofline weight — named
            # even on the degraded (no-device-plane) path, so a report
            # always says WHAT it measured, not just how long
            "top_kernels": [
                normalize_kernel_name(k.name) for k in sorted(
                    kernels, key=lambda k: -k.roofline_seconds(chip)
                )[:10]],
        },
        "geometry": dict(geometry or {}),
    }
    if profile.had_device:
        join = join_measured_modeled(profile.per_op, kernels, chip,
                                     dispatches_profiled)
        rec["join"] = dict(join)
        rec["join"]["rows"] = join["rows"][:top]
        rec["time_weighted_fusion_histogram"] = \
            time_weighted_histogram(join)
        rec["matmul_time_share"] = matmul_time_share(join)
        rec["measured_vs_roofline"] = round(
            join["measured_total_us"] / modeled_roofline_us, 3) \
            if modeled_roofline_us else None
        rec["top_unfused_by_time"] = time_weighted_chains(
            join, unfused_chains(list(kernels), limit=max(20, top)))
    else:
        rec["join"] = {
            "available": False,
            "reason": "no device plane in trace — CPU backend records "
                      "host events only; kernel attribution needs a "
                      "TPU run (wall-time-per-dispatch kept)",
        }
        rec["time_weighted_fusion_histogram"] = {}
        rec["matmul_time_share"] = None
        rec["measured_vs_roofline"] = None
        rec["top_unfused_by_time"] = []
    return rec


# ---------------------------------------------------------------------------
# run-under-profiler helpers (lazy jax)
# ---------------------------------------------------------------------------

def host_example_args(args: tuple) -> tuple:
    """Registry example args pulled back to HOST numpy. Several sites
    donate buffers (the decode tick donates its cache, TrainStep its
    state); executing the REAL site object twice over device-resident
    example args would die on the donated buffer. Host leaves re-upload
    per call, so donation only ever eats the fresh copy. Typed PRNG
    keys cannot become numpy and stay as-is — no registered site
    donates its key argument."""
    import jax
    import numpy as np

    def pull(x):
        dt = getattr(x, "dtype", None)
        if dt is not None and jax.dtypes.issubdtype(
                dt, jax.dtypes.prng_key):
            return x
        return np.asarray(x)
    return jax.tree_util.tree_map(pull, args)


def measure_dispatch(fn, args: tuple, rounds: int = 3,
                     inner: int = 3) -> List[float]:
    """Per-dispatch wall seconds, ``rounds`` samples of ``inner``
    dispatches each (block_until_ready closes every sample's clock).
    The caller interleaves programs ACROSS rounds so one background
    spike cannot land on one program only."""
    import jax
    out = []
    for _ in range(max(1, rounds)):
        t0 = _now()
        for _ in range(max(1, inner)):
            jax.block_until_ready(fn(*args))
        out.append((_now() - t0) / max(1, inner))
    return out


def _now() -> float:
    import time
    return time.perf_counter()


def trace_dispatches(fn, args: tuple, dispatches: int,
                     logdir: str) -> List[dict]:
    """Run ``dispatches`` executions under a programmatic
    ``jax.profiler`` session into ``logdir`` and return the parsed
    trace events. One session per program keeps attribution clean —
    every device event in the trace belongs to this program."""
    import jax
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        for _ in range(max(1, dispatches)):
            jax.block_until_ready(fn(*args))
    finally:
        jax.profiler.stop_trace()
    return load_trace_events(logdir)


def profile_program(build_result, *, rounds: int = 3, inner: int = 3,
                    profile_dispatches: int = 3,
                    logdir: Optional[str] = None,
                    chip: "str | ChipSpec" = DEFAULT_CHIP,
                    name: str = "program") -> dict:
    """End-to-end convenience over ONE BuildResult: warm, measure
    dispatch wall time, trace under the profiler, parse + join, and
    return the runtime report. Runs the builder's cleanup in a finally
    (the registry consumer contract). The CLI uses the pieces directly
    so it can interleave rounds across programs; tests and ad-hoc
    callers use this."""
    import tempfile
    r = build_result
    try:
        hlo = r.fn.lower(*r.args).compile().as_text()
        args = host_example_args(r.args)
        import jax
        jax.block_until_ready(r.fn(*args))            # warm
        dispatch_s = measure_dispatch(r.fn, args, rounds, inner)
        d = logdir or tempfile.mkdtemp(prefix="tpuprof_")
        events = trace_dispatches(r.fn, args, profile_dispatches, d)
    finally:
        if r.cleanup is not None:
            r.cleanup()
    return runtime_report(name, hlo_text=hlo, events=events,
                          dispatch_s=dispatch_s,
                          dispatches_profiled=profile_dispatches,
                          chip=chip, geometry=r.geometry)


# ---------------------------------------------------------------------------
# baseline gate (tools/tpuprof_baseline.json)
# ---------------------------------------------------------------------------
#
# Baseline shape:
#   {"version": 1, "chip": "v5lite", "tolerance": 2.5,
#    "budgets": {"<program>": {"dispatch_ms": 12.3}},
#    "anchors": {"<program>": {"kind": "matmul_time_share_floor",
#                              "min_share": 0.5}
#                          | {"kind": "measured_vs_roofline",
#                             "max_ratio": 40.0}},
#    "notes": {...}}
#
# Budgets re-pin wholesale on --update-baseline (medians of this run;
# partial runs merge); the tolerance band absorbs host jitter. Anchors
# are hand-set invariants that survive updates and need a device plane
# to evaluate — where there is none they are SKIPPED loudly (the
# record's anchors_skipped), never silently passed.


def load_profile_baseline(path: str) -> dict:
    with open(path) as fh:
        base = json.load(fh)
    if not isinstance(base, dict) or "budgets" not in base:
        raise ValueError(f"malformed tpuprof baseline {path!r}: needs "
                         "a 'budgets' dict (see analysis/"
                         "runtime_profile.py)")
    return base


def updated_profile_baseline(base: Optional[dict],
                             reports: Dict[str, dict]) -> dict:
    """Re-pin per-program dispatch medians from this run; anchors,
    notes and the tolerance survive (loosening an anchor or the band
    is a hand edit — the review point)."""
    base = dict(base or {})
    budgets = {}
    for name, rep in sorted(reports.items()):
        med = rep.get("dispatch", {}).get("median_ms")
        if med is None:
            continue
        budgets[name] = {"dispatch_ms": round(float(med), 3)}
    base["budgets"] = budgets
    base.setdefault("anchors", {})
    base.setdefault("notes", {})
    base.setdefault("tolerance", DEFAULT_TOLERANCE)
    base["version"] = 1
    base.setdefault("chip", DEFAULT_CHIP)
    return base


def check_profile_baseline(reports: Dict[str, dict],
                           baseline: Optional[dict],
                           live_programs: Sequence[str],
                           require_all: bool = False
                           ) -> Tuple[List[Finding], List[dict]]:
    """Gate the measured reports. Returns ``(findings, skipped)`` —
    findings empty == gate passes; ``skipped`` lists anchors that
    could NOT be evaluated (no device plane / no join) with reasons,
    which the CLI surfaces in its record so a CPU run never reads as
    its TPU anchors holding."""
    findings: List[Finding] = []
    skipped: List[dict] = []
    baseline = baseline or {"budgets": {}}
    budgets = baseline.get("budgets", {})
    anchors = baseline.get("anchors", {})
    tol = float(baseline.get("tolerance", DEFAULT_TOLERANCE))
    live = set(live_programs)

    if require_all:
        for prog in sorted((set(budgets) | set(anchors)) & live
                           - set(reports)):
            findings.append(Finding(
                PROF_BUDGET, Severity.ERROR, prog, "not-measured",
                f"live program {prog!r} is baselined but produced no "
                "measured report this run — its budgets/anchors were "
                "NOT checked (skipped build? device count?); a full "
                "run must measure every baselined site", {}))

    for section, table in (("budgets", budgets), ("anchors", anchors)):
        for prog in sorted(table):
            if prog not in live:
                findings.append(Finding(
                    STALE_PROF_PROGRAM, Severity.ERROR, prog, section,
                    f"baseline {section} entry names {prog!r} but the "
                    "ProgramRegistry has no such program — renamed or "
                    "deleted without re-pinning "
                    "(tools/tpuprof.py --update-baseline; anchors "
                    "move by hand)", {}))

    for name, rep in sorted(reports.items()):
        med = rep.get("dispatch", {}).get("median_ms")
        b = budgets.get(name)
        if b is None:
            findings.append(Finding(
                PROF_BUDGET, Severity.WARN, name, "unbaselined",
                f"program {name!r} has no tpuprof dispatch budget — a "
                "newly registered program must be pinned (review its "
                "report, then --update-baseline)",
                {"dispatch_ms": med}))
            continue
        if med is None:
            continue
        budget = float(b.get("dispatch_ms", 0.0))
        if budget and med > budget * tol:
            findings.append(Finding(
                PROF_BUDGET, Severity.WARN, name, "dispatch_ms",
                f"measured dispatch median {med:.3f} ms exceeds the "
                f"pinned {budget:.3f} ms x tolerance {tol} — the "
                "program got structurally slower (new compile per "
                "call? dropped fusion? extra sync), or the host is "
                "drowning; re-run, then fix or --update-baseline",
                {"measured_ms": med, "budget_ms": budget,
                 "tolerance": tol}))

    for name, a in sorted(anchors.items()):
        rep = reports.get(name)
        if rep is None:
            continue    # partial runs; full runs flagged above
        kind = a.get("kind", "")
        if kind == "matmul_time_share_floor":
            share = rep.get("matmul_time_share")
            if share is None:
                skipped.append({
                    "program": name, "kind": kind,
                    "reason": rep.get("join", {}).get(
                        "reason", "no measured<->modeled join")})
                continue
            floor = float(a.get("min_share", 0.0))
            if share < floor:
                findings.append(Finding(
                    PROF_ANCHOR, Severity.ERROR, name, kind,
                    f"measured matmul time share {share:.4f} broke "
                    f"the hand-set floor {floor:.4f} — non-matmul "
                    "kernels now own the step's device time",
                    {"measured": share, "floor": floor}))
        elif kind == "measured_vs_roofline":
            ratio = rep.get("measured_vs_roofline")
            if ratio is None:
                skipped.append({
                    "program": name, "kind": kind,
                    "reason": rep.get("join", {}).get(
                        "reason", "no measured<->modeled join")})
                continue
            max_ratio = float(a.get("max_ratio", 10.0))
            if ratio > max_ratio:
                findings.append(Finding(
                    PROF_ANCHOR, Severity.ERROR, name, kind,
                    f"measured device time is {ratio:.2f}x the "
                    f"modeled roofline (max {max_ratio}x) — the "
                    "program drifted further from what the chip "
                    "could do (launch overhead? serialization? an "
                    "unmodeled pass)",
                    {"measured_ratio": ratio, "max_ratio": max_ratio}))
        else:
            findings.append(Finding(
                PROF_ANCHOR, Severity.ERROR, name, "unknown-kind",
                f"anchor for {name!r} has unknown kind {kind!r} "
                "(valid: matmul_time_share_floor, "
                "measured_vs_roofline) — the invariant was NOT "
                "evaluated; fix the baseline", {"kind": kind}))
    return findings, skipped
