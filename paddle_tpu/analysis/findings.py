"""tpulint finding model + baseline gate.

The reference ships whole analysis layers over its graph IR (pass
framework, FLAGS_check_nan_inf, memory-reuse checkers under
paddle/fluid/framework/ir/). Our IR is the jaxpr / lowered StableHLO of
each jitted program; tpulint findings are the structured output of
walking it. This module is the shared vocabulary: a `Finding` is a
(code, program, site) identity plus human message and machine `data`;
the baseline JSON records how many of each identity the tree is KNOWN
to contain, and the gate fails on anything beyond that — the same
ratchet policy as the reference's disabled-op lists, but machine-diffed.

Baseline JSON shape (tools/tpulint_baseline.json):

    {"version": 1,
     "counts": {"<code>::<program>::<site>": n, ...},
     "must_stay_clean": ["<key or key prefix>", ...],
     "notes": {"<key prefix>": "why this is pinned", ...}}

`counts` tolerates up to n occurrences of a key (existing, accepted
hazards — e.g. the embedding gather every causal LM contains).
`must_stay_clean` entries are regression anchors for hazards that were
FIXED: any produced finding whose key starts with such a prefix fails
the gate even if someone also bumps `counts` — reintroducing a fixed
hazard requires editing the anchor itself, which is the point.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

__all__ = [
    "Finding", "Severity",
    "DTYPE_PROMOTION", "SCATTER_OP", "GATHER_OP", "HOST_CALLBACK",
    "UNDONATED_BUFFER", "BAKED_RNG_KEY", "COLLECTIVE",
    "RECOMPILE_DIM", "RECOMPILE_STRUCTURE",
    "JIT_IN_CALL", "JIT_NO_DONATION", "TRACED_ATTR_MUTATION",
    "NUMPY_IN_TRACE", "STALE_QUARANTINE",
    "RACE_UNGUARDED_ATTR", "RACE_BLOCKING_UNDER_LOCK",
    "RACE_LOCK_ORDER", "RACE_CHECK_THEN_ACT", "RACE_ORPHAN_THREAD",
    "COST_BUDGET", "COST_ANCHOR", "STALE_COST_PROGRAM",
    "PROF_BUDGET", "PROF_ANCHOR", "STALE_PROF_PROGRAM",
    "count_findings", "diff_against_baseline", "load_baseline",
    "findings_to_json", "GATE_SEVERITIES",
]

# -- finding codes ---------------------------------------------------------
# program linter (jaxpr / StableHLO level)
DTYPE_PROMOTION = "dtype-promotion"      # silent widening convert on arrays
SCATTER_OP = "scatter-op"                # scatter in a compiled program
GATHER_OP = "gather-op"                  # gather (informational inventory)
HOST_CALLBACK = "host-callback"          # io/pure/debug callback in program
UNDONATED_BUFFER = "undonated-buffer"    # donatable input left undonated
BAKED_RNG_KEY = "baked-rng-key"          # PRNG key constant-folded at trace
COLLECTIVE = "collective"                # collective inventory entry (info)
# recompile-hazard analyzer
RECOMPILE_DIM = "recompile-dim"          # arg dim varies across call specs
RECOMPILE_STRUCTURE = "recompile-structure"  # pytree structure varies
# codebase (AST) lint
JIT_IN_CALL = "jit-in-call"              # jax.jit(...)(...) retrace-per-call
JIT_NO_DONATION = "jit-no-donation"      # hot-wrapper jit without knobs
TRACED_ATTR_MUTATION = "traced-attr-mutation"  # self.x = <expr> in forward
NUMPY_IN_TRACE = "numpy-in-trace"        # numpy call on traced values
STALE_QUARANTINE = "stale-quarantine"    # quarantine entry matches no test
# tpurace (concurrency.py) lock-discipline lint
RACE_UNGUARDED_ATTR = "race-unguarded-attr"    # guarded attr touched
#                                                outside its lock
RACE_BLOCKING_UNDER_LOCK = "race-blocking-under-lock"  # sleep/IO/
#                                                .result while locked
RACE_LOCK_ORDER = "race-lock-order"            # static lock-order cycle
RACE_CHECK_THEN_ACT = "race-check-then-act"    # unlocked test-then-set
RACE_ORPHAN_THREAD = "race-orphan-thread"      # non-daemon, never joined
# tpucost (hlo_cost.py) roofline gate
COST_BUDGET = "cost-budget"              # ratcheted budget exceeded
COST_ANCHOR = "cost-anchor"              # hand-set cost invariant broken
STALE_COST_PROGRAM = "stale-cost-program"  # baseline names a gone program
# tpuprof (runtime_profile.py) measured-runtime gate
PROF_BUDGET = "prof-budget"              # measured dispatch-time ratchet
PROF_ANCHOR = "prof-anchor"              # hand-set measured invariant
STALE_PROF_PROGRAM = "stale-prof-program"  # baseline names a gone program


class Severity:
    """Display/triage tiers. Severity does NOT exempt a finding from
    the gate: every key's count ratchets against the baseline — the
    whole point of pinning the gather/collective inventory is that a
    regression in an 'info' count (e.g. a broken sharding annotation
    doubling the step's all-gathers) still fails CI."""
    ERROR = "error"
    WARN = "warn"
    INFO = "info"


# kept for introspection/compat: severities are display tiers only
GATE_SEVERITIES = (Severity.ERROR, Severity.WARN, Severity.INFO)


@dataclass
class Finding:
    code: str
    severity: str
    program: str        # program name, or repo-relative path for AST lint
    site: str           # stable location id (primitive, arg, symbol) —
                        # never a line number: lines shift, baselines rot
    message: str
    data: dict = field(default_factory=dict)

    @property
    def key(self) -> str:
        return f"{self.code}::{self.program}::{self.site}"

    def to_dict(self) -> dict:
        return {"code": self.code, "severity": self.severity,
                "program": self.program, "site": self.site,
                "message": self.message, "data": self.data,
                "key": self.key}


def _weight(f: "Finding") -> int:
    """Aggregated findings (e.g. '2 scatter op(s)') carry their op count
    in data['count']; the baseline ratchet counts OPS, not finding
    records, so 2 scatters growing to 3 still trips the gate."""
    try:
        return max(1, int(f.data.get("count", 1)))
    except (TypeError, ValueError):
        return 1


def count_findings(findings: List[Finding]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for f in findings:
        out[f.key] = out.get(f.key, 0) + _weight(f)
    return out


def load_baseline(path: str) -> dict:
    with open(path) as fh:
        base = json.load(fh)
    if not isinstance(base, dict) or "counts" not in base:
        raise ValueError(f"malformed baseline {path!r}: needs a 'counts' "
                         "dict (see analysis/findings.py docstring)")
    return base


def diff_against_baseline(findings: List[Finding],
                          baseline: Optional[dict]) -> List[dict]:
    """Return the gate-relevant NEW findings: occurrences of any key
    (every severity — info inventories are count-pinned too) beyond the
    baseline's tolerated count, plus ANY hit on a must_stay_clean
    anchor. Empty list == gate passes."""
    baseline = baseline or {"counts": {}}
    counts = baseline.get("counts", {})
    anchors = tuple(baseline.get("must_stay_clean", []))
    seen: Dict[str, int] = {}
    new: List[dict] = []
    for f in findings:
        # '::'-boundary prefix match: anchor "x::train_step" must not
        # capture a future program named "train_step_acc"
        anchored = any(f.key == a or f.key.startswith(a + "::")
                       for a in anchors)
        seen[f.key] = seen.get(f.key, 0) + _weight(f)
        if anchored:
            d = f.to_dict()
            d["reason"] = "must_stay_clean regression anchor"
            new.append(d)
        elif seen[f.key] > int(counts.get(f.key, 0)):
            d = f.to_dict()
            d["reason"] = (f"count {seen[f.key]} exceeds baseline "
                           f"{int(counts.get(f.key, 0))}")
            new.append(d)
    return new


def findings_to_json(findings: List[Finding], new: List[dict],
                     programs: List[str]) -> dict:
    return {
        "version": 1,
        "programs": sorted(programs),
        "counts": count_findings(findings),
        "findings": [f.to_dict() for f in findings],
        "new": new,
        "gate": "fail" if new else "pass",
    }
