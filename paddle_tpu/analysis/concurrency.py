"""tpurace static lock-discipline lint: AST pass over the tree for the
race/deadlock hazard classes the serving+training concurrency surface
has hand-fixed one at a time (the registry ``get`` deadlock PR 5, the
``_pool_is_binding`` engine-thread race PR 9, journal first-writer-wins
conflicts PR 15).

The model is guarded-attribute inference, per class:

- **Lock attributes** are ``self.X = threading.Lock()/RLock()/
  Condition()`` (or the ``paddle_tpu.obs.locks`` ``make_lock`` /
  ``make_rlock`` / ``make_condition`` factories — the sanitizer
  adoption must not blind the lint).
- **Guarded attributes** are attributes WRITTEN at least once while a
  ``with self.<lock>:`` is held, in any method other than
  ``__init__``. Writes are plain/aug assignment, subscript assignment,
  ``del``, and calls of known container mutators
  (``append``/``pop``/``update``/...).
- Findings:
  * ``race-unguarded-attr`` — a guarded attr read or written outside
    every lock of its class. Cross-class accesses count too: the lint
    types ``self.j = j`` from annotated ``__init__`` params (and
    simple local aliases), so ``j.tokens`` touched outside
    ``j.cond`` in ANOTHER class is the same finding.
  * ``race-blocking-under-lock`` — while a lock is held (a ``with``,
    or a ``*_locked``-suffix method, the caller-holds-the-lock
    convention): ``time.sleep``, ``urlopen``/socket connects,
    ``subprocess`` calls, ``future.result()``, jax device fetch /
    ``block_until_ready``. ``.wait()`` on a condition is exempt — it
    RELEASES the lock.
  * ``race-lock-order`` — edges of the static lock-order graph
    (nested ``with``s, plus one-hop ``self.m()`` / typed ``obj.m()``
    calls into lock-taking methods) that close a cycle.
  * ``race-check-then-act`` (warn) — in a lock-owning class, an
    ``if`` that reads ``self.X`` deciding a write of ``self.X``,
    outside the lock.
  * ``race-orphan-thread`` — ``threading.Thread`` created non-daemon
    with no ``.join()`` path on the attribute it is stored to.

Conventions the lint honors (they are load-bearing in this tree):
``__init__``/``__del__`` are single-threaded by contract and exempt
from guarded-attr/check-then-act flagging; a ``*_locked``-suffix
method asserts "caller holds the lock" (the ``_QosScheduler`` idiom)
and is exempt from unguarded-attr but TREATED AS LOCKED for
blocking-under-lock.

Suppression: ``# tpurace: disable=<code>`` (or a bare ``disable``) on
the flagged line. Sites are ``Class::attr`` / ``Class::method`` —
"::"-separated so baseline ``must_stay_clean`` anchors can pin a whole
class (``race-unguarded-attr::<file>::<Class>``) at a prefix boundary.

Gate: ``tools/tpurace.py`` vs ``tools/tpurace_baseline.json``.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .findings import (RACE_BLOCKING_UNDER_LOCK, RACE_CHECK_THEN_ACT,
                       RACE_LOCK_ORDER, RACE_ORPHAN_THREAD,
                       RACE_UNGUARDED_ATTR, Finding, Severity)

__all__ = ["lint_concurrency_tree", "lint_concurrency_paths",
           "lint_concurrency_file", "collect_classes", "ClassInfo"]

_DISABLE_RE = re.compile(r"#\s*tpurace:\s*disable(?:=([\w,-]+))?")

# threading constructors / sanitizer factories that make self.X a lock
_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_LOCK_FACTORIES = {"make_lock", "make_rlock", "make_condition"}

# container-mutator method names that count as WRITES of self.X for
# guarded-attribute inference (self._queue.append(...) under the lock
# is what marks _queue guarded)
_MUTATORS = {"append", "extend", "insert", "remove", "pop", "clear",
             "update", "setdefault", "popitem", "add", "discard",
             "appendleft", "popleft", "sort", "reverse"}

# callables that BLOCK while a lock is held (module.attr or bare name)
_BLOCKING_CALLS = {
    ("time", "sleep"), ("socket", "create_connection"),
    ("subprocess", "run"), ("subprocess", "call"),
    ("subprocess", "check_call"), ("subprocess", "check_output"),
    ("subprocess", "Popen"), ("jax", "device_get"),
}
# attribute-call names that block regardless of receiver
_BLOCKING_ATTRS = {"urlopen", "result", "block_until_ready"}


def _disabled_codes(line: str) -> Optional[Set[str]]:
    m = _DISABLE_RE.search(line)
    if not m:
        return None
    if not m.group(1):
        return set()               # bare disable: every code
    return {c.strip() for c in m.group(1).split(",")}


def _ann_name(ann) -> Optional[str]:
    """Class name out of a parameter annotation: ``j: _ReqJournal``,
    ``router: "Router"`` (string forward refs), ``rep: mod.Replica``.
    Optional[...] and other generics are ignored — precise enough."""
    if isinstance(ann, ast.Name):
        return ann.id
    if isinstance(ann, ast.Attribute):
        return ann.attr
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.strip().split(".")[-1].strip("'\" ") or None
    return None


def _is_self_attr(node) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _is_lock_ctor(value: ast.AST) -> bool:
    """``threading.Lock()`` / ``Condition(...)`` / ``make_lock(...)``
    (bare or via any module alias: ``locks.make_rlock``)."""
    if not isinstance(value, ast.Call):
        return False
    f = value.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    return name in _LOCK_CTORS or name in _LOCK_FACTORIES


@dataclass
class ClassInfo:
    name: str
    relpath: str
    lock_attrs: Set[str] = field(default_factory=set)
    guarded: Set[str] = field(default_factory=set)
    attr_types: Dict[str, str] = field(default_factory=dict)
    # method -> lock attrs its body acquires via `with self.X`
    method_locks: Dict[str, Set[str]] = field(default_factory=dict)
    joined_attrs: Set[str] = field(default_factory=set)   # self.X.join(


# ---------------------------------------------------------------------------
# pass 1: per-class inventory
# ---------------------------------------------------------------------------

class _Collector(ast.NodeVisitor):
    def __init__(self, relpath: str):
        self.relpath = relpath
        self.classes: Dict[str, ClassInfo] = {}
        self._cls: List[ClassInfo] = []
        self._fn: List[str] = []
        self._held = 0                 # depth of self-lock withs

    def visit_ClassDef(self, node: ast.ClassDef):
        info = ClassInfo(node.name, self.relpath)
        self.classes[node.name] = info
        self._cls.append(info)
        self.generic_visit(node)
        self._cls.pop()

    def _visit_fn(self, node):
        self._fn.append(node.name)
        cls = self._cls[-1] if self._cls else None
        if cls is not None and len(self._fn) == 1:
            cls.method_locks.setdefault(node.name, set())
            if node.name == "__init__":
                # annotated params give self.X = param its type
                anns = {}
                args = node.args
                for a in (args.posonlyargs + args.args
                          + args.kwonlyargs):
                    t = _ann_name(a.annotation)
                    if t:
                        anns[a.arg] = t
                for stmt in node.body:
                    if (isinstance(stmt, ast.Assign)
                            and len(stmt.targets) == 1):
                        attr = _is_self_attr(stmt.targets[0])
                        if not attr:
                            continue
                        v = stmt.value
                        if (isinstance(v, ast.Name)
                                and v.id in anns):
                            cls.attr_types[attr] = anns[v.id]
                        elif (isinstance(v, ast.Call)
                              and isinstance(v.func, ast.Name)):
                            cls.attr_types[attr] = v.func.id
        self.generic_visit(node)
        self._fn.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_With(self, node: ast.With):
        cls = self._cls[-1] if self._cls else None
        takes = []
        for item in node.items:
            attr = _is_self_attr(item.context_expr)
            if cls is not None and attr and attr in cls.lock_attrs:
                takes.append(attr)
        if takes and cls is not None and self._fn:
            cls.method_locks.setdefault(self._fn[0], set()).update(takes)
        self._held += len(takes)
        self.generic_visit(node)
        self._held -= len(takes)

    def _note_write(self, attr: str):
        cls = self._cls[-1] if self._cls else None
        if (cls is None or not self._fn or self._fn[0] == "__init__"
                or attr in cls.lock_attrs):
            return
        if self._held > 0:
            cls.guarded.add(attr)

    def visit_Assign(self, node: ast.Assign):
        cls = self._cls[-1] if self._cls else None
        for t in node.targets:
            attr = _is_self_attr(t)
            if attr:
                if cls is not None and _is_lock_ctor(node.value):
                    cls.lock_attrs.add(attr)
                    cls.guarded.discard(attr)
                else:
                    self._note_write(attr)
            elif isinstance(t, ast.Subscript):
                a2 = _is_self_attr(t.value)
                if a2:
                    self._note_write(a2)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        attr = _is_self_attr(node.target)
        if attr:
            self._note_write(attr)
        elif isinstance(node.target, ast.Subscript):
            a2 = _is_self_attr(node.target.value)
            if a2:
                self._note_write(a2)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete):
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                attr = _is_self_attr(t.value)
                if attr:
                    self._note_write(attr)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute):
            base_attr = _is_self_attr(f.value)
            if base_attr:
                if f.attr in _MUTATORS:
                    self._note_write(base_attr)
                if f.attr == "join" and self._cls:
                    self._cls[-1].joined_attrs.add(base_attr)
        self.generic_visit(node)


def collect_classes(paths: List[str], root: str) -> Dict[str, ClassInfo]:
    """Pass 1 over ``paths``: per-class lock attrs, guarded attrs,
    attribute types, method->locks map. Keyed by class NAME (the tree
    keeps concurrency-bearing class names unique; a collision merges
    conservatively toward more findings, never fewer)."""
    out: Dict[str, ClassInfo] = {}
    for path in paths:
        relpath = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), filename=path)
        except (OSError, SyntaxError):
            continue
        c = _Collector(relpath)
        c.visit(tree)
        for name, info in c.classes.items():
            prev = out.get(name)
            if prev is None:
                out[name] = info
            else:
                prev.lock_attrs |= info.lock_attrs
                prev.guarded |= info.guarded
                prev.attr_types.update(info.attr_types)
                for m, ls in info.method_locks.items():
                    prev.method_locks.setdefault(m, set()).update(ls)
                prev.joined_attrs |= info.joined_attrs
    return out


# ---------------------------------------------------------------------------
# pass 2: flagging
# ---------------------------------------------------------------------------

def _exempt_method(name: str) -> bool:
    return name in ("__init__", "__del__") or name.endswith("_locked")


class _Access:
    __slots__ = ("line", "method", "write")

    def __init__(self, line, method, write):
        self.line = line
        self.method = method
        self.write = write


class _Flagger(ast.NodeVisitor):
    """One file's flagging walk. Shared mutable state across files:
    ``order_edges`` (the static lock-order graph) and the aggregated
    ``unguarded`` access map."""

    def __init__(self, relpath: str, lines: List[str],
                 classes: Dict[str, ClassInfo],
                 unguarded: Dict[Tuple[str, str, str], List[_Access]],
                 order_edges: Dict[Tuple[str, str], dict]):
        self.relpath = relpath
        self.lines = lines
        self.classes = classes
        self.unguarded = unguarded
        self.order_edges = order_edges
        self.findings: List[Finding] = []
        self._cls: List[Optional[ClassInfo]] = []
        self._fn: List[str] = []
        # held locks: list of (base_key, ClassName, lockattr)
        # base_key: ("self",) or ("local", varname) or
        # ("selfattr", fieldname)
        self._held: List[Tuple[tuple, str, str]] = []
        self._local_types: List[Dict[str, str]] = []
        self._blocking_seen: Set[Tuple[str, str]] = set()
        self._cta_seen: Set[Tuple[str, str]] = set()

    # -- plumbing ----------------------------------------------------------
    def _suppressed(self, node, code) -> bool:
        ln = getattr(node, "lineno", 0)
        if 1 <= ln <= len(self.lines):
            dis = _disabled_codes(self.lines[ln - 1])
            if dis is not None and (not dis or code in dis):
                return True
        return False

    def _emit(self, node, code, severity, site, message, data=None):
        if self._suppressed(node, code):
            return
        self.findings.append(Finding(
            code, severity, self.relpath, site, message,
            dict(data or {}, line=getattr(node, "lineno", 0))))

    def _cur_cls(self) -> Optional[ClassInfo]:
        return self._cls[-1] if self._cls else None

    def _cur_fn(self) -> str:
        return self._fn[0] if self._fn else "<module>"

    def _type_of(self, node) -> Optional[str]:
        """Static type of an expression, best effort: a local alias /
        annotated param, or ``self.field`` with a known field type."""
        if isinstance(node, ast.Name):
            for scope in reversed(self._local_types):
                if node.id in scope:
                    return scope[node.id]
            return None
        attr = _is_self_attr(node)
        if attr is not None:
            cls = self._cur_cls()
            if cls is not None:
                return cls.attr_types.get(attr)
        return None

    def _base_key(self, node) -> Optional[tuple]:
        if isinstance(node, ast.Name):
            return ("local", node.id)
        attr = _is_self_attr(node)
        if attr is not None:
            return ("selfattr", attr)
        return None

    def _holds(self, base_key: tuple, cls_name: str) -> bool:
        """Is ANY lock of ``cls_name`` held for this base object (or,
        for self accesses, any self lock)?"""
        return any(b == base_key and c == cls_name
                   for b, c, _ in self._held)

    # -- scope -------------------------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef):
        self._cls.append(self.classes.get(node.name))
        self.generic_visit(node)
        self._cls.pop()

    def _visit_fn(self, node):
        self._fn.append(node.name)
        scope: Dict[str, str] = {}
        args = node.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            t = _ann_name(a.annotation)
            if t and t in self.classes:
                scope[a.arg] = t
        self._local_types.append(scope)
        # a *_locked method asserts the caller holds every lock of the
        # class: model that for blocking-under-lock purposes
        cls = self._cur_cls()
        pushed = 0
        if (cls is not None and len(self._fn) == 1
                and node.name.endswith("_locked")):
            for la in sorted(cls.lock_attrs):
                self._held.append((("self",), cls.name, la))
                pushed += 1
        self.generic_visit(node)
        for _ in range(pushed):
            self._held.pop()
        self._local_types.pop()
        self._fn.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    # -- alias tracking ----------------------------------------------------
    def _track_alias(self, target, value):
        if not isinstance(target, ast.Name) or not self._local_types:
            return
        t = self._type_of(value)
        if t:
            self._local_types[-1][target.id] = t
        else:
            self._local_types[-1].pop(target.id, None)

    # -- with: lock acquisition -------------------------------------------
    def _lock_of(self, expr) -> Optional[Tuple[tuple, str, str]]:
        """``with <expr>:`` — is expr a known lock? Returns
        (base_key, ClassName, lockattr)."""
        if not isinstance(expr, ast.Attribute):
            return None
        # self.X
        attr = _is_self_attr(expr)
        cls = self._cur_cls()
        if attr is not None:
            if cls is not None and attr in cls.lock_attrs:
                return (("self",), cls.name, attr)
            return None
        # obj.X / self.field.X with typed base
        t = self._type_of(expr.value)
        if t and t in self.classes \
                and expr.attr in self.classes[t].lock_attrs:
            bk = self._base_key(expr.value)
            if bk is not None:
                return (bk, t, expr.attr)
        return None

    def _add_order_edge(self, src: Tuple[str, str], dst: Tuple[str, str],
                        node):
        if src == dst:
            return        # reentrant same-lock: RLock territory
        a = f"{src[0]}.{src[1]}"
        b = f"{dst[0]}.{dst[1]}"
        if a == b:
            return
        self.order_edges.setdefault((a, b), {
            "file": self.relpath, "line": getattr(node, "lineno", 0),
            "method": f"{self._cur_cls().name if self._cur_cls() else '<module>'}"
                      f"::{self._cur_fn()}"})

    def visit_With(self, node: ast.With):
        taken = []
        for item in node.items:
            lk = self._lock_of(item.context_expr)
            if lk is not None:
                for _, hc, hl in self._held:
                    self._add_order_edge((hc, hl), (lk[1], lk[2]),
                                         item.context_expr)
                self._held.append(lk)
                taken.append(lk)
        self.generic_visit(node)
        for _ in taken:
            self._held.pop()

    # -- accesses ----------------------------------------------------------
    def _flag_access(self, node: ast.Attribute, write: bool):
        attr = node.attr
        base_self = _is_self_attr(node)
        if base_self is not None:
            cls = self._cur_cls()
            if (cls is None or attr not in cls.guarded
                    or _exempt_method(self._cur_fn())
                    or (len(self._fn) != 1
                        and not self._fn)):
                return
            if self._holds(("self",), cls.name):
                return
            if self._suppressed(node, RACE_UNGUARDED_ATTR):
                return
            key = (self.relpath, cls.name, attr)
            self.unguarded.setdefault(key, []).append(
                _Access(node.lineno, self._cur_fn(), write))
            return
        # typed foreign object: obj.attr
        t = self._type_of(node.value)
        if not t or t not in self.classes:
            return
        info = self.classes[t]
        if attr not in info.guarded:
            return
        bk = self._base_key(node.value)
        if bk is None or self._holds(bk, t):
            return
        if self._suppressed(node, RACE_UNGUARDED_ATTR):
            return
        key = (self.relpath, t, attr)
        self.unguarded.setdefault(key, []).append(
            _Access(node.lineno, self._cur_fn(), write))

    def visit_Attribute(self, node: ast.Attribute):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self._flag_access(node, write=True)
        elif isinstance(node.ctx, ast.Load):
            # loads that are just the base of a deeper attribute /
            # call get visited naturally; flag the leaf access only
            self._flag_access(node, write=False)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign):
        if len(node.targets) == 1:
            self._track_alias(node.targets[0], node.value)
            if isinstance(node.targets[0], ast.Tuple) \
                    and isinstance(node.value, ast.Tuple) \
                    and len(node.targets[0].elts) == len(node.value.elts):
                for t, v in zip(node.targets[0].elts, node.value.elts):
                    self._track_alias(t, v)
        self.generic_visit(node)

    # -- blocking under lock ----------------------------------------------
    def _call_blocks(self, node: ast.Call) -> Optional[str]:
        f = node.func
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name) \
                    and (f.value.id, f.attr) in _BLOCKING_CALLS:
                return f"{f.value.id}.{f.attr}"
            if f.attr in _BLOCKING_ATTRS:
                # cond.wait() releases the lock — but .wait is not in
                # the list anyway; .result on a lock-ish receiver is
                # still a future by convention here
                return f".{f.attr}"
        elif isinstance(f, ast.Name) and f.id in _BLOCKING_ATTRS:
            return f.id
        return None

    def visit_Call(self, node: ast.Call):
        # mutator calls count as writes of the receiver attr
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
            if isinstance(f.value, ast.Attribute):
                self._flag_access(f.value, write=True)
        if self._held:
            what = self._call_blocks(node)
            if what is not None:
                cls = self._cur_cls()
                site = (f"{cls.name if cls else '<module>'}"
                        f"::{self._cur_fn()}::{what.lstrip('.')}")
                dkey = (site, self.relpath)
                if dkey not in self._blocking_seen:
                    self._blocking_seen.add(dkey)
                    held = ", ".join(f"{c}.{l}" for _, c, l in
                                     self._held)
                    self._emit(
                        node, RACE_BLOCKING_UNDER_LOCK, Severity.WARN,
                        site,
                        f"blocking call {what} while holding {held} — "
                        "every other thread contending on that lock "
                        "stalls for the full duration; move the "
                        "blocking work outside the critical section",
                        {"held": held})
        self.generic_visit(node)

    # -- check-then-act ----------------------------------------------------
    def _attrs_read(self, expr) -> Set[str]:
        out = set()
        for n in ast.walk(expr):
            a = _is_self_attr(n)
            if a is not None and isinstance(n.ctx, ast.Load):
                out.add(a)
        return out

    def _attrs_written(self, stmts) -> Set[str]:
        out = set()
        for stmt in stmts:
            for n in ast.walk(stmt):
                if isinstance(n, ast.Attribute) \
                        and isinstance(n.ctx, (ast.Store, ast.Del)):
                    a = _is_self_attr(n)
                    if a is not None:
                        out.add(a)
                elif isinstance(n, ast.Subscript) \
                        and isinstance(n.ctx, (ast.Store, ast.Del)):
                    a = _is_self_attr(n.value)
                    if a is not None:
                        out.add(a)
                elif isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr in _MUTATORS:
                    a = _is_self_attr(n.func.value)
                    if a is not None:
                        out.add(a)
        return out

    def visit_If(self, node: ast.If):
        cls = self._cur_cls()
        if (cls is not None and cls.lock_attrs and not self._held
                and self._fn and not _exempt_method(self._cur_fn())):
            hot = ((self._attrs_read(node.test)
                    & self._attrs_written(node.body))
                   - cls.lock_attrs)
            for attr in sorted(hot):
                site = f"{cls.name}::{self._cur_fn()}::{attr}"
                if (site, self.relpath) in self._cta_seen:
                    continue
                self._cta_seen.add((site, self.relpath))
                self._emit(
                    node, RACE_CHECK_THEN_ACT, Severity.WARN, site,
                    f"check-then-act on self.{attr} outside "
                    f"{'/'.join(sorted(cls.lock_attrs))} — the state "
                    "tested can change between the test and the write; "
                    "take the lock around the pair (or mark the method "
                    "*_locked if the caller already holds it)")
        self.generic_visit(node)

    # -- orphan threads ----------------------------------------------------
    def _is_thread_ctor(self, node: ast.Call) -> bool:
        f = node.func
        return ((isinstance(f, ast.Attribute) and f.attr == "Thread"
                 and isinstance(f.value, ast.Name)
                 and f.value.id == "threading")
                or (isinstance(f, ast.Name) and f.id == "Thread"))

    def generic_visit(self, node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.value, ast.Call) \
                and self._is_thread_ctor(node.value):
            self._check_thread(node.value, _is_self_attr(node.targets[0]))
        elif isinstance(node, ast.Expr) \
                and isinstance(node.value, ast.Call):
            call = node.value
            # threading.Thread(...).start() chains
            if isinstance(call.func, ast.Attribute) \
                    and call.func.attr == "start" \
                    and isinstance(call.func.value, ast.Call) \
                    and self._is_thread_ctor(call.func.value):
                self._check_thread(call.func.value, None)
        super().generic_visit(node)

    def _check_thread(self, ctor: ast.Call, stored_attr: Optional[str]):
        for kw in ctor.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value:
                return
        cls = self._cur_cls()
        if stored_attr and cls is not None \
                and stored_attr in cls.joined_attrs:
            return                 # non-daemon but joined: a stop() path
        site = (f"{cls.name if cls else '<module>'}::{self._cur_fn()}")
        self._emit(
            ctor, RACE_ORPHAN_THREAD, Severity.WARN, site,
            "non-daemon Thread with no joining stop() path — it will "
            "outlive (and hang) interpreter shutdown; pass daemon=True "
            "or store it on self and join it in stop()/close()",
            {"stored_as": stored_attr or ""})


# ---------------------------------------------------------------------------
# cycle detection + assembly
# ---------------------------------------------------------------------------

def _find_cycles(edges: Dict[Tuple[str, str], dict]) -> List[List[str]]:
    """Elementary cycles in the lock-order graph, deduped by node set
    (one finding per distinct cycle, whatever rotation found it)."""
    graph: Dict[str, List[str]] = {}
    for a, b in edges:
        graph.setdefault(a, []).append(b)
        graph.setdefault(b, [])
    cycles: List[List[str]] = []
    seen_sets: Set[frozenset] = set()

    def dfs(start: str, node: str, path: List[str], visited: Set[str]):
        for nxt in graph.get(node, ()):
            if nxt == start and len(path) > 1:
                key = frozenset(path)
                if key not in seen_sets:
                    seen_sets.add(key)
                    lo = min(range(len(path)), key=lambda i: path[i])
                    cycles.append(path[lo:] + path[:lo])
            elif nxt not in visited and nxt > start:
                # only walk nodes > start: each cycle is discovered
                # from its smallest node exactly once
                visited.add(nxt)
                dfs(start, nxt, path + [nxt], visited)
                visited.discard(nxt)

    for n in sorted(graph):
        dfs(n, n, [n], {n})
    return sorted(cycles)


def lint_concurrency_paths(paths: List[str], root: str) -> List[Finding]:
    classes = collect_classes(paths, root)
    unguarded: Dict[Tuple[str, str, str], List[_Access]] = {}
    order_edges: Dict[Tuple[str, str], dict] = {}
    findings: List[Finding] = []
    for path in sorted(paths):
        relpath = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as fh:
                src = fh.read()
            tree = ast.parse(src, filename=path)
        except OSError:
            continue
        except SyntaxError as e:
            findings.append(Finding("lint-error", Severity.ERROR,
                                    relpath, "parse",
                                    f"syntax error: {e}", {}))
            continue
        fl = _Flagger(relpath, src.splitlines(), classes,
                      unguarded, order_edges)
        fl.visit(tree)
        findings.extend(fl.findings)
    # aggregate unguarded accesses: one finding per (file, class, attr)
    for (relpath, cls_name, attr) in sorted(unguarded):
        accs = unguarded[(relpath, cls_name, attr)]
        locks = "/".join(sorted(classes[cls_name].lock_attrs)) or "?"
        kinds = ("writes" if all(a.write for a in accs) else
                 "reads" if not any(a.write for a in accs) else
                 "reads+writes")
        findings.append(Finding(
            RACE_UNGUARDED_ATTR, Severity.WARN, relpath,
            f"{cls_name}::{attr}",
            f"{cls_name}.{attr} is written under {locks} elsewhere but "
            f"touched outside it here ({len(accs)} {kinds}: "
            f"{', '.join(sorted({a.method for a in accs}))}) — a "
            "torn/stale view races the locked writer",
            {"count": len(accs),
             "lines": sorted(a.line for a in accs),
             "methods": sorted({a.method for a in accs})}))
    for cyc in _find_cycles(order_edges):
        ring = cyc + [cyc[0]]
        detail = []
        for a, b in zip(ring, ring[1:]):
            e = order_edges.get((a, b))
            if e:
                detail.append(f"{a}->{b} at {e['file']}:{e['line']} "
                              f"({e['method']})")
        findings.append(Finding(
            RACE_LOCK_ORDER, Severity.ERROR, "<lock-graph>",
            "->".join(ring),
            "static lock-order cycle: two threads taking these locks "
            "in opposing orders deadlock; impose one global order "
            f"({'; '.join(detail)})",
            {"edges": detail}))
    findings.sort(key=lambda f: f.key)
    return findings


def lint_concurrency_tree(root: str,
                          package: str = "paddle_tpu") -> List[Finding]:
    """The tpurace pass over every .py under <root>/<package>."""
    paths: List[str] = []
    pkg_root = os.path.join(root, package)
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                paths.append(os.path.join(dirpath, fname))
    return lint_concurrency_paths(paths, root)


def lint_concurrency_file(path: str, root: str) -> List[Finding]:
    """Two-pass lint over ONE file (test fixtures)."""
    return lint_concurrency_paths([path], root)
