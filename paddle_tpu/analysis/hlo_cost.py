"""tpucost — static fusion & HBM-traffic cost model over compiled HLO.

The MFU campaign (ROADMAP item 3) needs its measurement half before any
fusion work can land: "Operator Fusion in XLA" (PAPERS.md 2301.13062)
shows XLA's fusion decisions are analyzable — and frequently suboptimal
— from the HLO text alone, and MPK (PAPERS.md 2512.22219) motivates
knowing exactly which per-layer HBM round-trips dominate the decode
tick. This module turns the compiled HLO of any registered program into
a per-kernel inventory WITHOUT executing anything:

- every top-level instruction of the entry computation (recursing into
  while bodies with their statically-recovered trip counts, call
  targets, and the costlier conditional branch) is one KERNEL — one
  launch, one HBM round-trip boundary;
- a kernel's HBM bytes are its operand reads + result writes; values
  produced INSIDE a fusion never touch HBM (the cache-awareness that
  makes fusion worth measuring), so a fused producer is free and an
  unfused one pays write + re-read;
- FLOPs per kernel: dots count 2 * prod(result dims) * contraction
  size (batch dims included via the result), elementwise arithmetic
  counts one per output element, reductions count their input elements;
  data movement (copy/transpose/broadcast/slice/gather/...) is zero
  FLOPs but full traffic — exactly the ops a roofline says are free to
  fuse and expensive to leave standalone;
- roofline-predicted time per kernel under a configurable
  :class:`ChipSpec` = max(flops/peak, bytes/bw); the program total is
  the sum over kernels x trip counts.

The chip-spec table here is the ONE place accelerator constants live:
`tools/tpucost.py` defaults to v5-lite (the chip the measured 33.6% MFU
anchor ran on) and `tools/northstar_model.py` imports its v5p numbers
from the same table.

`check_cost_baseline` is the gate: per-program ratcheted budgets (total
HBM bytes, kernel count, matmul-FLOP share floor) plus must-stay-true
anchors (the engine decode tick's modeled HBM bytes within 1.15x of the
analytic KV-cache + weight bound; train_step's matmul share never
drops), emitted as `analysis.findings.Finding`s so tpulint's
baseline/report idioms carry over unchanged.

Parsing is line-based over the text `Compiled.as_text()` returns —
checked-in fixtures under tests/fixtures/hlo/ exercise it with zero
compiles.
"""
from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .findings import (COST_ANCHOR, COST_BUDGET, STALE_COST_PROGRAM,
                       Finding, Severity)

__all__ = [
    "ChipSpec", "CHIP_SPECS", "DEFAULT_CHIP", "HLO_DTYPE_BYTES",
    "parse_hlo_module", "program_cost", "collect_kernels", "KernelCost",
    "analytic_decode_hbm_bytes", "analytic_paged_decode_hbm_bytes",
    "analytic_verify_hbm_bytes",
    "check_cost_baseline",
    "load_cost_baseline", "updated_cost_baseline",
]

# ---------------------------------------------------------------------------
# chip specs — the one table lives in chips.py (dependency-free so
# tools/northstar_model.py can load it without the package import);
# re-exported here as the tpucost-facing surface
# ---------------------------------------------------------------------------

from .chips import CHIP_SPECS, DEFAULT_CHIP, ChipSpec  # noqa: E402

# HLO dtype -> bytes (shared: program_lint's collective inventory uses
# this same table)
HLO_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
    "token": 0, "opaque": 0,
}

# ---------------------------------------------------------------------------
# HLO text parsing
# ---------------------------------------------------------------------------

_SHAPE_RE = re.compile(r"(?P<dt>[a-z][a-z0-9]*)\[(?P<dims>[0-9,]*)\]")
_COMP_RE = re.compile(
    r"^(?P<entry>ENTRY\s+)?%?(?P<name>[\w.\-]+)\s*\(.*\)\s*->.*\{")
_INSTR_RE = re.compile(r"^\s*(?P<root>ROOT\s+)?%(?P<name>[\w.\-]+)\s*=\s*")
_TYPE_RE = re.compile(r"[a-z][a-z0-9]*\[[0-9,]*\](?:\{[^}]*\})?")
_OPCODE_RE = re.compile(r"\s*(?P<op>[\w\-]+)\(")
_OPND_RE = re.compile(r"%(?P<name>[\w.\-]+)")

_ATTR_RES = {
    "kind": re.compile(r"\bkind=(\w+)"),
    "calls": re.compile(r"\bcalls=%?([\w.\-]+)"),
    "condition": re.compile(r"\bcondition=%?([\w.\-]+)"),
    "body": re.compile(r"\bbody=%?([\w.\-]+)"),
    "to_apply": re.compile(r"\bto_apply=%?([\w.\-]+)"),
    "lhs_contracting_dims": re.compile(
        r"\blhs_contracting_dims=\{([0-9,]*)\}"),
    "direction": re.compile(r"\bdirection=(\w+)"),
    "custom_call_target": re.compile(r'\bcustom_call_target="([^"]+)"'),
    "branch_computations": re.compile(r"\bbranch_computations=\{([^}]*)\}"),
    "true_computation": re.compile(r"\btrue_computation=%?([\w.\-]+)"),
    "false_computation": re.compile(r"\bfalse_computation=%?([\w.\-]+)"),
    "op_name": re.compile(r'\bop_name="([^"]*)"'),
}


@dataclass
class Instr:
    name: str
    opcode: str
    shapes: List[Tuple[str, Tuple[int, ...]]]   # result shapes, flattened
    operands: List[str]                         # operand instruction names
    attrs: Dict[str, str]
    root: bool = False
    literal: str = ""                           # constant literal text


@dataclass
class Computation:
    name: str
    entry: bool
    instrs: List[Instr] = field(default_factory=list)
    by_name: Dict[str, Instr] = field(default_factory=dict)

    @property
    def root(self) -> Optional[Instr]:
        for i in self.instrs:
            if i.root:
                return i
        return self.instrs[-1] if self.instrs else None


@dataclass
class HloModule:
    computations: Dict[str, Computation]
    entry: str


def _shapes_of(text: str) -> List[Tuple[str, Tuple[int, ...]]]:
    out = []
    for m in _SHAPE_RE.finditer(text):
        dims = tuple(int(d) for d in m.group("dims").split(",") if d)
        out.append((m.group("dt"), dims))
    return out


def shape_bytes(shapes: Sequence[Tuple[str, Tuple[int, ...]]]) -> int:
    total = 0
    for dt, dims in shapes:
        n = 1
        for d in dims:
            n *= d
        total += n * HLO_DTYPE_BYTES.get(dt, 4)
    return total


def shape_elems(shapes: Sequence[Tuple[str, Tuple[int, ...]]]) -> int:
    total = 0
    for _, dims in shapes:
        n = 1
        for d in dims:
            n *= d
        total += n
    return total


def _balanced(s: str, start: int) -> int:
    """Index just past the ')' matching the '(' at `start`."""
    depth = 0
    for i in range(start, len(s)):
        if s[i] == "(":
            depth += 1
        elif s[i] == ")":
            depth -= 1
            if depth == 0:
                return i + 1
    return len(s)


def _parse_instr(line: str) -> Optional[Instr]:
    m = _INSTR_RE.match(line)
    if m is None:
        return None
    rest = line[m.end():].lstrip()
    if rest.startswith("("):            # tuple-typed result
        end = _balanced(rest, 0)
        type_str, rest = rest[:end], rest[end:].lstrip()
    else:
        tm = _TYPE_RE.match(rest)
        if tm is None:
            return None
        type_str, rest = tm.group(0), rest[tm.end():].lstrip()
    om = _OPCODE_RE.match(rest)
    if om is None:
        return None
    opcode = om.group("op")
    open_paren = om.end() - 1
    close = _balanced(rest, open_paren)
    inner = rest[open_paren + 1:close - 1]
    tail = rest[close:]
    attrs = {}
    for key, rx in _ATTR_RES.items():
        am = rx.search(tail)
        if am:
            attrs[key] = am.group(1)
    return Instr(
        name=m.group("name"), opcode=opcode, shapes=_shapes_of(type_str),
        operands=[o.group("name") for o in _OPND_RE.finditer(inner)],
        attrs=attrs, root=bool(m.group("root")),
        literal=inner if opcode == "constant" else "")


def parse_hlo_module(text: str) -> HloModule:
    """Line-based parse of `Compiled.as_text()` output into computations
    of instructions. Tolerant: unrecognized lines are skipped, so a new
    XLA attribute can never crash the pass (it only degrades detail)."""
    comps: Dict[str, Computation] = {}
    entry = ""
    cur: Optional[Computation] = None
    for line in text.splitlines():
        if cur is None:
            cm = _COMP_RE.match(line)
            if cm:
                cur = Computation(cm.group("name"),
                                  bool(cm.group("entry")))
            continue
        if line.strip() == "}":
            comps[cur.name] = cur
            if cur.entry:
                entry = cur.name
            cur = None
            continue
        ins = _parse_instr(line)
        if ins is not None:
            cur.instrs.append(ins)
            cur.by_name[ins.name] = ins
    if not entry and comps:       # single-computation fixture w/o ENTRY
        entry = next(iter(comps))
    return HloModule(comps, entry)


# ---------------------------------------------------------------------------
# per-op FLOP model
# ---------------------------------------------------------------------------

# one FLOP per output element
_ELEMWISE = {
    "add", "subtract", "multiply", "divide", "power", "maximum",
    "minimum", "abs", "negate", "exponential", "exponential-minus-one",
    "log", "log-plus-one", "tanh", "logistic", "sqrt", "rsqrt", "cbrt",
    "sine", "cosine", "tan", "atan2", "remainder", "sign", "compare",
    "select", "clamp", "and", "or", "xor", "not", "shift-left",
    "shift-right-arithmetic", "shift-right-logical", "is-finite",
    "floor", "ceil", "round-nearest-afz", "round-nearest-even",
    "popcnt", "count-leading-zeros", "erf", "map", "select-and-scatter",
}

# zero FLOPs, full HBM traffic when standalone
_DATA_MOVEMENT = {
    "copy", "copy-start", "transpose", "reshape", "broadcast", "slice",
    "dynamic-slice", "dynamic-update-slice", "concatenate", "pad",
    "reverse", "gather", "iota", "convert", "bitcast-convert", "real",
    "imag", "complex", "rng", "rng-bit-generator", "sort",
}

_COLLECTIVES = {"all-reduce", "all-gather", "reduce-scatter",
                "collective-permute", "all-to-all",
                "all-reduce-start", "all-gather-start",
                "collective-permute-start"}

# free glue: no kernel, no HBM boundary of its own
_SKIP = {
    "parameter", "constant", "get-tuple-element", "tuple", "bitcast",
    "after-all", "partition-id", "replica-id", "get-dimension-size",
    "add-dependency", "domain", "opt-barrier", "copy-done",
    "all-reduce-done", "all-gather-done", "collective-permute-done",
    "rng-get-and-update-state", "send", "send-done", "recv",
    "recv-done",
}

# kernels smaller than this (operands + results) are scalar glue —
# loop counters, predicates — excluded from the fusion histogram and
# the kernel-count budget so the ratchet tracks real HBM traffic
SCALAR_GLUE_BYTES = 4096


@dataclass
class KernelCost:
    """One launched kernel (top-level instruction or fusion), already
    multiplied by its loop trip count."""
    name: str
    opcode: str
    klass: str                 # histogram class (see fusion.py)
    flops: float
    matmul_flops: float
    bytes_read: int
    bytes_written: int
    trip: int
    path: str                  # loop/call nesting, e.g. "while.2"
    op_name: str = ""          # jax-level metadata label
    operands: Tuple[str, ...] = ()

    @property
    def hbm_bytes(self) -> int:
        return self.bytes_read + self.bytes_written

    @property
    def intensity(self) -> float:
        return self.flops / self.hbm_bytes if self.hbm_bytes else 0.0

    def roofline_seconds(self, chip: ChipSpec) -> float:
        return max(self.flops / chip.peak_flops,
                   self.hbm_bytes / chip.hbm_bandwidth)

    def to_dict(self, chip: ChipSpec) -> dict:
        return {
            "name": self.name, "op": self.opcode, "class": self.klass,
            "flops": self.flops, "matmul_flops": self.matmul_flops,
            "bytes_read": self.bytes_read,
            "bytes_written": self.bytes_written, "trip": self.trip,
            "path": self.path, "op_name": self.op_name,
            "arithmetic_intensity": round(self.intensity, 3),
            "roofline_us": round(self.roofline_seconds(chip) * 1e6, 3),
        }


def _operand_shapes(ins: Instr, comp: Computation):
    seen = set()
    for name in ins.operands:
        if name in seen:        # a kernel streams each operand once
            continue
        seen.add(name)
        src = comp.by_name.get(name)
        if src is not None:
            yield src


def _dot_flops(ins: Instr, comp: Computation) -> float:
    out_elems = shape_elems(ins.shapes)
    lhs = comp.by_name.get(ins.operands[0]) if ins.operands else None
    k = 1
    if lhs is not None and lhs.shapes:
        dims = lhs.shapes[0][1]
        cdims = [int(d) for d in
                 ins.attrs.get("lhs_contracting_dims", "").split(",")
                 if d]
        for d in cdims:
            if d < len(dims):
                k *= dims[d]
    return 2.0 * out_elems * k


def _plain_op_flops(ins: Instr, comp: Computation) -> Tuple[float, float]:
    """(flops, matmul_flops) for one non-fusion instruction."""
    op = ins.opcode
    if op == "dot":
        f = _dot_flops(ins, comp)
        return f, f
    if op in _ELEMWISE:
        return float(shape_elems(ins.shapes)), 0.0
    if op in ("reduce", "reduce-window"):
        src = comp.by_name.get(ins.operands[0]) if ins.operands else None
        elems = shape_elems(src.shapes) if src is not None \
            else shape_elems(ins.shapes)
        return float(elems), 0.0
    if op == "scatter" and len(ins.operands) >= 3:
        upd = comp.by_name.get(ins.operands[2])
        if upd is not None:
            return float(shape_elems(upd.shapes)), 0.0
    if op in ("all-reduce", "all-reduce-start"):
        return float(shape_elems(ins.shapes)), 0.0
    return 0.0, 0.0            # data movement / unknown: traffic only


def _fusion_flops(ins: Instr, module: HloModule,
                  notes: List[str]) -> Tuple[float, float]:
    called = module.computations.get(ins.attrs.get("calls", ""))
    if called is None:
        notes.append(f"fusion {ins.name}: called computation not found")
        return 0.0, 0.0
    flops = matmul = 0.0
    for sub in called.instrs:
        if sub.opcode == "fusion":      # nested fusion (rare)
            f, m = _fusion_flops(sub, module, notes)
        else:
            f, m = _plain_op_flops(sub, called)
        flops += f
        matmul += m
    return flops, matmul


# ---------------------------------------------------------------------------
# trip counts & kernel collection
# ---------------------------------------------------------------------------

def _trip_count(module: HloModule, cond_name: str) -> Optional[int]:
    """Recover a while loop's static trip count from its condition
    computation: jax's scan/fori lower to `iter < K` (or <=) against a
    constant, starting at 0 — the shape every registered program's
    loops have. None when the pattern doesn't match."""
    comp = module.computations.get(cond_name)
    if comp is None:
        return None
    root = comp.root
    if root is None or root.opcode != "compare":
        return None
    const = None
    for opn in root.operands:
        src = comp.by_name.get(opn)
        if src is not None and src.opcode == "constant":
            try:
                const = int(src.literal.strip())
            except ValueError:
                return None
    if const is None:
        return None
    direction = root.attrs.get("direction", "LT")
    if direction == "LT":
        return max(const, 1)
    if direction == "LE":
        return max(const + 1, 1)
    return None


def _kernel_class(ins: Instr, bytes_total: int) -> str:
    if ins.opcode == "fusion":
        return {"kLoop": "loop", "kInput": "input", "kOutput": "output",
                "kCustom": "custom"}.get(ins.attrs.get("kind", ""),
                                         "loop")
    if ins.opcode == "dot":
        return "dot"
    # convolution FLOPs are not modeled (no conv on any registered hot
    # path) — class it by traffic, never as a 0-FLOP "dot" that would
    # hollow out the matmul-share ratchet; collect_kernels notes it
    if ins.opcode in _COLLECTIVES:
        return "collective"
    if ins.opcode == "custom-call":
        return "custom-call"
    if bytes_total < SCALAR_GLUE_BYTES:
        return "scalar"
    return "unfused"


def collect_kernels(module: HloModule, comp_name: Optional[str] = None,
                    trip: int = 1, path: str = "",
                    notes: Optional[List[str]] = None) -> List[KernelCost]:
    """Walk a computation (default: entry) and return every kernel,
    recursing through while bodies (x trip count), call targets, and
    the costlier conditional branch."""
    if notes is None:
        notes = []
    comp = module.computations.get(comp_name or module.entry)
    if comp is None:
        return []
    out: List[KernelCost] = []
    for ins in comp.instrs:
        op = ins.opcode
        if op in _SKIP:
            continue
        if op == "while":
            body = ins.attrs.get("body", "")
            t = _trip_count(module, ins.attrs.get("condition", ""))
            if t is None:
                notes.append(
                    f"while {ins.name}: trip count not statically "
                    "recoverable — body counted once")
                t = 1
            out.extend(collect_kernels(
                module, body, trip * t,
                f"{path}/{ins.name}" if path else ins.name, notes))
            continue
        if op == "call":
            out.extend(collect_kernels(
                module, ins.attrs.get("to_apply", ""), trip,
                f"{path}/{ins.name}" if path else ins.name, notes))
            continue
        if op == "conditional":
            branches = []
            if "branch_computations" in ins.attrs:
                branches = re.findall(r"[\w.\-]+",
                                      ins.attrs["branch_computations"])
            else:
                branches = [ins.attrs.get(k) for k in
                            ("true_computation", "false_computation")
                            if ins.attrs.get(k)]
            best: List[KernelCost] = []
            for b in branches:
                cand = collect_kernels(
                    module, b, trip,
                    f"{path}/{ins.name}" if path else ins.name, notes)
                if sum(k.hbm_bytes for k in cand) >= \
                        sum(k.hbm_bytes for k in best):
                    best = cand
            out.extend(best)
            continue
        if op == "convolution":
            notes.append(f"convolution {ins.name}: FLOPs not modeled "
                         "(traffic counted; matmul share excludes it)")
        reads = sum(shape_bytes(src.shapes)
                    for src in _operand_shapes(ins, comp))
        writes = shape_bytes(ins.shapes)
        if op == "fusion":
            flops, matmul = _fusion_flops(ins, module, notes)
        else:
            flops, matmul = _plain_op_flops(ins, comp)
        out.append(KernelCost(
            name=ins.name, opcode=op,
            klass=_kernel_class(ins, reads + writes),
            flops=flops * trip, matmul_flops=matmul * trip,
            bytes_read=reads * trip, bytes_written=writes * trip,
            trip=trip, path=path, op_name=ins.attrs.get("op_name", ""),
            operands=tuple(ins.operands)))
    return out


# ---------------------------------------------------------------------------
# program inventory
# ---------------------------------------------------------------------------

def program_cost(hlo_text: str, *, name: str = "program",
                 chip: "str | ChipSpec" = DEFAULT_CHIP,
                 detail: bool = False, top_chains: int = 5) -> dict:
    """The per-program inventory record: FLOPs, HBM bytes, arithmetic
    intensity, roofline time under `chip`, fusion-kind histogram, and
    the ranked top unfused elementwise chains. `detail=True` adds the
    full per-kernel list (big; the CLI's --json report includes it)."""
    from .fusion import fusion_histogram, unfused_chains
    # lazy: program_lint imports HLO_DTYPE_BYTES from this module
    from .program_lint import collective_inventory_from_hlo
    if isinstance(chip, str):
        chip = CHIP_SPECS[chip]
    notes: List[str] = []
    module = parse_hlo_module(hlo_text)
    kernels = collect_kernels(module, notes=notes)
    coll = collective_inventory_from_hlo(hlo_text)
    flops = sum(k.flops for k in kernels)
    matmul = sum(k.matmul_flops for k in kernels)
    reads = sum(k.bytes_read for k in kernels)
    writes = sum(k.bytes_written for k in kernels)
    hbm = reads + writes
    roofline = sum(k.roofline_seconds(chip) for k in kernels)
    chains = unfused_chains(kernels, limit=top_chains)
    rec = {
        "program": name,
        "chip": chip.name,
        "flops": flops,
        "matmul_flops": matmul,
        "matmul_flop_share": round(matmul / flops, 6) if flops else 0.0,
        "bytes_read": reads,
        "bytes_written": writes,
        "hbm_bytes": hbm,
        "arithmetic_intensity": round(flops / hbm, 3) if hbm else 0.0,
        "roofline_seconds": roofline,
        "flop_time_seconds": flops / chip.peak_flops,
        "hbm_time_seconds": hbm / chip.hbm_bandwidth,
        "bound": ("compute" if flops / chip.peak_flops
                  >= hbm / chip.hbm_bandwidth else "bandwidth"),
        "kernel_count": sum(1 for k in kernels if k.klass != "scalar"),
        # per-chip transferred collective bytes (ring accounting,
        # program_lint.collective_inventory_from_hlo) — the quantity
        # the comm_bytes anchor and the collective_bytes budget ratchet
        # gate (ISSUE 17: wire-precision wins must not silently revert)
        "collectives": coll,
        "collective_bytes": sum(v["bytes"] for v in coll.values()),
        "fusion_histogram": fusion_histogram(kernels),
        "top_unfused": chains,
        "notes": notes,
    }
    if detail:
        rec["kernels"] = [k.to_dict(chip) for k in kernels]
    return rec


# ---------------------------------------------------------------------------
# analytic anchors
# ---------------------------------------------------------------------------

def analytic_decode_hbm_bytes(geometry: dict) -> int:
    """Analytic HBM bytes for one engine decode TICK under the CURRENT
    one-hot masked-write regime (the MPK per-layer round-trip
    accounting): each of the `tick_tokens` micro-steps streams every
    weight once (param_bytes) and makes SEVEN full passes over the KV
    cache — the layout/transpose fusion (read + write), the masked
    select itself (read + write), the loop-carry copy XLA materializes
    for the donated cache (read + write), and the attention read:

        tick_tokens * (param_bytes + 7 * kv_cache_bytes)

    The IDEAL regime is 3 passes (attention read + in-place
    read-modify-write) — the 7-pass accounting is what the compiled
    HLO actually does today (PERF.md PR 6 records the inventory), and
    the mega-kernelization campaign's job is to delete the other four.
    The decode_hbm anchor pins modeled/analytic <= 1.15x so an EIGHTH
    pass (an unfused activation chain, a dropped fusion) fails CI; a
    genuine fusion win shrinks modeled bytes and the ratcheted
    hbm_bytes budget is what locks it in."""
    return int(geometry["tick_tokens"]
               * (geometry["param_bytes"]
                  + 7 * geometry["kv_cache_bytes"]))


def analytic_paged_decode_hbm_bytes(geometry: dict) -> int:
    """Analytic HBM bytes for one PAGED engine decode tick (ISSUE 9).

    The paged tick swaps the dense slot rows for page pools plus a
    per-micro-step GATHER into the [N, pages_per_slot * page] view
    attention consumes, so the accounting splits in two:

    - ``kv_cache_bytes`` (the POOL — what HBM actually stores) makes
      FOUR passes: the one-hot page write's read + write and the
      donated-carry copy's read + write. Pool bytes scale with LIVE
      tokens admitted, not slots * max_len — at a pool sized below
      slots * pages_per_slot this is where paging cuts tick traffic.
    - ``kv_view_bytes`` (the gathered view, all layers, k + v) makes
      THREE passes: the gather's write, the attention read, and the
      gather's read side modeled at view size (the parser charges a
      gather's operand at result scale).

        tick_tokens * (param_bytes + 4*pool_bytes + 3*view_bytes)

    The IDEAL regime fuses the gather into attention (1 view pass) and
    writes pages in place (1 pool pass) — the same mega-kernelization
    target the dense anchor documents. The anchor pins modeled <=
    max_ratio of this bound so an extra full-view or full-pool pass
    (a dropped fusion in the gather/write chain) fails CI."""
    return int(geometry["tick_tokens"]
               * (geometry["param_bytes"]
                  + 4 * geometry["kv_cache_bytes"]
                  + 3 * geometry["kv_view_bytes"]))


def analytic_verify_hbm_bytes(geometry: dict) -> int:
    """Analytic HBM bytes for one speculative VERIFY-K dispatch
    (ISSUE 13) — the k-token bound that makes the multi-token tick a
    bandwidth win. The verify program is ONE target forward over the
    [tok, d1..dk] block for every slot: weights stream ONCE and the KV
    cache makes the 7 passes the dense decode micro-step pays (masked
    block write read+write, layout fusion read+write, donated-carry
    copy read+write, attention read) ONCE —

        param_bytes + 7 * kv_cache_bytes

    versus the plain tick's ``tick_tokens * (param_bytes + 7 *
    kv_cache_bytes)``: per EMITTED token the verify dispatch moves up
    to (k+1)x fewer bytes (acceptance decides how much of the bound is
    realized). The measured program sits ~1.27x above this bound: the
    per-row BLOCK write (take_along_axis of the k+1 incoming rows per
    cache position + dense select) materializes its gathered values at
    cache scale — roughly two extra cache passes the S=1 one-hot write
    doesn't pay; the anchor's max_ratio carries that headroom, so one
    MORE full cache pass or weight stream (re-per-tokenizing the
    block) still fails CI."""
    return int(geometry["param_bytes"] + 7 * geometry["kv_cache_bytes"])


# ---------------------------------------------------------------------------
# baseline gate (tools/tpucost_baseline.json)
# ---------------------------------------------------------------------------
#
# Baseline shape:
#   {"version": 1, "chip": "v5lite",
#    "budgets": {"<program>": {"hbm_bytes": N, "kernel_count": N,
#                              "matmul_flop_share_min": 0.x,
#                              "collective_bytes": N}},
#    "anchors": {"<program>": {"kind": "decode_hbm"|"matmul_share_floor"
#                                      |"comm_bytes"|"fusion_hbm",
#                              "max_ratio": 1.15 | "min_share": 0.x |
#                              "baseline_program": "...",
#                              "min_ratio": 3.5 |
#                              "max_kernel_delta": -3}},
#    "notes": {...}}
#
# Budgets RATCHET (hbm_bytes/kernel_count/collective_bytes may only
# stay or shrink, matmul share may only stay or grow) and are rewritten
# wholesale by --update-baseline; anchors are hand-set invariants that
# survive updates — the must_stay_clean idiom, numeric.


def load_cost_baseline(path: str) -> dict:
    import json
    with open(path) as fh:
        base = json.load(fh)
    if not isinstance(base, dict) or "budgets" not in base:
        raise ValueError(f"malformed tpucost baseline {path!r}: needs a "
                         "'budgets' dict (see analysis/hlo_cost.py)")
    return base


def updated_cost_baseline(base: Optional[dict],
                          inventories: Dict[str, dict]) -> dict:
    """Re-pin budgets from this run's measurements; anchors and notes
    survive (accepting a regression in an ANCHORED quantity requires
    editing the anchor by hand — that is the review point)."""
    base = dict(base or {})
    budgets = {}
    for name, inv in sorted(inventories.items()):
        budgets[name] = {
            "hbm_bytes": int(inv["hbm_bytes"]),
            "kernel_count": int(inv["kernel_count"]),
            "matmul_flop_share_min": math.floor(
                inv["matmul_flop_share"] * 1e4) / 1e4,
        }
        # pin what the run measured: inventories always carry
        # collective_bytes (0 for single-chip programs), but a summary
        # from an older report without the field must not grow a gate
        if "collective_bytes" in inv:
            budgets[name]["collective_bytes"] = int(
                inv["collective_bytes"])
    base["budgets"] = budgets
    base.setdefault("anchors", {})
    base.setdefault("notes", {})
    base["version"] = 1
    base.setdefault("chip", DEFAULT_CHIP)
    return base


def check_cost_baseline(inventories: Dict[str, dict],
                        baseline: Optional[dict],
                        live_programs: Sequence[str],
                        geometries: Optional[Dict[str, dict]] = None,
                        require_all: bool = False) -> List[Finding]:
    """Gate the measured inventories. Returns violation findings (empty
    == gate passes): cost-budget for ratchet breaks and unbaselined
    programs, cost-anchor for broken invariants, stale-cost-program for
    baseline entries naming a program the registry no longer has (the
    registry-rename rot check, analogous to stale-quarantine).

    `require_all=True` (a FULL run, not a --programs subset): a live
    baselined program MISSING from the inventories is itself a
    violation — a site silently skipped (device count, builder error
    swallowed upstream) must not read as its anchors passing."""
    findings: List[Finding] = []
    baseline = baseline or {"budgets": {}}
    budgets = baseline.get("budgets", {})
    anchors = baseline.get("anchors", {})
    geometries = geometries or {}
    live = set(live_programs)

    if require_all:
        for prog in sorted((set(budgets) | set(anchors)) & live
                           - set(inventories)):
            findings.append(Finding(
                COST_BUDGET, Severity.ERROR, prog, "not-measured",
                f"live program {prog!r} is baselined but produced no "
                "inventory this run — its budgets/anchors were NOT "
                "checked (skipped build? device count?); a full run "
                "must measure every registered site", {}))

    for section, table in (("budgets", budgets), ("anchors", anchors)):
        for prog in sorted(table):
            if prog not in live:
                findings.append(Finding(
                    STALE_COST_PROGRAM, Severity.ERROR, prog, section,
                    f"baseline {section} entry names {prog!r} but the "
                    "ProgramRegistry has no such program — renamed or "
                    "deleted without re-pinning "
                    "(tools/tpucost.py --update-baseline; anchors move "
                    "by hand)", {}))

    for name, inv in sorted(inventories.items()):
        b = budgets.get(name)
        if b is None:
            findings.append(Finding(
                COST_BUDGET, Severity.WARN, name, "unbaselined",
                f"program {name!r} has no tpucost budget — a newly "
                "registered program must be pinned (review its "
                "inventory, then --update-baseline)",
                {"hbm_bytes": inv["hbm_bytes"]}))
            continue
        hbm_budget = int(b.get("hbm_bytes", 0))
        if inv["hbm_bytes"] > hbm_budget:
            findings.append(Finding(
                COST_BUDGET, Severity.WARN, name, "hbm_bytes",
                f"modeled HBM traffic {inv['hbm_bytes']} exceeds the "
                f"pinned budget {hbm_budget} — a fusion regressed "
                "or new traffic appeared (review, fix, or "
                "--update-baseline)",
                {"measured": inv["hbm_bytes"], "budget": hbm_budget}))
        kern_budget = int(b.get("kernel_count", 0))
        if inv["kernel_count"] > kern_budget:
            findings.append(Finding(
                COST_BUDGET, Severity.WARN, name, "kernel_count",
                f"{inv['kernel_count']} kernels exceed the pinned "
                f"{kern_budget} — XLA split a previously fused "
                "region (more launches, more HBM round-trips)",
                {"measured": inv["kernel_count"],
                 "budget": kern_budget}))
        coll_budget = b.get("collective_bytes")
        if coll_budget is not None \
                and inv.get("collective_bytes", 0) > int(coll_budget):
            findings.append(Finding(
                COST_BUDGET, Severity.WARN, name, "collective_bytes",
                f"per-chip collective bytes "
                f"{inv.get('collective_bytes', 0)} exceed the pinned "
                f"budget {int(coll_budget)} — a collective regressed "
                "to a wider wire dtype or new cross-chip traffic "
                "appeared (review, fix, or --update-baseline)",
                {"measured": inv.get("collective_bytes", 0),
                 "budget": int(coll_budget)}))
        share_min = float(b.get("matmul_flop_share_min", 0.0))
        if inv["matmul_flop_share"] < share_min:
            findings.append(Finding(
                COST_BUDGET, Severity.WARN, name, "matmul_flop_share",
                f"matmul FLOP share {inv['matmul_flop_share']:.4f} "
                f"dropped below the pinned floor {share_min:.4f} — "
                "non-matmul work grew relative to the MXU work that "
                "pays for it",
                {"measured": inv["matmul_flop_share"],
                 "floor": share_min}))

    for name, a in sorted(anchors.items()):
        inv = inventories.get(name)
        if inv is None:
            continue    # partial runs; full runs flagged above
        kind = a.get("kind", "")
        if kind == "decode_hbm":
            geom = geometries.get(name) or {}
            try:
                bound = analytic_decode_hbm_bytes(geom)
            except KeyError:
                findings.append(Finding(
                    COST_ANCHOR, Severity.ERROR, name, "decode_hbm",
                    "decode_hbm anchor needs geometry metadata "
                    "(param_bytes, kv_cache_bytes, tick_tokens) on the "
                    "registered site's BuildResult", {}))
                continue
            ratio = inv["hbm_bytes"] / bound if bound else float("inf")
            if ratio > float(a.get("max_ratio", 1.15)):
                findings.append(Finding(
                    COST_ANCHOR, Severity.ERROR, name, "decode_hbm",
                    f"decode tick models {inv['hbm_bytes']} HBM bytes "
                    f"= {ratio:.3f}x the analytic KV+weight bound "
                    f"{bound} (max {a.get('max_ratio', 1.15)}x) — "
                    "unfused activation traffic crept into the tick",
                    {"measured": inv["hbm_bytes"], "analytic": bound,
                     "ratio": round(ratio, 4)}))
        elif kind == "decode_hbm_paged":
            geom = geometries.get(name) or {}
            try:
                bound = analytic_paged_decode_hbm_bytes(geom)
            except KeyError:
                findings.append(Finding(
                    COST_ANCHOR, Severity.ERROR, name,
                    "decode_hbm_paged",
                    "decode_hbm_paged anchor needs geometry metadata "
                    "(param_bytes, kv_cache_bytes, kv_view_bytes, "
                    "tick_tokens) on the registered site's "
                    "BuildResult", {}))
                continue
            ratio = inv["hbm_bytes"] / bound if bound else float("inf")
            if ratio > float(a.get("max_ratio", 1.15)):
                findings.append(Finding(
                    COST_ANCHOR, Severity.ERROR, name,
                    "decode_hbm_paged",
                    f"paged decode tick models {inv['hbm_bytes']} HBM "
                    f"bytes = {ratio:.3f}x the analytic pool+view "
                    f"bound {bound} (max {a.get('max_ratio', 1.15)}x) "
                    "— an extra full-pool or full-view pass crept "
                    "into the tick",
                    {"measured": inv["hbm_bytes"], "analytic": bound,
                     "ratio": round(ratio, 4)}))
        elif kind == "verify_hbm":
            geom = geometries.get(name) or {}
            try:
                bound = analytic_verify_hbm_bytes(geom)
            except KeyError:
                findings.append(Finding(
                    COST_ANCHOR, Severity.ERROR, name, "verify_hbm",
                    "verify_hbm anchor needs geometry metadata "
                    "(param_bytes, kv_cache_bytes) on the registered "
                    "site's BuildResult", {}))
                continue
            ratio = inv["hbm_bytes"] / bound if bound else float("inf")
            if ratio > float(a.get("max_ratio", 1.15)):
                findings.append(Finding(
                    COST_ANCHOR, Severity.ERROR, name, "verify_hbm",
                    f"verify-k dispatch models {inv['hbm_bytes']} HBM "
                    f"bytes = {ratio:.3f}x the analytic single-pass "
                    f"k-token bound {bound} (max "
                    f"{a.get('max_ratio', 1.15)}x) — an extra weight "
                    "stream or cache pass re-per-tokenized the verify "
                    "block",
                    {"measured": inv["hbm_bytes"], "analytic": bound,
                     "ratio": round(ratio, 4)}))
        elif kind == "comm_bytes":
            # wire-precision invariant (ISSUE 17): this program's
            # per-chip collective bytes must stay at least min_ratio
            # BELOW its full-precision twin's — int8/bf16 collectives
            # silently reverting to f32 payloads is exactly the
            # regression this anchor exists to catch
            ref_name = a.get("baseline_program", "")
            ref = inventories.get(ref_name)
            if ref is None:
                if ref_name in live:
                    continue    # partial run; full runs flag missing
                findings.append(Finding(
                    COST_ANCHOR, Severity.ERROR, name, "comm_bytes",
                    f"comm_bytes anchor references baseline_program "
                    f"{ref_name!r} which the registry does not have — "
                    "fix the baseline", {"baseline_program": ref_name}))
                continue
            mine = int(inv.get("collective_bytes", 0))
            theirs = int(ref.get("collective_bytes", 0))
            min_ratio = float(a.get("min_ratio", 1.0))
            ratio = (theirs / mine) if mine else float("inf")
            if ratio < min_ratio:
                findings.append(Finding(
                    COST_ANCHOR, Severity.ERROR, name, "comm_bytes",
                    f"collective bytes {mine} vs {ref_name}'s {theirs} "
                    f"= {ratio:.2f}x reduction, below the anchored "
                    f"{min_ratio:.2f}x — the quantized collectives "
                    "regressed toward full-precision wire bytes",
                    {"measured": mine, "reference": theirs,
                     "ratio": round(ratio, 4),
                     "min_ratio": min_ratio}))
        elif kind == "matmul_share_floor":
            floor = float(a.get("min_share", 0.0))
            if inv["matmul_flop_share"] < floor:
                findings.append(Finding(
                    COST_ANCHOR, Severity.ERROR, name,
                    "matmul_share_floor",
                    f"matmul FLOP share {inv['matmul_flop_share']:.4f} "
                    f"broke the hand-set anchor floor {floor:.4f}",
                    {"measured": inv["matmul_flop_share"],
                     "floor": floor}))
        elif kind == "fusion_hbm":
            # fused-kernel A/B invariant (ISSUE 19): this program is
            # its baseline_program with a fusion knob ON — its modeled
            # HBM bytes must stay at or below max_ratio of the unfused
            # twin's (the measured win is PINNED, not aspirational),
            # and, when max_kernel_delta is set, its kernel count must
            # not creep back up past baseline + max_kernel_delta
            ref_name = a.get("baseline_program", "")
            ref = inventories.get(ref_name)
            if ref is None:
                if ref_name in live:
                    continue    # partial run; full runs flag missing
                findings.append(Finding(
                    COST_ANCHOR, Severity.ERROR, name, "fusion_hbm",
                    f"fusion_hbm anchor references baseline_program "
                    f"{ref_name!r} which the registry does not have — "
                    "fix the baseline", {"baseline_program": ref_name}))
                continue
            max_ratio = float(a.get("max_ratio", 1.0))
            ratio = (inv["hbm_bytes"] / ref["hbm_bytes"]
                     if ref["hbm_bytes"] else float("inf"))
            if ratio > max_ratio:
                findings.append(Finding(
                    COST_ANCHOR, Severity.ERROR, name, "fusion_hbm",
                    f"fused program models {inv['hbm_bytes']} HBM "
                    f"bytes = {ratio:.4f}x its unfused twin "
                    f"{ref_name}'s {ref['hbm_bytes']} (max "
                    f"{max_ratio:.4f}x) — the fused-kernel win "
                    "regressed",
                    {"measured": inv["hbm_bytes"],
                     "reference": ref["hbm_bytes"],
                     "ratio": round(ratio, 4),
                     "max_ratio": max_ratio}))
            if "max_kernel_delta" in a:
                delta = (int(inv["kernel_count"])
                         - int(ref["kernel_count"]))
                if delta > int(a["max_kernel_delta"]):
                    findings.append(Finding(
                        COST_ANCHOR, Severity.ERROR, name,
                        "fusion_hbm",
                        f"fused program launches {inv['kernel_count']} "
                        f"kernels vs {ref_name}'s "
                        f"{ref['kernel_count']} (delta {delta:+d}, max "
                        f"{int(a['max_kernel_delta']):+d}) — the "
                        "fused chain's kernel-count shrinkage "
                        "regressed",
                        {"measured": inv["kernel_count"],
                         "reference": ref["kernel_count"],
                         "delta": delta,
                         "max_kernel_delta":
                             int(a["max_kernel_delta"])}))
        else:
            # a typo while hand-editing the baseline must not silently
            # DISABLE an invariant — unknown kinds fail loudly
            findings.append(Finding(
                COST_ANCHOR, Severity.ERROR, name, "unknown-kind",
                f"anchor for {name!r} has unknown kind {kind!r} "
                "(valid: decode_hbm, decode_hbm_paged, verify_hbm, "
                "matmul_share_floor, comm_bytes, fusion_hbm) — the "
                "invariant was NOT evaluated; fix the baseline",
                {"kind": kind}))
    return findings
