"""Program linter: walk a jitted program's ClosedJaxpr + lowered
StableHLO and emit structured hazard findings.

The properties checked here are all statically decidable from the
lowered program ("Operator Fusion in XLA: Analysis and Evaluation",
PAPERS.md) — no execution happens. `lint_program` only traces and
lowers (`jax.jit(...).lower()`); the optional collective inventory
additionally compiles, because GSPMD inserts collectives during SPMD
partitioning, AFTER StableHLO — they exist only in the compiled HLO.

Hazard classes (paddle_tpu.analysis.findings codes):
- dtype-promotion: widening float convert_element_type on a non-trivial
  array — silent f32 (or f64) upcasts double HBM traffic on TPU.
- scatter-op / gather-op: scatter is warn (one-hot masked writes beat
  scatter 2.5x on the decode cache hot path — PERF.md PR 2); gather is
  info (embedding lookups are legitimate gathers; the baseline pins the
  accepted count so regressions still trip the gate).
- host-callback: io_callback/pure_callback/debug_callback inside a
  compiled program forces a host round-trip per execution.
- baked-rng-key: a PRNG key captured as a trace-time constant — every
  run replays identical "randomness" (framework/random.py rng_guard
  contract exists precisely to prevent this).
- undonated-buffer: an input whose (shape, dtype) matches an output and
  is big enough to matter, not marked donated — the caller is paying a
  full HBM copy XLA could alias away (train-step params, KV caches).
- collective: inventory info finding per collective kind with count and
  byte estimate (the EQuARX-style audit: know what collectives/dtypes a
  program actually contains before it reaches hardware).
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
from jax import dtypes as _dtypes

from ._util import leaf_labels
from .findings import (BAKED_RNG_KEY, COLLECTIVE, DTYPE_PROMOTION,
                       GATHER_OP, HOST_CALLBACK, SCATTER_OP,
                       UNDONATED_BUFFER, Finding, Severity)
from .hlo_cost import HLO_DTYPE_BYTES as _HLO_DTYPE_BYTES

__all__ = ["lint_program", "collective_inventory_from_hlo"]

# widening float chains flagged by dtype-promotion (narrow -> wider set)
_WIDENS = {
    "bfloat16": ("float32", "float64"),
    "float16": ("float32", "float64"),
    "float32": ("float64",),
}

_CALLBACK_PRIMS = {"pure_callback", "io_callback", "debug_callback",
                   "callback", "outside_call"}

# explicit (shard_map/pmap-level) collective primitives visible in jaxprs
_JAXPR_COLLECTIVES = {"psum", "all_gather", "all_to_all", "ppermute",
                      "pmax", "pmin", "psum_scatter", "reduce_scatter"}

# HLO op names of post-partitioning collectives (compiled programs)
_HLO_COLLECTIVE_RE = re.compile(
    r"=\s*(?P<lhs>[^=]*?)\s*"
    r"(?P<op>all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(?:-start)?\(")

_HLO_SHAPE_RE = re.compile(r"(?P<dt>[a-z]+\d+|pred)\[(?P<dims>[0-9,]*)\]")

# {{0,1},{2,3}} explicit form, the iota form [groups,size]<=[n], or the
# EMPTY form {} (HLO for "all replicas in one group")
_REPLICA_GROUPS_RE = re.compile(
    r"replica_groups=(?:\{\{(?P<first>[0-9, ]*)\}"
    r"|\[(?P<ng>[0-9]+),(?P<gs>[0-9]+)\]<="
    r"|(?P<all>\{\}))")
_NUM_PARTITIONS_RE = re.compile(
    r"\b(?:num_partitions|replica_count)=(\d+)")

def _replica_group_size(line: str, all_devices: int = 1) -> int:
    """Devices per replica group on one collective's HLO line.
    `replica_groups={}` means ALL replicas form one group — the caller
    passes the module's partition/replica count for that case; no
    annotation at all reads as a degenerate single-device group."""
    m = _REPLICA_GROUPS_RE.search(line)
    if m is None:
        return 1
    if m.group("all") is not None:
        return max(all_devices, 1)
    if m.group("gs") is not None:
        return max(int(m.group("gs")), 1)
    first = [x for x in m.group("first").split(",") if x.strip()]
    return max(len(first), 1)


# per-chip transferred fraction of the RESULT bytes for a ring
# algorithm over an n-wide group (the northstar_model.py accounting):
# all-gather's result is the full gathered tensor -> (n-1)/n of it
# moves; reduce-scatter's result is the 1/n shard -> (n-1) x result;
# ring all-reduce = reduce-scatter + all-gather phases; a permute is
# one hop; all-to-all keeps (n-1)/n.
def _xfer_factor(op: str, n: int) -> float:
    if op == "collective-permute":
        return 1.0      # one hop; pairs, not replica groups
    if n <= 1:
        return 0.0      # degenerate self-group: nothing crosses ICI
    return {"all-gather": (n - 1) / n,
            "reduce-scatter": float(n - 1),
            "all-reduce": 2 * (n - 1) / n,
            "all-to-all": (n - 1) / n}.get(op, 1.0)


def _subjaxprs(params: dict):
    """Yield every Jaxpr/ClosedJaxpr nested in an eqn's params (pjit,
    scan, while, cond branches, custom_jvp/vjp, remat, shard_map...)."""
    from jax.core import ClosedJaxpr, Jaxpr
    for v in params.values():
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for x in vals:
            if isinstance(x, ClosedJaxpr):
                yield x.jaxpr, tuple(x.consts)
            elif isinstance(x, Jaxpr):
                yield x, ()


def _walk(jaxpr, consts, path=""):
    """Depth-first (eqn, path) over a jaxpr and all sub-jaxprs; also
    yields ('consts', consts, path) groups so key constants anywhere in
    the nesting are seen."""
    yield ("consts", consts, path)
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        yield ("eqn", eqn, path)
        for sub, sub_consts in _subjaxprs(eqn.params):
            yield from _walk(sub, sub_consts, f"{path}/{name}" if path
                             else name)


def _is_key_const(c) -> bool:
    dt = getattr(c, "dtype", None)
    if dt is not None:
        try:
            if _dtypes.issubdtype(dt, _dtypes.prng_key):
                return True
        except (TypeError, AttributeError):
            pass
    # raw-key form: uint32 vector of 2 (threefry) or 4 (rbg) words
    shape = tuple(getattr(c, "shape", ()) or ())
    return (dt is not None and np.dtype(dt) == np.uint32
            and shape in ((2,), (4,), (1, 2), (1, 4)))


def _aval_nbytes(aval) -> int:
    try:
        return int(np.prod(aval.shape)) * np.dtype(aval.dtype).itemsize
    except (TypeError, ValueError):
        return 0


def collective_inventory_from_hlo(hlo_text: str) -> Dict[str, dict]:
    """Parse compiled-HLO text into {collective-kind: {count, bytes,
    result_bytes, group_size}}. `result_bytes` sums each op's result
    shapes (tuple results of -start forms included); `bytes` is the
    PER-CHIP transferred estimate — result bytes scaled by the ring
    transfer factor for the op's replica-group size (counting groups:
    an 8-wide all-gather moves (n-1)/n of the gathered tensor per chip,
    not the whole result — the ZeRO-2 inventory was overstating every
    entry before groups were counted). `group_size` is the max group
    width seen for the kind (mixed widths keep per-op scaling)."""
    inv: Dict[str, dict] = {}
    # module-wide device count, for empty replica_groups={} (= one
    # all-replica group): max over the HloModule header line's
    # num_partitions / replica_count annotations — the whole first
    # line, since a real-size entry_computation_layout pushes the
    # attribute thousands of chars in
    header = hlo_text[:hlo_text.find("\n")] if "\n" in hlo_text \
        else hlo_text
    all_devices = max((int(n) for n in
                       _NUM_PARTITIONS_RE.findall(header)),
                      default=1)
    for line in hlo_text.splitlines():
        m = _HLO_COLLECTIVE_RE.search(line)
        if m is None:
            continue
        op = m.group("op")
        nbytes = 0
        for sm in _HLO_SHAPE_RE.finditer(line[:m.end("op")]):
            dims = sm.group("dims")
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            nbytes += n * _HLO_DTYPE_BYTES.get(sm.group("dt"), 4)
        group = _replica_group_size(line, all_devices)
        rec = inv.setdefault(op, {"count": 0, "bytes": 0,
                                  "result_bytes": 0, "group_size": 1})
        rec["count"] += 1
        rec["result_bytes"] += nbytes
        rec["bytes"] += int(nbytes * _xfer_factor(op, group))
        rec["group_size"] = max(rec["group_size"], group)
    return inv


def lint_program(name: str, fn, args: Tuple = (), kwargs: Optional[dict]
                 = None, *, compile_collectives: bool = False,
                 donation_bytes_threshold: int = 16 * 1024,
                 promotion_min_elems: int = 128) -> List[Finding]:
    """Lint one jitted program. `fn` may be a `jax.jit` wrapper or a
    plain traceable callable (then it is wrapped un-donated — donation
    findings reflect the wrapper actually passed, so pass the REAL
    program object to audit its donation).

    Only traces/lowers; compiles additionally iff compile_collectives
    (GSPMD materializes collectives post-partitioning)."""
    kwargs = dict(kwargs or {})
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    findings: List[Finding] = []

    closed = jax.make_jaxpr(jitted)(*args, **kwargs)

    promo: Dict[Tuple[str, str], int] = {}
    prim_hits: Dict[str, int] = {}
    jaxpr_colls: Dict[str, dict] = {}
    baked_keys: List[str] = []
    seen_key_const_ids = set()

    for kind, obj, path in _walk(closed.jaxpr, tuple(closed.consts)):
        if kind == "consts":
            for c in obj:
                if id(c) in seen_key_const_ids:
                    continue
                if _is_key_const(c):
                    seen_key_const_ids.add(id(c))
                    baked_keys.append(
                        f"const:{tuple(getattr(c, 'shape', ()) or ())}")
            continue
        eqn = obj
        pname = eqn.primitive.name
        if pname == "convert_element_type":
            src = eqn.invars[0].aval
            dst = eqn.outvars[0].aval
            if (str(src.dtype) in _WIDENS
                    and str(dst.dtype) in _WIDENS[str(src.dtype)]
                    and int(np.prod(src.shape or ()))
                    >= promotion_min_elems):
                promo[(str(src.dtype), str(dst.dtype))] = promo.get(
                    (str(src.dtype), str(dst.dtype)), 0) + 1
        elif pname.startswith("scatter"):
            prim_hits["scatter"] = prim_hits.get("scatter", 0) + 1
        elif pname == "gather":
            prim_hits["gather"] = prim_hits.get("gather", 0) + 1
        elif pname in _CALLBACK_PRIMS:
            prim_hits[pname] = prim_hits.get(pname, 0) + 1
        elif pname in _JAXPR_COLLECTIVES:
            nbytes = sum(_aval_nbytes(v.aval) for v in eqn.outvars)
            rec = jaxpr_colls.setdefault(pname, {"count": 0, "bytes": 0})
            rec["count"] += 1
            rec["bytes"] += nbytes

    for (src, dst), n in sorted(promo.items()):
        findings.append(Finding(
            DTYPE_PROMOTION, Severity.WARN, name, f"{src}->{dst}",
            f"{n} widening convert(s) {src}->{dst} on arrays >= "
            f"{promotion_min_elems} elems — check for unintended "
            f"promotion (weak-type literals, mixed-dtype math)",
            {"count": n}))
    n_scatter = prim_hits.get("scatter", 0)
    if n_scatter:
        findings.append(Finding(
            SCATTER_OP, Severity.WARN, name, "scatter",
            f"{n_scatter} scatter op(s) in compiled program — on the "
            "decode/cache hot path one-hot masked writes are 2.5x "
            "faster (PERF.md, PR 2)", {"count": n_scatter}))
    n_gather = prim_hits.get("gather", 0)
    if n_gather:
        findings.append(Finding(
            GATHER_OP, Severity.INFO, name, "gather",
            f"{n_gather} gather op(s) (embedding lookups are expected; "
            "baseline pins the accepted count)", {"count": n_gather}))
    for cb in sorted(set(prim_hits) & _CALLBACK_PRIMS):
        findings.append(Finding(
            HOST_CALLBACK, Severity.WARN, name, cb,
            f"{prim_hits[cb]} {cb}(s) inside the compiled program — "
            "each execution pays a host round-trip",
            {"count": prim_hits[cb]}))
    for site in sorted(set(baked_keys)):
        findings.append(Finding(
            BAKED_RNG_KEY, Severity.WARN, name, site,
            "PRNG key constant-folded into the program at trace time — "
            "every run replays the same stream; thread the key as an "
            "argument (framework/random.rng_guard contract)", {}))
    for pname, rec in sorted(jaxpr_colls.items()):
        findings.append(Finding(
            COLLECTIVE, Severity.INFO, name, pname,
            f"{rec['count']} {pname} op(s), ~{rec['bytes']} bytes",
            dict(rec)))

    # -- donation audit (lowered StableHLO + args_info) -------------------
    try:
        lowered = jitted.lower(*args, **kwargs)
    except Exception as e:   # pragma: no cover - lowering bugs surface loud
        findings.append(Finding(
            "lint-error", Severity.ERROR, name, "lower",
            f"lowering failed: {type(e).__name__}: {e}", {}))
        return findings
    arg_leaves = jax.tree_util.tree_leaves(lowered.args_info)
    labels = leaf_labels(args, kwargs)
    # output avals from the jaxpr already in hand — a third abstract
    # trace (eval_shape) would double-charge big programs
    out_set = {(tuple(a.shape), str(a.dtype))
               for a in closed.out_avals if hasattr(a, "shape")}
    for i, info in enumerate(arg_leaves):
        aval = getattr(info, "aval", info)
        donated = bool(getattr(info, "donated", False))
        sig = (tuple(aval.shape), str(aval.dtype))
        if (not donated and sig in out_set
                and _aval_nbytes(aval) >= donation_bytes_threshold):
            label = labels[i] if i < len(labels) else f"arg{i}"
            findings.append(Finding(
                UNDONATED_BUFFER, Severity.WARN, name,
                f"{label}:{list(aval.shape)}:{aval.dtype}",
                f"input {label} {sig} matches an output aval and is "
                f"{_aval_nbytes(aval)} bytes but is not donated — the "
                "caller pays a copy XLA could alias away "
                "(donate_argnums)", {"nbytes": _aval_nbytes(aval)}))

    if compile_collectives:
        try:
            hlo = lowered.compile().as_text()
        except Exception as e:
            findings.append(Finding(
                "lint-error", Severity.ERROR, name, "compile",
                f"compile for collective inventory failed: "
                f"{type(e).__name__}: {e}", {}))
            return findings
        for op, rec in sorted(collective_inventory_from_hlo(hlo).items()):
            findings.append(Finding(
                COLLECTIVE, Severity.INFO, name, op,
                f"{rec['count']} {op} op(s), ~{rec['bytes']} bytes "
                f"transferred per chip per step (group size "
                f"{rec['group_size']})", dict(rec)))
    return findings
