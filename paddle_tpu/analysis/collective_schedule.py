"""AOT collective-schedule analysis: did the gathers overlap compute?

The ZeRO-3 chunked-overlap schedule (PAPERS.md arXiv 2112.01075; wired
in distributed/parallel_step.py `gather_chained`) claims each layer
group's weight all-gather rides UNDER the previous group's matmuls
instead of front-loading every gather before the first layer. Two
statically-checkable artifacts back that claim, both available without
running a step:

1. the LOWERED (StableHLO) text carries one `optimization_barrier` per
   gathered leaf — the token chain that makes gather i+1 data-dependent
   on gather i's output, so NO backend scheduler can front-load or
   combine the per-layer gathers (`gather_chain_links`);
2. the COMPILED module is scheduled (`is_scheduled=true`), so the
   printed instruction order of the entry computation IS the execution
   schedule — `gather_overlap_report` measures how the all-gathers
   actually interleave with compute, and `diff_schedules` puts two
   programs' schedules side by side (the fp32-GSPMD vs quantized A/B
   that tools/bench_collectives.py prints).
"""
from __future__ import annotations

import re
from typing import Dict, List, Tuple

__all__ = ["schedule_events", "gather_overlap_report",
           "gather_chain_links", "diff_schedules"]

_ENTRY_RE = re.compile(r"ENTRY [^{]*\{(.*?)\n\}", re.S)
_COLLECTIVE_RE = re.compile(
    r"=\s*\S+\s+(all-reduce|all-gather|reduce-scatter|"
    r"collective-permute|all-to-all)(?:-start)?\(")
# compute carriers in a post-fusion entry computation: fusions, raw
# dots/convolutions that escaped fusion, and backend custom-calls
# (oneDNN/oneAPI matmul on CPU, Mosaic kernels on TPU)
_COMPUTE_RE = re.compile(
    r"=\s*\S+\s+(fusion|dot|convolution|custom-call)\(")


def schedule_events(compiled_hlo: str) -> List[Tuple[int, str]]:
    """Ordered (instruction_index, kind) events of the entry
    computation, kind one of the collective op names or "compute".
    Only meaningful on a SCHEDULED module (compiled `.as_text()` with
    `is_scheduled=true`) where printed order is execution order; raises
    ValueError otherwise so a caller can't silently diff garbage."""
    if "is_scheduled=true" not in compiled_hlo.split("\n", 1)[0]:
        raise ValueError(
            "schedule_events needs a scheduled module (compiled "
            "HloModule with is_scheduled=true); got unscheduled text — "
            "pass compiled.as_text(), not lowered StableHLO")
    m = _ENTRY_RE.search(compiled_hlo)
    if m is None:
        raise ValueError("no ENTRY computation found in HLO text")
    events: List[Tuple[int, str]] = []
    for i, line in enumerate(m.group(1).splitlines()):
        cm = _COLLECTIVE_RE.search(line)
        if cm is not None:
            events.append((i, cm.group(1)))
            continue
        if _COMPUTE_RE.search(line):
            events.append((i, "compute"))
    return events


def gather_overlap_report(compiled_hlo: str) -> Dict[str, object]:
    """Interleaving metrics for the all-gathers in a scheduled program:

    - n_gathers / n_compute: event counts;
    - interleaved_gaps: adjacent gather pairs with >= 1 compute event
      scheduled BETWEEN them — a front-loaded schedule (every gather
      in one block before the first matmul) scores 0;
    - max_gather_run: longest run of gathers with no compute between
      (combined/front-loaded schedules show one run == n_gathers);
    - front_loaded: True when every gather precedes every compute.
    """
    events = schedule_events(compiled_hlo)
    kinds = [k for _, k in events]
    n_g = sum(1 for k in kinds if k == "all-gather")
    n_c = sum(1 for k in kinds if k == "compute")
    gaps = 0
    run = 0
    max_run = 0
    since_last_gather_compute = False
    seen_gather = False
    for k in kinds:
        if k == "all-gather":
            if seen_gather and since_last_gather_compute:
                gaps += 1
                run = 1
            else:
                run += 1
            max_run = max(max_run, run)
            seen_gather = True
            since_last_gather_compute = False
        elif k == "compute":
            since_last_gather_compute = True
    first_c = kinds.index("compute") if n_c else len(kinds)
    last_g = (len(kinds) - 1 - kinds[::-1].index("all-gather")) \
        if n_g else -1
    return {"n_gathers": n_g, "n_compute": n_c,
            "interleaved_gaps": gaps, "max_gather_run": max_run,
            "front_loaded": bool(n_g and n_c and last_g < first_c)}


def gather_chain_links(lowered_text: str) -> int:
    """Number of optimization_barrier chain links in LOWERED text (the
    `.lower(...).as_text()` StableHLO) — one per stage-3 gathered leaf
    when the chunked-overlap schedule is active, 0 in fp32/GSPMD mode.
    XLA legally drops the barriers after scheduling, so this must read
    the pre-optimization module."""
    return len(re.findall(r"\boptimization_barrier\b", lowered_text))


def diff_schedules(compiled_a: str, compiled_b: str,
                   label_a: str = "a", label_b: str = "b") -> Dict:
    """Side-by-side schedule comparison of two compiled programs:
    per-kind event counts plus each side's gather_overlap_report."""
    out: Dict[str, object] = {}
    for label, text in ((label_a, compiled_a), (label_b, compiled_b)):
        counts: Dict[str, int] = {}
        for _, k in schedule_events(text):
            counts[k] = counts.get(k, 0) + 1
        out[label] = {"counts": counts,
                      "overlap": gather_overlap_report(text)}
    return out
