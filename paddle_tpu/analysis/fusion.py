"""Fusion inventory: what XLA fused, and what it left on the table.

"Operator Fusion in XLA" (PAPERS.md 2301.13062) observes that XLA's
fusion decisions are recoverable from the optimized HLO and frequently
leave adjacent elementwise work in separate kernels — each such
boundary pays a full write + re-read of the intermediate through HBM.
This module consumes the kernel list `hlo_cost.collect_kernels`
produces and answers two questions per program:

- `fusion_histogram`: how many kernels of each class (loop/input/
  output/custom fusions, standalone dots, collectives, custom calls,
  unfused elementwise, scalar glue) — the kernel_count budget in
  tools/tpucost_baseline.json ratchets on the non-scalar total;
- `unfused_chains`: the ranked "top unfused HBM traffic" report —
  connected chains of fusable kernels (elementwise ops and kLoop
  fusions) that consume each other's outputs yet were compiled as
  separate kernels. `intermediate_bytes` is the traffic crossing the
  chain's internal boundaries once; fusing the chain deletes up to
  2x that (the producer's write and the consumer's re-read). These
  chains are the candidate list every later Pallas-kernel /
  mega-kernelization PR starts from.
"""
from __future__ import annotations

from typing import Dict, List

from .hlo_cost import KernelCost, _DATA_MOVEMENT, _ELEMWISE

__all__ = ["fusion_histogram", "unfused_chains", "FUSABLE_CLASSES"]

# kernel classes that a loop fusion could in principle absorb
FUSABLE_CLASSES = ("loop", "unfused")


def fusion_histogram(kernels: List[KernelCost]) -> Dict[str, int]:
    """Kernel count per class. Classes: loop/input/output/custom
    (fusion kinds), dot, collective, custom-call, unfused (standalone
    elementwise/data-movement big enough to matter), scalar (glue)."""
    hist: Dict[str, int] = {}
    for k in kernels:
        hist[k.klass] = hist.get(k.klass, 0) + 1
    return hist


def _fusable(k: KernelCost) -> bool:
    if k.klass not in FUSABLE_CLASSES:
        return False
    if k.klass == "loop":
        return True
    # class "unfused": only elementwise-shaped ops join a chain
    return (k.opcode in _ELEMWISE or k.opcode in _DATA_MOVEMENT
            or k.opcode == "reduce")


def unfused_chains(kernels: List[KernelCost], limit: int = 5
                   ) -> List[dict]:
    """Rank producer->consumer chains of fusable kernels left unfused.

    Kernels are grouped by (path, trip) — a chain never crosses a loop
    boundary (XLA could not fuse across it either). Within a group,
    every edge where a fusable kernel reads a fusable kernel's output
    is an avoidable HBM round-trip; connected components with >= 2
    kernels are chains, ranked by the bytes crossing their internal
    edges (already trip-multiplied by collect_kernels)."""
    # nodes are keyed (path, trip, name): XLA deduplicates identical
    # computations, so two loops can emit kernels with the SAME
    # instruction names — bare-name keys would merge chains across the
    # loop boundaries the grouping exists to respect
    by_key: Dict[tuple, KernelCost] = {}
    groups: Dict[tuple, List[KernelCost]] = {}
    for k in kernels:
        if _fusable(k):
            by_key[(k.path, k.trip, k.name)] = k
            groups.setdefault((k.path, k.trip), []).append(k)

    parent: Dict[tuple, tuple] = {}

    def find(x: tuple) -> tuple:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    edge_list: List[tuple] = []
    for (path, trip), ks in groups.items():
        names = {k.name for k in ks}
        for k in ks:
            kk = (path, trip, k.name)
            parent.setdefault(kk, kk)
            for opn in set(k.operands):
                if opn in names and opn != k.name:
                    ok = (path, trip, opn)
                    parent.setdefault(ok, ok)
                    edge_list.append((ok, kk))

    for a, b in edge_list:
        ra, rb = find(a), find(b)
        if ra != rb:
            parent[ra] = rb

    comps: Dict[tuple, List[tuple]] = {}
    for key in parent:
        comps.setdefault(find(key), []).append(key)
    # one write per DISTINCT producer: a fan-out intermediate (one
    # producer, two chain consumers) crosses HBM once, not per edge
    boundary: Dict[tuple, int] = {}
    for a in {a for a, _ in edge_list}:
        r = find(a)
        boundary[r] = boundary.get(r, 0) + by_key[a].bytes_written

    chains = []
    for root, members in comps.items():
        if len(members) < 2:
            continue
        ks = [by_key[m] for m in members]
        ops = sorted({k.op_name for k in ks if k.op_name})
        chains.append({
            "kernels": sorted(m[2] for m in members),
            "kernel_count": len(members),
            "ops": ops,
            "path": ks[0].path,
            "trip": ks[0].trip,
            "intermediate_bytes": boundary.get(root, 0),
            "savable_bytes": 2 * boundary.get(root, 0),
        })
    chains.sort(key=lambda c: c["intermediate_bytes"], reverse=True)
    return chains[:limit]
