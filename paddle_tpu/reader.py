"""paddle.reader — legacy reader-creator combinators.

Parity: python/paddle/reader/decorator.py (cache, map_readers, buffered,
shuffle, chain, compose, firstn, xmap_readers). A "reader" is a no-arg
callable returning an iterable of samples; these combinators compose
them. Kept because classic paddle data pipelines (paddle.batch(
paddle.reader.shuffle(train(), 500), 32)) still appear in user code; new
code should use paddle_tpu.io.DataLoader.
"""
from __future__ import annotations

import itertools
import queue
import random
import threading

__all__ = ["cache", "map_readers", "buffered", "shuffle", "chain",
           "compose", "firstn", "xmap_readers"]


def cache(reader):
    """Cache all samples in memory on first pass (decorator.py:45)."""
    all_data = []
    loaded = [False]

    def new_reader():
        if not loaded[0]:
            fresh = list(reader())   # commit only on a complete pass
            all_data.extend(fresh)
            loaded[0] = True
        return iter(all_data)
    return new_reader


def map_readers(func, *readers):
    """Sample-wise func over zipped readers (decorator.py:84)."""
    def new_reader():
        for items in zip(*[r() for r in readers]):
            yield func(*items)
    return new_reader


def shuffle(reader, buf_size):
    """Buffered shuffle (decorator.py:125)."""
    def new_reader():
        buf = []
        for s in reader():
            buf.append(s)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            random.shuffle(buf)
            yield from buf
    return new_reader


def chain(*readers):
    """Concatenate readers (decorator.py:174)."""
    def new_reader():
        return itertools.chain(*[r() for r in readers])
    return new_reader


def compose(*readers, **kwargs):
    """Zip readers into flat tuples (decorator.py:238).
    check_alignment=True (default) raises if lengths differ."""
    check_alignment = kwargs.pop("check_alignment", True)
    if kwargs:
        raise TypeError(f"unexpected kwargs {sorted(kwargs)}")

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def new_reader():
        its = [iter(r()) for r in readers]
        while True:
            outs, stops = [], 0
            for it in its:
                try:
                    outs.append(make_tuple(next(it)))
                except StopIteration:
                    stops += 1
            if stops == len(its):
                return
            if stops:
                if check_alignment:
                    raise RuntimeError(
                        "compose: readers have different lengths")
                return
            yield tuple(itertools.chain(*outs))
    return new_reader


def buffered(reader, size):
    """Background-thread prefetch buffer (decorator.py buffered).
    Source errors re-raise in the consumer, not silently truncate."""
    end = object()

    def new_reader():
        q = queue.Queue(maxsize=size)

        def feed():
            try:
                for s in reader():
                    q.put(s)
                q.put(end)
            except BaseException as e:   # ship the error to the consumer
                q.put(e)

        t = threading.Thread(target=feed, daemon=True)
        t.start()
        while True:
            s = q.get()
            if s is end:
                return
            if isinstance(s, BaseException):
                raise s
            yield s
    return new_reader


def firstn(reader, n):
    """First n samples (decorator.py firstn)."""
    def new_reader():
        return itertools.islice(reader(), n)
    return new_reader


def xmap_readers(mapper, reader, process_num, buffer_size,
                 order=False):
    """Parallel map over samples with a thread pool, bounded by
    buffer_size in-flight items (decorator.py xmap_readers). Results are
    yielded in submission order (deterministic either way here — the
    thread pool preserves nothing else worth exposing)."""
    from concurrent.futures import ThreadPoolExecutor

    def new_reader():
        with ThreadPoolExecutor(process_num) as pool:
            it = iter(reader())   # ONE pass over the source
            pending = [pool.submit(mapper, s)
                       for s in itertools.islice(it, buffer_size)]
            for s in it:
                done = pending.pop(0)
                pending.append(pool.submit(mapper, s))
                yield done.result()
            for f in pending:
                yield f.result()
    return new_reader
