"""paddle.geometric parity — graph message passing + segment ops.

Reference: python/paddle/geometric/ (math.py segment ops :23-192,
message_passing/send_recv.py send_u_recv:35 / send_ue_recv:178 /
send_uv). The reference backs these with dedicated CUDA
graph_send_recv kernels; on TPU they are jax.ops.segment_* reductions —
one gather + one scatter-reduce, jittable and differentiable, with
`out_size`/num_segments static so XLA keeps shapes fixed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..autograd.tape import apply
from ..core.tensor import Tensor

__all__ = ["segment_sum", "segment_mean", "segment_min", "segment_max",
           "send_u_recv", "send_ue_recv", "send_uv", "reindex_graph",
           "sample_neighbors", "reindex_heter_graph"]


def _num_segments(segment_ids, explicit=None):
    if explicit is not None:
        return int(explicit)
    ids = segment_ids.value if isinstance(segment_ids, Tensor) \
        else jnp.asarray(segment_ids)
    return int(jax.device_get(jnp.max(ids))) + 1 if ids.size else 0


def _segment(op):
    def run(data, segment_ids, name=None):
        n = _num_segments(segment_ids)

        def f(d, ids):
            return _reduce(d, ids, op, n)

        return apply(f, data, segment_ids, _op_name=f"segment_{op}")

    return run


segment_sum = _segment("sum")
segment_mean = _segment("mean")
segment_min = _segment("min")
segment_max = _segment("max")
segment_sum.__doc__ = "Parity: geometric/math.py:23"
segment_mean.__doc__ = "Parity: geometric/math.py:78"
segment_min.__doc__ = "Parity: geometric/math.py:136"
segment_max.__doc__ = "Parity: geometric/math.py:192"


def _reduce(gathered, dst, reduce_op, n):
    if reduce_op == "sum":
        return jax.ops.segment_sum(gathered, dst, num_segments=n)
    if reduce_op == "mean":
        s = jax.ops.segment_sum(gathered, dst, num_segments=n)
        cnt = jax.ops.segment_sum(
            jnp.ones_like(dst, gathered.dtype), dst, num_segments=n)
        shape = (-1,) + (1,) * (gathered.ndim - 1)
        return s / jnp.maximum(cnt, 1).reshape(shape)
    if reduce_op in ("min", "max"):
        fn = jax.ops.segment_min if reduce_op == "min" \
            else jax.ops.segment_max
        out = fn(gathered, dst, num_segments=n)
        # empty segments: reference returns 0; jax fills +/-inf (float)
        # or the iinfo sentinel (int)
        if jnp.issubdtype(gathered.dtype, jnp.floating):
            return jnp.where(jnp.isfinite(out), out, 0)
        info = jnp.iinfo(gathered.dtype)
        sentinel = info.max if reduce_op == "min" else info.min
        return jnp.where(out == sentinel, 0, out)
    raise ValueError(
        f"reduce_op should be sum/mean/min/max, but got {reduce_op}")


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Parity: geometric/message_passing/send_recv.py:35 — gather rows of
    x at src_index, scatter-reduce them at dst_index."""
    xv = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    n = int(out_size) if out_size is not None else xv.shape[0]

    def f(d, src, dst):
        return _reduce(d[src], dst, reduce_op, n)

    return apply(f, x, src_index, dst_index, _op_name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Parity: send_recv.py:178 — combine gathered node features with
    edge features (add/sub/mul/div) before the scatter-reduce."""
    xv = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    n = int(out_size) if out_size is not None else xv.shape[0]
    ops = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
           "div": jnp.divide}
    if message_op not in ops:
        raise ValueError(
            f"message_op should be add/sub/mul/div, but got {message_op}")

    def f(d, e, src, dst):
        msg = d[src]
        ev = e
        while ev.ndim < msg.ndim:
            ev = ev[..., None]
        return _reduce(ops[message_op](msg, ev), dst, reduce_op, n)

    return apply(f, x, y, src_index, dst_index, _op_name="send_ue_recv")


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Parity: send_recv.py send_uv — per-edge message from both
    endpoint features (no reduce)."""
    ops = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
           "div": jnp.divide}
    if message_op not in ops:
        raise ValueError(
            f"message_op should be add/sub/mul/div, but got {message_op}")

    def f(xv, yv, src, dst):
        return ops[message_op](xv[src], yv[dst])

    return apply(f, x, y, src_index, dst_index, _op_name="send_uv")


def reindex_graph(x, neighbors, count, name=None):
    """Parity: geometric/reindex.py reindex_graph — compress node ids to
    a contiguous range (host-side; output sizes are data-dependent)."""
    import numpy as np
    xs = np.asarray(x.value if isinstance(x, Tensor) else x)
    nb = np.asarray(neighbors.value if isinstance(neighbors, Tensor)
                    else neighbors)
    uniq = dict((int(v), i) for i, v in enumerate(xs))
    next_id = len(uniq)
    out_nodes = list(xs)
    reindexed = np.empty_like(nb)
    for i, v in enumerate(nb):
        v = int(v)
        if v not in uniq:
            uniq[v] = next_id
            next_id += 1
            out_nodes.append(v)
        reindexed[i] = uniq[v]
    cnt = np.asarray(count.value if isinstance(count, Tensor) else count)
    dst = np.repeat(np.arange(len(cnt)), cnt)
    return (Tensor(jnp.asarray(reindexed), stop_gradient=True),
            Tensor(jnp.asarray(dst), stop_gradient=True),
            Tensor(jnp.asarray(np.asarray(out_nodes)),
                   stop_gradient=True))


def sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                     eids=None, return_eids=False, perm_buffer=None,
                     name=None):
    """Parity: geometric/sampling/neighbors.py sample_neighbors — for
    each input node, sample up to sample_size neighbors from the CSC
    graph (row, colptr). Host-side (data-dependent output size)."""
    import numpy as np
    r = np.asarray(row.value if isinstance(row, Tensor) else row)
    cp = np.asarray(colptr.value if isinstance(colptr, Tensor) else colptr)
    nodes = np.asarray(input_nodes.value
                       if isinstance(input_nodes, Tensor) else input_nodes)
    ev = np.asarray(eids.value if isinstance(eids, Tensor) else eids) \
        if eids is not None else None
    out_nb, out_cnt, out_eids = [], [], []
    rng = np.random.RandomState(0 if perm_buffer is not None else None)
    for n in nodes.reshape(-1):
        lo, hi = int(cp[n]), int(cp[n + 1])
        deg = hi - lo
        if sample_size < 0 or deg <= sample_size:
            sel = np.arange(lo, hi)
        else:
            sel = lo + rng.choice(deg, size=sample_size, replace=False)
        out_nb.append(r[sel])
        out_cnt.append(len(sel))
        if ev is not None:
            out_eids.append(ev[sel])
    nb = Tensor(jnp.asarray(np.concatenate(out_nb) if out_nb
                            else np.empty(0, r.dtype)), stop_gradient=True)
    cnt = Tensor(jnp.asarray(np.asarray(out_cnt, np.int32)),
                 stop_gradient=True)
    if return_eids:
        assert ev is not None, "return_eids requires eids"
        return nb, cnt, Tensor(jnp.asarray(np.concatenate(out_eids)),
                               stop_gradient=True)
    return nb, cnt


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Parity: geometric/reindex.py reindex_heter_graph — reindex a
    heterogeneous neighborhood (list of per-edge-type neighbor arrays)
    into one contiguous id space shared across types; returns
    CONCATENATED (reindex_src, reindex_dst, out_nodes) like the
    reference."""
    import numpy as np
    xs = np.asarray(x.value if isinstance(x, Tensor) else x)
    uniq = {int(v): i for i, v in enumerate(xs)}
    out_nodes = list(xs)
    src_all, dst_all = [], []
    for nb, cnt in zip(neighbors, count):
        nbv = np.asarray(nb.value if isinstance(nb, Tensor) else nb)
        cv = np.asarray(cnt.value if isinstance(cnt, Tensor) else cnt)
        re_nb = np.empty_like(nbv)
        for i, v in enumerate(nbv):
            v = int(v)
            if v not in uniq:
                uniq[v] = len(out_nodes)
                out_nodes.append(v)
            re_nb[i] = uniq[v]
        src_all.append(re_nb)
        dst_all.append(np.repeat(np.arange(len(cv)), cv))
    src = np.concatenate(src_all) if src_all else np.empty(0, np.int64)
    dst = np.concatenate(dst_all) if dst_all else np.empty(0, np.int64)
    return (Tensor(jnp.asarray(src), stop_gradient=True),
            Tensor(jnp.asarray(dst), stop_gradient=True),
            Tensor(jnp.asarray(np.asarray(out_nodes)), stop_gradient=True))
