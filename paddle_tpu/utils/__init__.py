"""paddle.utils parity — cpp_extension (out-of-tree native ops),
unique_name, deprecated helpers (reference: python/paddle/utils/)."""
from __future__ import annotations

import functools
import warnings

from . import cpp_extension  # noqa: F401

__all__ = ["cpp_extension", "unique_name", "deprecated", "try_import",
           "run_check", "require_version"]


class _UniqueNameGenerator:
    def __init__(self):
        self._ids = {}

    def __call__(self, prefix: str) -> str:
        i = self._ids.get(prefix, 0)
        self._ids[prefix] = i + 1
        return f"{prefix}_{i}"


_generator = _UniqueNameGenerator()


class unique_name:
    """Parity: paddle.utils.unique_name.generate."""

    @staticmethod
    def generate(prefix: str) -> str:
        return _generator(prefix)


def deprecated(update_to="", since="", reason=""):
    """Parity: paddle.utils.deprecated decorator."""

    def wrap(fn):
        @functools.wraps(fn)
        def inner(*args, **kwargs):
            warnings.warn(
                f"{fn.__name__} is deprecated since {since}"
                + (f", use {update_to} instead" if update_to else "")
                + (f" ({reason})" if reason else ""),
                DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        return inner

    return wrap


def try_import(module_name: str):
    """Parity: paddle.utils.try_import."""
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            f"Failed to import {module_name!r}; it is an optional "
            f"dependency of this feature") from e


def run_check():
    """Parity: paddle.utils.run_check — one tiny computation on the
    attached device."""
    import jax
    import jax.numpy as jnp
    x = jnp.ones((128, 128))
    y = (x @ x).sum()
    dev = jax.devices()[0]
    print(f"PaddleTPU works! device={dev.device_kind} "
          f"platform={dev.platform} result={float(y)}")


def require_version(min_version: str, max_version=None):
    """Parity: paddle.utils.require_version — check the installed
    framework version against [min_version, max_version]."""
    from .. import __version__ as ver

    def parse(v):
        return tuple(int(p) for p in str(v).split(".")[:3] if p.isdigit())

    cur = parse(ver)
    if parse(min_version) > cur:
        raise Exception(
            f"installed version {ver} < required minimum {min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"installed version {ver} > allowed maximum {max_version}")
