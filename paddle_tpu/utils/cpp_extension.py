"""C++ extension loader — out-of-tree native ops.

Parity: python/paddle/utils/cpp_extension/ (load/CppExtension) and the
phi C-ABI (paddle/phi/capi/capi.h). The reference JIT-compiles a
custom-op .so against paddle/extension.h; here the contract is a plain
C ABI (no framework headers needed) and the compiled function runs
host-side, bridged into traced programs with jax.pure_callback — the
right TPU split: device kernels belong in Pallas (framework/custom_op),
C++ belongs on the host (IO, CPU pre/post-processing, legacy numerics).

C ABI (float32):

    extern "C" void <op>(const float* const* ins,
                         const long long* const* shapes,
                         const int* ndims, int n_ins, float* out);

    // optional gradient: last input is the output cotangent, writes one
    // grad buffer per ORIGINAL input
    extern "C" void <op>_grad(const float* const* ins,
                              const long long* const* shapes,
                              const int* ndims, int n_ins,
                              float* const* grad_outs);
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Callable, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..autograd.tape import apply

__all__ = ["load", "CppExtension", "get_build_directory"]

_ARGTYPES = [
    ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
    ctypes.POINTER(ctypes.POINTER(ctypes.c_longlong)),
    ctypes.POINTER(ctypes.c_int),
    ctypes.c_int,
]


def get_build_directory(override: Optional[str] = None) -> str:
    d = override or os.environ.get("PADDLE_EXTENSION_DIR") or os.path.join(
        tempfile.gettempdir(), "paddle_tpu_extensions")
    os.makedirs(d, exist_ok=True)
    return d


def _compile(name: str, sources: Sequence[str], extra_cxx_flags,
             build_directory: Optional[str] = None) -> str:
    tag = hashlib.sha1()
    for src in sources:
        with open(src, "rb") as f:
            tag.update(f.read())
    tag.update(" ".join(extra_cxx_flags or []).encode())
    out = os.path.join(get_build_directory(build_directory),
                       f"lib{name}_{tag.hexdigest()[:12]}.so")
    if not os.path.exists(out):
        # build to a temp name and rename: a killed/concurrent build must
        # never leave a truncated .so behind the cache check
        tmp = out + f".tmp{os.getpid()}"
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
               *(extra_cxx_flags or []), *sources, "-o", tmp]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"cpp_extension build failed:\n{' '.join(cmd)}\n"
                f"{proc.stderr}")
        os.replace(tmp, out)
    return out


def _marshal(arrays):
    arrs = [np.ascontiguousarray(np.asarray(a, np.float32))
            for a in arrays]
    ins = (ctypes.POINTER(ctypes.c_float) * len(arrs))(*[
        a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)) for a in arrs])
    shape_bufs = [np.asarray(a.shape, np.longlong) for a in arrs]
    shapes = (ctypes.POINTER(ctypes.c_longlong) * len(arrs))(*[
        s.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong))
        for s in shape_bufs])
    ndims = (ctypes.c_int * len(arrs))(*[a.ndim for a in arrs])
    return arrs, shape_bufs, ins, shapes, ndims


class CppExtension:
    """A loaded extension library. `call` runs an exported op as a
    framework op (eager and under jit via pure_callback); gradients use
    the `<op>_grad` export when present."""

    def __init__(self, name: str, lib_path: str):
        self.name = name
        self._path = lib_path
        self._lib = ctypes.CDLL(lib_path)

    def _fn(self, op_name, grad=False):
        try:
            fn = getattr(self._lib, op_name + ("_grad" if grad else ""))
        except AttributeError:
            return None
        if grad:
            fn.argtypes = _ARGTYPES + [
                ctypes.POINTER(ctypes.POINTER(ctypes.c_float))]
        else:
            fn.argtypes = _ARGTYPES + [ctypes.POINTER(ctypes.c_float)]
        fn.restype = None
        return fn

    def call(self, op_name: str, *tensors, out_shape=None,
             out_dtype=jnp.float32):
        """Run `op_name` on the inputs; out_shape defaults to the first
        input's shape (elementwise convention)."""
        fwd = self._fn(op_name)
        if fwd is None:
            raise AttributeError(
                f"{self._path} exports no symbol {op_name!r}")
        grad_fn = self._fn(op_name, grad=True)

        def host_fwd(*arrays):
            arrs, _sb, ins, shapes, ndims = _marshal(arrays)
            shape = tuple(out_shape) if out_shape is not None \
                else arrs[0].shape
            out = np.zeros(shape, np.float32)
            fwd(ins, shapes, ndims, len(arrs),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
            # the C ABI is float32; honor the promised callback dtype
            return out.astype(np.dtype(out_dtype), copy=False)

        def host_bwd(*arrays_and_ct):
            arrs, _sb, ins, shapes, ndims = _marshal(arrays_and_ct)
            n_orig = len(arrs) - 1
            grads = [np.zeros(a.shape, np.float32)
                     for a in arrs[:n_orig]]
            gptrs = (ctypes.POINTER(ctypes.c_float) * n_orig)(*[
                g.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
                for g in grads])
            grad_fn(ins, shapes, ndims, len(arrs), gptrs)
            return tuple(grads)

        def make_callback(*xs):
            shape = tuple(out_shape) if out_shape is not None \
                else xs[0].shape
            spec = jax.ShapeDtypeStruct(shape, out_dtype)
            return jax.pure_callback(host_fwd, spec, *xs, vmap_method=None)

        if grad_fn is not None:
            core = jax.custom_vjp(make_callback)

            def fwd_rule(*xs):
                return make_callback(*xs), xs

            def bwd_rule(res, ct):
                specs = tuple(jax.ShapeDtypeStruct(x.shape, x.dtype)
                              for x in res)
                return jax.pure_callback(host_bwd, specs, *res, ct,
                                         vmap_method=None)

            core.defvjp(fwd_rule, bwd_rule)
        else:
            core = make_callback

        return apply(core, *tensors, _op_name=f"{self.name}.{op_name}")

    def __getattr__(self, op_name):
        if op_name.startswith("_"):
            raise AttributeError(op_name)

        def bound(*tensors, **kw):
            return self.call(op_name, *tensors, **kw)

        return bound


def load(name: str, sources: Sequence[str], extra_cxx_flags=None,
         extra_include_paths: Optional[Sequence[str]] = None,
         build_directory: Optional[str] = None, verbose: bool = False):
    """Parity: utils/cpp_extension.load — JIT-compile C++ sources and
    return the loaded extension."""
    flags = list(extra_cxx_flags or [])
    for inc in extra_include_paths or []:
        flags.append(f"-I{inc}")
    lib = _compile(name, sources, flags, build_directory)
    if verbose:
        print(f"[cpp_extension] {name} -> {lib}")
    return CppExtension(name, lib)


def CUDAExtension(sources, *args, **kwargs):
    """Parity: utils.cpp_extension.CUDAExtension — no CUDA toolchain in a
    TPU build; .cu sources cannot compile here."""
    raise NotImplementedError(
        "CUDAExtension requires nvcc; this is a TPU build — write the op "
        "as a jnp/pallas composition (framework.custom_op) or build a CPU "
        "C++ op with CppExtension")


def setup(name=None, ext_modules=None, **kwargs):
    """Parity: utils.cpp_extension.setup — the setuptools ceremony
    collapses onto `load()`. Accepts the ported patterns: an already-
    loaded CppExtension, a {"sources": [...]} mapping, or anything with a
    `.sources` attribute (the reference's Extension objects)."""
    exts = ext_modules if isinstance(ext_modules, (list, tuple)) else \
        ([ext_modules] if ext_modules is not None else [])
    built = []
    for i, ext in enumerate(exts):
        if isinstance(ext, CppExtension):
            built.append(ext)
            continue
        sources = (ext.get("sources") if isinstance(ext, dict)
                   else getattr(ext, "sources", None))
        if not sources:
            raise TypeError(
                "setup() expects CppExtension instances or objects with "
                f"a 'sources' list, got {type(ext)}")
        ext_name = (ext.get("name") if isinstance(ext, dict)
                    else getattr(ext, "name", None)) or name or f"ext{i}"
        built.append(load(ext_name, sources))
    return built


__all__ += ["CUDAExtension", "setup"]
