"""LLaMA decoder family — BASELINE.json config 4 (LLaMA-13B, TP+PP).

Capability parity: the reference trains LLaMA-class models through Fleet
hybrid parallelism (SURVEY.md §3.4; model code lives in PaddleNLP driven by
mpu/mp_layers.py + PipelineLayer). TPU-first re-design on the same TP
layer library as GPT:

- mp: q/k/v/gate/up projections are ColumnParallelLinear, o/down are
  RowParallelLinear (Megatron layout, one GSPMD allreduce per block pair);
- GQA: num_kv_heads < num_heads supported; kv heads are broadcast to query
  heads right before attention (XLA fuses the expand into the kernel);
- RoPE is applied to q/k on the full (pre-sp-shard) sequence;
- sp: ring attention dispatch when the "sp" mesh axis is real;
- pp: LlamaPipelineForCausalLM stacks blocks over the pp axis.

All matmul-heavy compute is bfloat16-friendly; norms/softmax accumulate in
fp32 (rms_norm upcasts internally).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp

from .. import tensor as T
from ..autograd.tape import apply
from ..distributed import mesh as mesh_mod
from ..distributed.meta_parallel import (ColumnParallelLinear, LayerDesc,
                                         PipelineLayer, RowParallelLinear,
                                         VocabParallelEmbedding)
from ..distributed.sequence_parallel import ring_attention
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer_base import Layer
from ..nn import Linear, RMSNorm

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM",
           "LlamaPipelineForCausalLM", "llama_tiny", "llama_7b", "llama_13b"]


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: Optional[int] = None  # None -> MHA
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    rms_eps: float = 1e-6
    initializer_range: float = 0.02
    # rematerialize each block in backward (jax.checkpoint) — scan path
    recompute: bool = False
    # remat policy for the scanned stack: "full" (save nothing) or
    # "dots" (save matmul outputs, recompute only elementwise)
    recompute_policy: str = "full"
    # compile the block stack as ONE lax.scan over [L, ...]-stacked params
    # (models/scanned.py ScannedStack) — depth-independent HLO
    scan_layers: bool = False
    # when >0, forward (no-cache path) returns (hidden, lm_weight) and
    # training uses fused_loss_fn (F.fused_linear_cross_entropy)
    fused_loss_chunk: int = 0

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads


def llama_tiny(**kw):
    return LlamaConfig(vocab_size=256, hidden_size=64, intermediate_size=176,
                       num_layers=4, num_heads=4, num_kv_heads=2,
                       max_seq_len=128, **kw)


def llama_7b(**kw):
    return LlamaConfig(hidden_size=4096, intermediate_size=11008,
                       num_layers=32, num_heads=32, **kw)


def llama_13b(**kw):
    return LlamaConfig(hidden_size=5120, intermediate_size=13824,
                       num_layers=40, num_heads=40, **kw)


from .gpt import _sp_active, cached_attention


def _rope(q, k, theta: float, offset=None):
    """Apply rotary position embedding to q/k ([B, S, H, D]); `offset`
    shifts the absolute positions (decode with KV cache) — a scalar, or
    a [B] vector of per-row offsets (continuous-batching slots)."""
    def f(qv, kv, *off):
        D = qv.shape[-1]
        S = qv.shape[1]
        half = D // 2
        freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
        pos = jnp.arange(S, dtype=jnp.float32)
        if off:
            o = jnp.asarray(off[0], jnp.float32)
            if o.ndim == 1:                     # per-row -> [B, S]
                pos = pos[None, :] + o[:, None]
            else:
                pos = pos + o
        ang = pos[..., None] * freqs            # [S, half] or [B, S, half]
        if ang.ndim == 2:
            cos = jnp.cos(ang)[None, :, None, :]   # [1, S, 1, half]
            sin = jnp.sin(ang)[None, :, None, :]
        else:
            cos = jnp.cos(ang)[:, :, None, :]      # [B, S, 1, half]
            sin = jnp.sin(ang)[:, :, None, :]

        def rot(x):
            # interleaved-pairs convention: (x0, x1) -> (x0 c - x1 s,
            # x1 c + x0 s); computed in fp32, cast back
            xf = x.astype(jnp.float32)
            x0 = xf[..., 0::2]
            x1 = xf[..., 1::2]
            r0 = x0 * cos - x1 * sin
            r1 = x1 * cos + x0 * sin
            out = jnp.stack([r0, r1], axis=-1).reshape(x.shape)
            return out.astype(x.dtype)

        return rot(qv), rot(kv)

    if offset is not None:
        return apply(f, q, k, offset, _op_name="rope")
    return apply(f, q, k, _op_name="rope")


class LlamaAttention(Layer):
    """Causal self-attention with RoPE and GQA, TP-sharded heads."""

    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        h, nh, nkv = cfg.hidden_size, cfg.num_heads, cfg.kv_heads
        if h % nh:
            raise ValueError("hidden_size % num_heads != 0")
        if nh % nkv:
            raise ValueError("num_heads % num_kv_heads != 0")
        self.num_heads = nh
        self.kv_heads = nkv
        self.head_dim = h // nh
        self.theta = cfg.rope_theta
        init = I.Normal(0.0, cfg.initializer_range)
        self.q_proj = ColumnParallelLinear(h, nh * self.head_dim,
                                           weight_attr=init, has_bias=False,
                                           gather_output=False)
        self.k_proj = ColumnParallelLinear(h, nkv * self.head_dim,
                                           weight_attr=init, has_bias=False,
                                           gather_output=False)
        self.v_proj = ColumnParallelLinear(h, nkv * self.head_dim,
                                           weight_attr=init, has_bias=False,
                                           gather_output=False)
        self.o_proj = RowParallelLinear(nh * self.head_dim, h,
                                        weight_attr=init, has_bias=False,
                                        input_is_parallel=True)

    def forward(self, x, cache=None, pos=None):
        B, S, _ = x.shape
        hd, nh, nkv = self.head_dim, self.num_heads, self.kv_heads
        q = T.reshape(self.q_proj(x), [B, S, nh, hd])
        k = T.reshape(self.k_proj(x), [B, S, nkv, hd])
        v = T.reshape(self.v_proj(x), [B, S, nkv, hd])
        q, k = _rope(q, k, self.theta, offset=pos)
        if cache is not None:
            # caches keep nkv heads; cached_attention broadcasts for GQA
            ctx, kc, vc = cached_attention(q, k, v, cache[0], cache[1],
                                           pos)
            return self.o_proj(
                T.reshape(ctx, [B, S, nh * hd])), (kc, vc)
        if nkv != nh:
            rep = nh // nkv
            k = T.repeat_interleave(k, rep, axis=2)
            v = T.repeat_interleave(v, rep, axis=2)
        if _sp_active():
            ctx = ring_attention(q, k, v, causal=True)
        else:
            ctx, _ = F.flash_attention(q, k, v, causal=True,
                                       training=self.training)
        return self.o_proj(T.reshape(ctx, [B, S, nh * hd]))


class LlamaMLP(Layer):
    """SwiGLU: down(silu(gate(x)) * up(x))."""

    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        h, m = cfg.hidden_size, cfg.intermediate_size
        init = I.Normal(0.0, cfg.initializer_range)
        self.gate_proj = ColumnParallelLinear(h, m, weight_attr=init,
                                              has_bias=False,
                                              gather_output=False)
        self.up_proj = ColumnParallelLinear(h, m, weight_attr=init,
                                            has_bias=False,
                                            gather_output=False)
        self.down_proj = RowParallelLinear(m, h, weight_attr=init,
                                           has_bias=False,
                                           input_is_parallel=True)

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaBlock(Layer):
    """Pre-RMSNorm block (the unit the pipeline stacks)."""

    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.input_layernorm = RMSNorm(cfg.hidden_size, cfg.rms_eps)
        self.self_attn = LlamaAttention(cfg)
        self.post_attention_layernorm = RMSNorm(cfg.hidden_size, cfg.rms_eps)
        self.mlp = LlamaMLP(cfg)

    def forward(self, x, cache=None, pos=None):
        if cache is not None:
            att, cache = self.self_attn(self.input_layernorm(x), cache,
                                        pos)
            x = x + att
            x = x + self.mlp(self.post_attention_layernorm(x))
            return x, cache
        x = x + self.self_attn(self.input_layernorm(x))
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x


class LlamaModel(Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.embed_tokens = VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size,
            weight_attr=I.Normal(0.0, cfg.initializer_range))
        if cfg.scan_layers:
            from .scanned import ScannedStack
            self.blocks = ScannedStack(lambda: LlamaBlock(cfg),
                                       cfg.num_layers,
                                       cfg.initializer_range,
                                       recompute=cfg.recompute,
                                       recompute_policy=cfg.recompute_policy)
        else:
            self.blocks = []
            for i in range(cfg.num_layers):
                blk = LlamaBlock(cfg)
                self.add_sublayer(f"block_{i}", blk)
                self.blocks.append(blk)
        self.norm = RMSNorm(cfg.hidden_size, cfg.rms_eps)

    def forward(self, ids, caches=None, pos=None):
        if ids.shape[-1] > self.cfg.max_seq_len:
            raise ValueError(
                f"sequence length {ids.shape[-1]} exceeds max_seq_len "
                f"{self.cfg.max_seq_len}")
        x = self.embed_tokens(ids)
        if caches is not None:
            if self.cfg.scan_layers:
                x, new_caches = self.blocks.forward_cached(x, caches, pos)
                return self.norm(x), new_caches
            new_caches = []
            for blk, c in zip(self.blocks, caches):
                x, c = blk(x, c, pos)
                new_caches.append(c)
            return self.norm(x), new_caches
        if self.cfg.scan_layers:
            return self.norm(self.blocks(x))
        if self.cfg.recompute and self.training:
            from ..distributed.recompute import recompute as _rc
            for blk in self.blocks:
                x = _rc(blk, x, policy=self.cfg.recompute_policy)
        else:
            for blk in self.blocks:
                x = blk(x)
        return self.norm(x)


class LlamaForCausalLM(Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.llama = LlamaModel(cfg)
        self.lm_head = Linear(cfg.hidden_size, cfg.vocab_size,
                              weight_attr=I.Normal(
                                  0.0, cfg.initializer_range),
                              bias_attr=False)

    def forward(self, ids, caches=None, pos=None):
        if caches is not None:
            x, caches = self.llama(ids, caches, pos)
            return self.lm_head(x), caches
        x = self.llama(ids)
        if self.cfg.fused_loss_chunk and self.training:
            # training-perf contract: hand (hidden, lm_weight [H, V]) to
            # fused_loss_fn so the logits never materialize (gated on
            # self.training so eval() callers always get logits)
            return x, self.lm_head.weight
        return self.lm_head(x)

    def make_loss_fn(self):
        from .gpt import GPTForCausalLM
        return GPTForCausalLM.make_loss_fn(self)

    def new_cache(self, batch_size: int, max_len: int, dtype="bfloat16"):
        """Per-layer (k, v) caches [B, max_len, n_kv_heads, hd]; stacked
        (k_stack, v_stack) for scan_layers models; dtype "int8" selects
        the dynamically-quantized cache (quantized_kv_cache)."""
        from .generation import new_kv_caches
        cfg = self.cfg
        hd = cfg.hidden_size // cfg.num_heads
        return new_kv_caches(cfg.num_layers, batch_size, max_len,
                             cfg.kv_heads, hd, dtype, cfg.scan_layers)

    def new_paged_cache(self, num_pages: int, page_size: int,
                        dtype="bfloat16"):
        """Per-layer (k, v) page pools for the paged serving engine
        (GQA: pools keep n_kv_heads; cached_attention broadcasts)."""
        from .generation import new_paged_kv_caches
        cfg = self.cfg
        hd = cfg.hidden_size // cfg.num_heads
        return new_paged_kv_caches(cfg.num_layers, num_pages, page_size,
                                   cfg.kv_heads, hd, dtype,
                                   cfg.scan_layers)

    def generate(self, input_ids, max_new_tokens=32, **kw):
        from .generation import generate
        return generate(self, input_ids, max_new_tokens, **kw)

    # next-token shift identical to GPT's
    @staticmethod
    def loss_fn(logits, labels):
        from .gpt import GPTForCausalLM
        return GPTForCausalLM.loss_fn(logits, labels)

    @staticmethod
    def fused_loss_fn(outputs, labels, chunk_size=512):
        from .gpt import GPTForCausalLM
        return GPTForCausalLM.fused_loss_fn(outputs, labels,
                                            chunk_size=chunk_size)


class _EmbedStage(Layer):
    def __init__(self, cfg):
        super().__init__()
        self.max_seq_len = cfg.max_seq_len
        self.embed = VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size,
            weight_attr=I.Normal(0.0, cfg.initializer_range))

    def forward(self, ids):
        if ids.shape[-1] > self.max_seq_len:
            raise ValueError(
                f"sequence length {ids.shape[-1]} exceeds max_seq_len "
                f"{self.max_seq_len}")
        return self.embed(ids)


class _HeadStage(Layer):
    def __init__(self, cfg):
        super().__init__()
        self.norm = RMSNorm(cfg.hidden_size, cfg.rms_eps)
        self.head = Linear(cfg.hidden_size, cfg.vocab_size,
                           weight_attr=I.Normal(0.0, cfg.initializer_range),
                           bias_attr=False)

    def forward(self, x):
        return self.head(self.norm(x))


class LlamaPipelineForCausalLM(PipelineLayer):
    """LLaMA arranged for the in-program pipeline schedule (config 4)."""

    def __init__(self, cfg: LlamaConfig, num_stages: Optional[int] = None,
                 recompute_interval: int = 0,
                 num_micro: Optional[int] = None, interleave: int = 1):
        self.cfg = cfg
        super().__init__(
            layers=[LayerDesc(_EmbedStage, cfg)]
            + [LayerDesc(LlamaBlock, cfg) for _ in range(cfg.num_layers)]
            + [LayerDesc(_HeadStage, cfg)],
            num_stages=num_stages,
            loss_fn=LlamaForCausalLM.loss_fn,
            recompute_interval=recompute_interval,
            recompute_policy=cfg.recompute_policy,
            num_micro=num_micro, interleave=interleave)
