"""GPT decoder family — the flagship pretraining model.

Capability parity: the reference trains GPT-3-scale models through Fleet
hybrid parallelism (SURVEY.md §3.4 north-star path; model code lives in
PaddleNLP, driven by the fleet TP layers mpu/mp_layers.py and
PipelineLayer). This is a TPU-first implementation of the same model
family, wired for every mesh axis at once:

- mp: qkv/mlp-in are ColumnParallelLinear, out-proj/mlp-out are
  RowParallelLinear, embeddings are VocabParallel (one GSPMD allreduce per
  block pair, Megatron layout over the innermost ICI axis);
- sp: attention dispatches to ring_attention when the "sp" axis is real
  (exceeds the reference — it has no sequence parallelism, §5.7);
- pp: GPTPipelineForCausalLM arranges the same blocks as a PipelineLayer
  (stacked params, in-program microbatch ring schedule);
- dp/sharding: batch sharding + ZeRO slot sharding come from
  ParallelTrainStep, orthogonal to the model.

All matmul-heavy compute is bfloat16-friendly (use amp.auto_cast or
Layer.bfloat16()); attention/log-softmax accumulate in fp32.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from .. import tensor as T
from ..distributed import mesh as mesh_mod
from ..distributed.meta_parallel import (ColumnParallelLinear, LayerDesc,
                                         PipelineLayer, RowParallelLinear,
                                         VocabParallelEmbedding)
from ..distributed.sequence_parallel import ring_attention
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer_base import Layer
from ..nn import Dropout, Embedding, LayerNorm, Linear

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM",
           "GPTPipelineForCausalLM", "gpt_tiny", "gpt_125m", "gpt_1p3b",
           "gpt_6p7b"]


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    ffn_mult: int = 4
    dropout: float = 0.0
    tie_embeddings: bool = True
    use_moe: bool = False
    moe_experts: int = 8
    initializer_range: float = 0.02


def gpt_tiny(**kw):
    return GPTConfig(vocab_size=256, hidden_size=64, num_layers=4,
                     num_heads=4, max_seq_len=128, **kw)


def gpt_125m(**kw):
    return GPTConfig(hidden_size=768, num_layers=12, num_heads=12, **kw)


def gpt_1p3b(**kw):
    return GPTConfig(hidden_size=2048, num_layers=24, num_heads=16,
                     max_seq_len=2048, **kw)


def gpt_6p7b(**kw):
    return GPTConfig(hidden_size=4096, num_layers=32, num_heads=32,
                     max_seq_len=2048, **kw)


def _sp_active() -> bool:
    mesh = mesh_mod.get_mesh(create_default=False)
    return mesh is not None and mesh.shape.get("sp", 1) > 1


class GPTAttention(Layer):
    """Causal self-attention, TP-sharded heads, sp-aware dispatch."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        h, nh = cfg.hidden_size, cfg.num_heads
        if h % nh:
            raise ValueError("hidden_size % num_heads != 0")
        self.num_heads = nh
        self.head_dim = h // nh
        init = I.Normal(0.0, cfg.initializer_range)
        self.qkv = ColumnParallelLinear(h, 3 * h, weight_attr=init,
                                        gather_output=False)
        self.out_proj = RowParallelLinear(h, h, weight_attr=init,
                                          input_is_parallel=True)
        self.dropout = Dropout(cfg.dropout)

    def forward(self, x):
        B, S, H = x.shape
        qkv = self.qkv(x)                       # [B, S, 3H] (mp-sharded)
        # contiguous last-dim slices + free reshapes (the 5-D
        # reshape-then-slice forced real relayout copies, ~5ms/step on the
        # 125M bench); values identical: [3H] is laid out [q(H);k(H);v(H)]
        hd, nh = self.head_dim, self.num_heads
        H3 = qkv.shape[-1]
        H = H3 // 3
        q = T.reshape(T.slice(qkv, [2], [0], [H]), [B, S, nh, hd])
        k = T.reshape(T.slice(qkv, [2], [H], [2 * H]), [B, S, nh, hd])
        v = T.reshape(T.slice(qkv, [2], [2 * H], [3 * H]), [B, S, nh, hd])
        if _sp_active():
            ctx = ring_attention(q, k, v, causal=True)
        else:
            ctx, _ = F.flash_attention(q, k, v, causal=True,
                                       training=self.training)
        ctx = T.reshape(ctx, [B, S, H])
        return self.dropout(self.out_proj(ctx))


class GPTMLP(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        h = cfg.hidden_size
        init = I.Normal(0.0, cfg.initializer_range)
        self.fc_in = ColumnParallelLinear(h, cfg.ffn_mult * h,
                                          weight_attr=init,
                                          gather_output=False)
        self.fc_out = RowParallelLinear(cfg.ffn_mult * h, h,
                                        weight_attr=init,
                                        input_is_parallel=True)
        self.dropout = Dropout(cfg.dropout)

    def forward(self, x):
        return self.dropout(self.fc_out(F.gelu(self.fc_in(x))))


class GPTBlock(Layer):
    """Pre-LN transformer block (the unit the pipeline stacks)."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln_1 = LayerNorm(cfg.hidden_size)
        self.attn = GPTAttention(cfg)
        self.ln_2 = LayerNorm(cfg.hidden_size)
        if cfg.use_moe:
            from ..distributed.moe import MoELayer
            self.mlp = MoELayer(cfg.hidden_size,
                                cfg.ffn_mult * cfg.hidden_size,
                                cfg.moe_experts)
        else:
            self.mlp = GPTMLP(cfg)

    def forward(self, x):
        x = x + self.attn(self.ln_1(x))
        x = x + self.mlp(self.ln_2(x))
        return x


class GPTEmbeddings(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        init = I.Normal(0.0, cfg.initializer_range)
        self.word_embeddings = VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size, weight_attr=init)
        self.position_embeddings = Embedding(
            cfg.max_seq_len, cfg.hidden_size, weight_attr=init)
        self.dropout = Dropout(cfg.dropout)

    def forward(self, ids):
        S = ids.shape[-1]
        max_len = self.position_embeddings.num_embeddings
        if S > max_len:
            raise ValueError(
                f"sequence length {S} exceeds max_seq_len {max_len}")
        pos = T.arange(0, S, dtype="int64")
        x = self.word_embeddings(ids) + self.position_embeddings(pos)
        return self.dropout(x)


class GPTModel(Layer):
    """Decoder stack without head. Parity role: GPTModel in the reference
    ecosystem driven through fleet (SURVEY.md §3.4)."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = GPTEmbeddings(cfg)
        self.blocks = []
        for i in range(cfg.num_layers):
            blk = GPTBlock(cfg)
            self.add_sublayer(f"block_{i}", blk)
            self.blocks.append(blk)
        self.ln_f = LayerNorm(cfg.hidden_size)

    def forward(self, ids):
        x = self.embeddings(ids)
        for blk in self.blocks:
            x = blk(x)
        return self.ln_f(x)


class GPTForCausalLM(Layer):
    """LM head on top; loss = causal LM cross-entropy.

    lm head is tied to the (vocab-parallel) embedding when
    cfg.tie_embeddings — the sharded logits matmul then feeds the
    ParallelCrossEntropy-style fp32 softmax inside F.cross_entropy.
    """

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.gpt = GPTModel(cfg)
        if not cfg.tie_embeddings:
            self.lm_head = Linear(cfg.hidden_size, cfg.vocab_size,
                                  weight_attr=I.Normal(
                                      0.0, cfg.initializer_range),
                                  bias_attr=False)

    def forward(self, ids):
        x = self.gpt(ids)
        if self.cfg.tie_embeddings:
            w = self.gpt.embeddings.word_embeddings.weight
            return T.matmul(x, T.transpose(w, [1, 0]))
        return self.lm_head(x)

    @staticmethod
    def loss_fn(logits, labels):
        """Next-token prediction: logits at position i predict labels[i+1]
        (callers pass labels=input_ids; the shift happens here)."""
        V = logits.shape[-1]
        shifted_logits = T.slice(logits, [1], [0], [logits.shape[1] - 1])
        shifted_labels = T.slice(labels, [1], [1], [labels.shape[1]])
        return T.mean(F.cross_entropy(
            T.reshape(shifted_logits, [-1, V]),
            T.reshape(shifted_labels, [-1])))


class _EmbedStage(Layer):
    def __init__(self, cfg):
        super().__init__()
        self.emb = GPTEmbeddings(cfg)

    def forward(self, ids):
        return self.emb(ids)


class _HeadStage(Layer):
    def __init__(self, cfg):
        super().__init__()
        self.ln_f = LayerNorm(cfg.hidden_size)
        self.head = Linear(cfg.hidden_size, cfg.vocab_size,
                           weight_attr=I.Normal(0.0, cfg.initializer_range),
                           bias_attr=False)

    def forward(self, x):
        return self.head(self.ln_f(x))


class GPTPipelineForCausalLM(PipelineLayer):
    """The same GPT arranged for pipeline parallelism.

    Parity: PipelineLayer GPT arrangements in the reference test suite
    (unittests/collective/fleet/hybrid_parallel_pp_transformer.py). Blocks
    stack over the pp axis; embeddings/head run as prologue/epilogue (so
    tying across stages is not used here — reference PP GPT uses
    SharedLayerDesc; with one global program the head stays a separate
    Linear for homogeneity).
    """

    def __init__(self, cfg: GPTConfig, num_stages: Optional[int] = None,
                 recompute_interval: int = 0,
                 num_micro: Optional[int] = None, interleave: int = 1):
        self.cfg = cfg
        super().__init__(
            layers=[LayerDesc(_EmbedStage, cfg)]
            + [LayerDesc(GPTBlock, cfg) for _ in range(cfg.num_layers)]
            + [LayerDesc(_HeadStage, cfg)],
            num_stages=num_stages,
            loss_fn=GPTForCausalLM.loss_fn,
            recompute_interval=recompute_interval,
            num_micro=num_micro, interleave=interleave)
