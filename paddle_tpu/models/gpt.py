"""GPT decoder family — the flagship pretraining model.

Capability parity: the reference trains GPT-3-scale models through Fleet
hybrid parallelism (SURVEY.md §3.4 north-star path; model code lives in
PaddleNLP, driven by the fleet TP layers mpu/mp_layers.py and
PipelineLayer). This is a TPU-first implementation of the same model
family, wired for every mesh axis at once:

- mp: qkv/mlp-in are ColumnParallelLinear, out-proj/mlp-out are
  RowParallelLinear, embeddings are VocabParallel (one GSPMD allreduce per
  block pair, Megatron layout over the innermost ICI axis);
- sp: attention dispatches to ring_attention when the "sp" axis is real
  (exceeds the reference — it has no sequence parallelism, §5.7);
- pp: GPTPipelineForCausalLM arranges the same blocks as a PipelineLayer
  (stacked params, in-program microbatch ring schedule);
- dp/sharding: batch sharding + ZeRO slot sharding come from
  ParallelTrainStep, orthogonal to the model.

All matmul-heavy compute is bfloat16-friendly (use amp.auto_cast or
Layer.bfloat16()); attention/log-softmax accumulate in fp32.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp

import jax

from .. import tensor as T
from ..core.tensor import Tensor
from ..jit.functional import functional_call
from ..distributed import mesh as mesh_mod
from ..distributed.meta_parallel import (ColumnParallelLinear, LayerDesc,
                                         PipelineLayer, RowParallelLinear,
                                         VocabParallelEmbedding)
from ..distributed.sequence_parallel import ring_attention
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer_base import Layer
from ..nn import Dropout, Embedding, LayerNorm, Linear
from .scanned import ScannedStack

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM",
           "GPTPipelineForCausalLM", "gpt_tiny", "gpt_125m", "gpt_1p3b",
           "gpt_6p7b"]


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    ffn_mult: int = 4
    dropout: float = 0.0
    tie_embeddings: bool = True
    use_moe: bool = False
    moe_experts: int = 8
    initializer_range: float = 0.02
    # rematerialize each block's activations in backward (jax.checkpoint;
    # parity: fleet recompute_interval=1 over the decoder stack)
    recompute: bool = False
    # remat policy for the scanned stack: "full" (save nothing) or
    # "dots" (save matmul outputs, recompute only elementwise)
    recompute_policy: str = "full"
    # compile the block stack as ONE lax.scan over [L, ...]-stacked params
    # instead of L unrolled copies — O(1) HLO in depth (GPTScannedBlocks)
    scan_layers: bool = False
    # when >0, forward (no-cache path) returns (hidden, lm_weight) instead
    # of logits and training uses fused_loss_fn — the LM-head projection
    # streams through F.fused_linear_cross_entropy in chunks of this many
    # tokens, so the [tokens, vocab] logits never materialize in HBM
    fused_loss_chunk: int = 0


def gpt_tiny(**kw):
    return GPTConfig(vocab_size=256, hidden_size=64, num_layers=4,
                     num_heads=4, max_seq_len=128, **kw)


def gpt_125m(**kw):
    return GPTConfig(hidden_size=768, num_layers=12, num_heads=12, **kw)


def gpt_1p3b(**kw):
    return GPTConfig(hidden_size=2048, num_layers=24, num_heads=16,
                     max_seq_len=2048, **kw)


def gpt_6p7b(**kw):
    return GPTConfig(hidden_size=4096, num_layers=32, num_heads=32,
                     max_seq_len=2048, **kw)


def _sp_active() -> bool:
    mesh = mesh_mod.get_mesh(create_default=False)
    return mesh is not None and mesh.shape.get("sp", 1) > 1


# re-export: incremental-decode attention now lives beside the flash
# kernel (generic serving infrastructure, not GPT-specific)
from ..nn.functional.flash_attention import cached_attention  # noqa: E402
from .generation import new_kv_caches as _new_cache  # noqa: E402


class GPTAttention(Layer):
    """Causal self-attention, TP-sharded heads, sp-aware dispatch."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        h, nh = cfg.hidden_size, cfg.num_heads
        if h % nh:
            raise ValueError("hidden_size % num_heads != 0")
        self.num_heads = nh
        self.head_dim = h // nh
        init = I.Normal(0.0, cfg.initializer_range)
        self.qkv = ColumnParallelLinear(h, 3 * h, weight_attr=init,
                                        gather_output=False)
        self.out_proj = RowParallelLinear(h, h, weight_attr=init,
                                          input_is_parallel=True)
        self.dropout = Dropout(cfg.dropout)

    def _qkv(self, x):
        B, S, _ = x.shape
        qkv = self.qkv(x)                       # [B, S, 3H] (mp-sharded)
        # contiguous last-dim slices + free reshapes (the 5-D
        # reshape-then-slice forced real relayout copies, ~5ms/step on the
        # 125M bench); values identical: [3H] is laid out [q(H);k(H);v(H)]
        hd, nh = self.head_dim, self.num_heads
        H = qkv.shape[-1] // 3
        q = T.reshape(T.slice(qkv, [2], [0], [H]), [B, S, nh, hd])
        k = T.reshape(T.slice(qkv, [2], [H], [2 * H]), [B, S, nh, hd])
        v = T.reshape(T.slice(qkv, [2], [2 * H], [3 * H]), [B, S, nh, hd])
        return q, k, v

    def forward(self, x, cache=None, pos=None):
        B, S, H = x.shape
        q, k, v = self._qkv(x)
        if cache is not None:
            ctx, kc, vc = cached_attention(q, k, v, cache[0], cache[1],
                                           pos)
            return self.dropout(self.out_proj(
                T.reshape(ctx, [B, S, H]))), (kc, vc)
        if _sp_active():
            ctx = ring_attention(q, k, v, causal=True)
        else:
            ctx, _ = F.flash_attention(q, k, v, causal=True,
                                       training=self.training)
        ctx = T.reshape(ctx, [B, S, H])
        return self.dropout(self.out_proj(ctx))


class GPTMLP(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        h = cfg.hidden_size
        init = I.Normal(0.0, cfg.initializer_range)
        self.fc_in = ColumnParallelLinear(h, cfg.ffn_mult * h,
                                          weight_attr=init,
                                          gather_output=False)
        self.fc_out = RowParallelLinear(cfg.ffn_mult * h, h,
                                        weight_attr=init,
                                        input_is_parallel=True)
        self.dropout = Dropout(cfg.dropout)

    def forward(self, x):
        return self.dropout(self.fc_out(F.gelu(self.fc_in(x))))


class GPTBlock(Layer):
    """Pre-LN transformer block (the unit the pipeline stacks)."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln_1 = LayerNorm(cfg.hidden_size)
        self.attn = GPTAttention(cfg)
        self.ln_2 = LayerNorm(cfg.hidden_size)
        if cfg.use_moe:
            from ..distributed.moe import MoELayer
            self.mlp = MoELayer(cfg.hidden_size,
                                cfg.ffn_mult * cfg.hidden_size,
                                cfg.moe_experts)
        else:
            self.mlp = GPTMLP(cfg)

    def forward(self, x, cache=None, pos=None):
        if cache is not None:
            att, cache = self.attn(self.ln_1(x), cache, pos)
            x = x + att
            x = x + self.mlp(self.ln_2(x))
            return x, cache
        x = x + self.attn(self.ln_1(x))
        x = x + self.mlp(self.ln_2(x))
        return x


class GPTScannedBlocks(ScannedStack):
    """GPT decoder stack as one lax.scan (``cfg.scan_layers``) — see
    models/scanned.py for the full design. MoE blocks work (per-layer
    aux losses ride the scan outputs); dropout is rejected (traced-once
    body would reuse one RNG draw per layer)."""

    def __init__(self, cfg: GPTConfig):
        ScannedStack.reject_dropout(cfg.dropout)
        super().__init__(lambda: GPTBlock(cfg), cfg.num_layers,
                         cfg.initializer_range, recompute=cfg.recompute,
                         recompute_policy=cfg.recompute_policy)
        self.cfg = cfg


class GPTEmbeddings(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        init = I.Normal(0.0, cfg.initializer_range)
        self.word_embeddings = VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size, weight_attr=init)
        self.position_embeddings = Embedding(
            cfg.max_seq_len, cfg.hidden_size, weight_attr=init)
        self.dropout = Dropout(cfg.dropout)

    def forward(self, ids, pos=None):
        S = ids.shape[-1]
        max_len = self.position_embeddings.num_embeddings
        if S > max_len:
            raise ValueError(
                f"sequence length {S} exceeds max_seq_len {max_len}")
        positions = T.arange(0, S, dtype="int64")
        if pos is not None:                     # decode offset
            p = T.cast(pos, "int64")
            if len(tuple(p.shape)) == 1:
                # per-row offsets [B] (continuous-batching slots, each
                # at its own decode position) -> positions [B, S]
                positions = (T.reshape(positions, [1, S])
                             + T.reshape(p, [-1, 1]))
            else:
                positions = positions + p
        x = self.word_embeddings(ids) + self.position_embeddings(positions)
        return self.dropout(x)


class GPTModel(Layer):
    """Decoder stack without head. Parity role: GPTModel in the reference
    ecosystem driven through fleet (SURVEY.md §3.4)."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = GPTEmbeddings(cfg)
        if cfg.scan_layers:
            self.blocks = GPTScannedBlocks(cfg)
        else:
            self.blocks = []
            for i in range(cfg.num_layers):
                blk = GPTBlock(cfg)
                self.add_sublayer(f"block_{i}", blk)
                self.blocks.append(blk)
        self.ln_f = LayerNorm(cfg.hidden_size)

    def forward(self, ids, caches=None, pos=None):
        if caches is not None:
            x = self.embeddings(ids, pos)
            if self.cfg.scan_layers:
                x, new_caches = self.blocks.forward_cached(x, caches, pos)
                return self.ln_f(x), new_caches
            new_caches = []
            for blk, c in zip(self.blocks, caches):
                x, c = blk(x, c, pos)
                new_caches.append(c)
            return self.ln_f(x), new_caches
        x = self.embeddings(ids)
        if self.cfg.scan_layers:
            return self.ln_f(self.blocks(x))
        if self.cfg.recompute and self.training:
            if self.cfg.use_moe:
                raise NotImplementedError(
                    "cfg.recompute with use_moe: the MoE aux-loss side "
                    "channel would cross the jax.checkpoint boundary "
                    "(tracer leak); use GPTPipelineForCausalLM's "
                    "recompute_interval for MoE models")
            from ..distributed.recompute import recompute as _rc
            for blk in self.blocks:
                x = _rc(blk, x, policy=self.cfg.recompute_policy)
        else:
            for blk in self.blocks:
                x = blk(x)
        return self.ln_f(x)


class GPTForCausalLM(Layer):
    """LM head on top; loss = causal LM cross-entropy.

    lm head is tied to the (vocab-parallel) embedding when
    cfg.tie_embeddings — the sharded logits matmul then feeds the
    ParallelCrossEntropy-style fp32 softmax inside F.cross_entropy.
    """

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.gpt = GPTModel(cfg)
        if not cfg.tie_embeddings:
            self.lm_head = Linear(cfg.hidden_size, cfg.vocab_size,
                                  weight_attr=I.Normal(
                                      0.0, cfg.initializer_range),
                                  bias_attr=False)

    def forward(self, ids, caches=None, pos=None):
        if caches is not None:
            x, caches = self.gpt(ids, caches, pos)
            return self._logits(x), caches
        x = self.gpt(ids)
        if self.cfg.fused_loss_chunk and self.training:
            # training-perf contract (cfg.fused_loss_chunk): hand the
            # hidden states + LM weight to fused_loss_fn so the logits
            # never materialize. Gated on self.training so eval()/
            # perplexity callers always get logits; decode/caches path
            # above returns logits for generate() either way.
            return x, self._lm_weight()
        return self._logits(x)

    def _lm_weight(self):
        if self.cfg.tie_embeddings:
            return self.gpt.embeddings.word_embeddings.weight  # [V, H]
        return self.lm_head.weight                             # [H, V]

    def _logits(self, x):
        if self.cfg.tie_embeddings:
            w = self.gpt.embeddings.word_embeddings.weight
            return T.matmul(x, T.transpose(w, [1, 0]))
        return self.lm_head(x)

    def new_cache(self, batch_size: int, max_len: int, dtype="bfloat16"):
        """Per-layer (k, v) cache arrays [B, max_len, nh, hd] for
        generate()."""
        cfg = self.cfg
        hd = cfg.hidden_size // cfg.num_heads
        return _new_cache(cfg.num_layers, batch_size, max_len,
                          cfg.num_heads, hd, dtype, cfg.scan_layers)

    def new_paged_cache(self, num_pages: int, page_size: int,
                        dtype="bfloat16"):
        """Per-layer (k, v) page POOLS for the paged serving engine —
        [num_pages, page_size, nh, hd] each; block tables are engine
        state, not part of this pytree."""
        from .generation import new_paged_kv_caches
        cfg = self.cfg
        hd = cfg.hidden_size // cfg.num_heads
        return new_paged_kv_caches(cfg.num_layers, num_pages, page_size,
                                   cfg.num_heads, hd, dtype,
                                   cfg.scan_layers)

    def generate(self, input_ids, max_new_tokens=32, **kw):
        from .generation import generate
        return generate(self, input_ids, max_new_tokens, **kw)

    @staticmethod
    def loss_fn(logits, labels):
        """Next-token prediction: logits at position i predict labels[i+1]
        (callers pass labels=input_ids; the shift happens here)."""
        V = logits.shape[-1]
        shifted_logits = T.slice(logits, [1], [0], [logits.shape[1] - 1])
        shifted_labels = T.slice(labels, [1], [1], [labels.shape[1]])
        return T.mean(F.cross_entropy(
            T.reshape(shifted_logits, [-1, V]),
            T.reshape(shifted_labels, [-1])))

    def make_loss_fn(self):
        """The loss composition this config trains with: fused_loss_fn
        bound to cfg.fused_loss_chunk when set, else plain loss_fn —
        call sites never re-encode the contract."""
        if self.cfg.fused_loss_chunk:
            import functools
            return functools.partial(self.fused_loss_fn,
                                     chunk_size=self.cfg.fused_loss_chunk)
        return self.loss_fn

    @staticmethod
    def fused_loss_fn(outputs, labels, chunk_size=512):
        """loss_fn counterpart for cfg.fused_loss_chunk models: outputs is
        (hidden, lm_weight) from a training-mode forward; the shifted
        tokens stream through F.fused_linear_cross_entropy so
        [tokens, vocab] logits never materialize.

        An eval()-mode forward returns plain logits (the fused return is
        gated on self.training), so make_loss_fn's output stays correct
        in both modes: logits fall through to loss_fn here."""
        if not isinstance(outputs, tuple):
            return GPTForCausalLM.loss_fn(outputs, labels)
        hidden, w = outputs
        S = hidden.shape[1]
        h_s = T.slice(hidden, [1], [0], [S - 1])
        l_s = T.slice(labels, [1], [1], [S])
        return F.fused_linear_cross_entropy(h_s, w, l_s,
                                            chunk_size=chunk_size)


class _EmbedStage(Layer):
    def __init__(self, cfg):
        super().__init__()
        self.emb = GPTEmbeddings(cfg)

    def forward(self, ids):
        return self.emb(ids)


class _HeadStage(Layer):
    def __init__(self, cfg):
        super().__init__()
        self.ln_f = LayerNorm(cfg.hidden_size)
        self.head = Linear(cfg.hidden_size, cfg.vocab_size,
                           weight_attr=I.Normal(0.0, cfg.initializer_range),
                           bias_attr=False)

    def forward(self, x):
        return self.head(self.ln_f(x))


class GPTPipelineForCausalLM(PipelineLayer):
    """The same GPT arranged for pipeline parallelism.

    Parity: PipelineLayer GPT arrangements in the reference test suite
    (unittests/collective/fleet/hybrid_parallel_pp_transformer.py). Blocks
    stack over the pp axis; embeddings/head run as prologue/epilogue (so
    tying across stages is not used here — reference PP GPT uses
    SharedLayerDesc; with one global program the head stays a separate
    Linear for homogeneity).
    """

    def __init__(self, cfg: GPTConfig, num_stages: Optional[int] = None,
                 recompute_interval: int = 0,
                 num_micro: Optional[int] = None, interleave: int = 1):
        self.cfg = cfg
        super().__init__(
            layers=[LayerDesc(_EmbedStage, cfg)]
            + [LayerDesc(GPTBlock, cfg) for _ in range(cfg.num_layers)]
            + [LayerDesc(_HeadStage, cfg)],
            num_stages=num_stages,
            loss_fn=GPTForCausalLM.loss_fn,
            recompute_interval=recompute_interval,
            recompute_policy=cfg.recompute_policy,
            num_micro=num_micro, interleave=interleave)
