"""Flagship model zoo (BASELINE.json configs: GPT-3 family pretraining,
LLaMA hybrid parallel; vision models live in paddle_tpu.vision)."""
from .gpt import (GPTConfig, GPTForCausalLM, GPTModel,
                  GPTPipelineForCausalLM, gpt_tiny, gpt_125m, gpt_1p3b,
                  gpt_6p7b)
from .llama import (LlamaConfig, LlamaForCausalLM, LlamaModel,
                    LlamaPipelineForCausalLM, llama_tiny, llama_7b,
                    llama_13b)
from .bert import (BertConfig, BertModel, BertForSequenceClassification,
                   BertForMaskedLM, ErnieModel, bert_tiny, bert_base,
                   ernie_3_tiny, ernie_3_base)

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM",
           "GPTPipelineForCausalLM", "gpt_tiny", "gpt_125m", "gpt_1p3b",
           "gpt_6p7b",
           "LlamaConfig", "LlamaModel", "LlamaForCausalLM",
           "LlamaPipelineForCausalLM", "llama_tiny", "llama_7b",
           "llama_13b",
           "BertConfig", "BertModel", "BertForSequenceClassification",
           "BertForMaskedLM", "ErnieModel", "bert_tiny", "bert_base",
           "ernie_3_tiny", "ernie_3_base"]
