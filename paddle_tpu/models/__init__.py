"""Flagship model zoo (BASELINE.json configs: GPT-3 family pretraining,
LLaMA-style hybrid parallel; vision models live in paddle_tpu.vision)."""
from .gpt import (GPTConfig, GPTForCausalLM, GPTModel,
                  GPTPipelineForCausalLM, gpt_tiny, gpt_125m, gpt_1p3b,
                  gpt_6p7b)

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM",
           "GPTPipelineForCausalLM", "gpt_tiny", "gpt_125m", "gpt_1p3b",
           "gpt_6p7b"]
