"""BERT/ERNIE encoder family — BASELINE.json config 2 (fine-tune path).

Capability parity: the reference fine-tunes ERNIE-3.0/BERT-class encoders
(model code in PaddleNLP over paddle.nn.TransformerEncoder,
python/paddle/nn/layer/transformer.py); serving is north-star config 5's
sibling (ERNIE-3.0 on the inference predictor). TPU-first re-design:

- encoder blocks are post-LN transformer layers on the same TP layer
  library as GPT/LLaMA (Column/RowParallelLinear, one allreduce per pair);
- token/position/segment embeddings + pooler + task heads
  (sequence classification, masked LM) as separate thin modules;
- ERNIE is architecturally BERT here (relu FFN default, same heads);
  `ErnieModel`/`ernie_3_tiny` are the named configs.

Fine-tuning runs through the ordinary TrainStep/ParallelTrainStep or
hapi Model.fit; serving through paddle_tpu.inference (AOT XLA).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from .. import tensor as T
from ..distributed.meta_parallel import (ColumnParallelLinear,
                                         RowParallelLinear,
                                         VocabParallelEmbedding)
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer_base import Layer
from ..nn import Dropout, Embedding, LayerNorm, Linear, Tanh

__all__ = ["BertConfig", "BertModel", "BertForSequenceClassification",
           "BertForMaskedLM", "ErnieModel", "bert_tiny", "bert_base",
           "ernie_3_tiny", "ernie_3_base"]


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_seq_len: int = 512
    type_vocab_size: int = 2
    hidden_act: str = "gelu"
    dropout: float = 0.1
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    # compile the encoder stack as ONE lax.scan over stacked params
    # (models/scanned.py) — depth-independent HLO; requires dropout=0.0
    scan_layers: bool = False


def bert_tiny(**kw):
    return BertConfig(vocab_size=512, hidden_size=64, num_layers=2,
                      num_heads=4, intermediate_size=128, max_seq_len=128,
                      dropout=0.0, **kw)


def bert_base(**kw):
    return BertConfig(**kw)


def ernie_3_tiny(**kw):
    kw.setdefault("hidden_act", "relu")
    return bert_tiny(**kw)


def ernie_3_base(**kw):
    # ERNIE-3.0-base: BERT-base geometry, relu FFN
    kw.setdefault("hidden_act", "relu")
    return BertConfig(**kw)


class BertEmbeddings(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        init = I.Normal(0.0, cfg.initializer_range)
        self.word_embeddings = VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size, weight_attr=init)
        self.position_embeddings = Embedding(cfg.max_seq_len,
                                             cfg.hidden_size,
                                             weight_attr=init)
        self.token_type_embeddings = Embedding(cfg.type_vocab_size,
                                               cfg.hidden_size,
                                               weight_attr=init)
        self.layer_norm = LayerNorm(cfg.hidden_size,
                                    epsilon=cfg.layer_norm_eps)
        self.dropout = Dropout(cfg.dropout)

    def forward(self, ids, token_type_ids=None):
        S = ids.shape[-1]
        if S > self.position_embeddings.num_embeddings:
            raise ValueError(
                f"sequence length {S} exceeds max_seq_len "
                f"{self.position_embeddings.num_embeddings}")
        pos = T.arange(0, S, dtype="int64")
        x = self.word_embeddings(ids) + self.position_embeddings(pos)
        if token_type_ids is None:
            token_type_ids = T.zeros_like(ids)
        x = x + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(x))


class BertSelfAttention(Layer):
    """Bidirectional self-attention (TP-sharded heads, padding mask)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        h, nh = cfg.hidden_size, cfg.num_heads
        if h % nh:
            raise ValueError("hidden_size % num_heads != 0")
        self.num_heads = nh
        self.head_dim = h // nh
        init = I.Normal(0.0, cfg.initializer_range)
        self.qkv = ColumnParallelLinear(h, 3 * h, weight_attr=init,
                                        gather_output=False)
        self.out_proj = RowParallelLinear(h, h, weight_attr=init,
                                          input_is_parallel=True)
        self.attn_dropout = cfg.dropout
        self.dropout = Dropout(cfg.dropout)

    def forward(self, x, attn_mask=None):
        B, S, _ = x.shape
        hd, nh = self.head_dim, self.num_heads
        qkv = self.qkv(x)
        H = qkv.shape[-1] // 3
        q = T.reshape(T.slice(qkv, [2], [0], [H]), [B, S, nh, hd])
        k = T.reshape(T.slice(qkv, [2], [H], [2 * H]), [B, S, nh, hd])
        v = T.reshape(T.slice(qkv, [2], [2 * H], [3 * H]), [B, S, nh, hd])
        ctx = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                             dropout_p=self.attn_dropout,
                                             is_causal=False,
                                             training=self.training)
        return self.dropout(self.out_proj(T.reshape(ctx, [B, S, H])))


class BertLayer(Layer):
    """Post-LN transformer encoder block (original BERT arrangement)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        h = cfg.hidden_size
        init = I.Normal(0.0, cfg.initializer_range)
        self.attn = BertSelfAttention(cfg)
        self.ln_1 = LayerNorm(h, epsilon=cfg.layer_norm_eps)
        self.fc_in = ColumnParallelLinear(h, cfg.intermediate_size,
                                          weight_attr=init,
                                          gather_output=False)
        self.fc_out = RowParallelLinear(cfg.intermediate_size, h,
                                        weight_attr=init,
                                        input_is_parallel=True)
        self.ln_2 = LayerNorm(h, epsilon=cfg.layer_norm_eps)
        self.dropout = Dropout(cfg.dropout)
        self.act = F.relu if cfg.hidden_act == "relu" else F.gelu

    def forward(self, x, attn_mask=None):
        x = self.ln_1(x + self.attn(x, attn_mask))
        y = self.dropout(self.fc_out(self.act(self.fc_in(x))))
        return self.ln_2(x + y)


class BertPooler(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.dense = Linear(cfg.hidden_size, cfg.hidden_size,
                            weight_attr=I.Normal(0.0,
                                                 cfg.initializer_range))
        self.activation = Tanh()

    def forward(self, x):
        # [CLS] token
        first = T.squeeze(T.slice(x, [1], [0], [1]), axis=1)
        return self.activation(self.dense(first))


class BertModel(Layer):
    """Encoder stack + pooler. Returns (sequence_output, pooled_output)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        if cfg.scan_layers:
            from .scanned import ScannedStack
            # guard before any submodule allocates
            ScannedStack.reject_dropout(cfg.dropout)
            self.embeddings = BertEmbeddings(cfg)
            self.layers = ScannedStack(lambda: BertLayer(cfg),
                                       cfg.num_layers,
                                       cfg.initializer_range)
        else:
            self.embeddings = BertEmbeddings(cfg)
            self.layers = []
            for i in range(cfg.num_layers):
                layer = BertLayer(cfg)
                self.add_sublayer(f"layer_{i}", layer)
                self.layers.append(layer)
        self.pooler = BertPooler(cfg)

    def forward(self, ids, token_type_ids=None, attention_mask=None):
        mask = None
        if attention_mask is not None:
            # [B, S] 1/0 -> additive [B, 1, 1, S]
            m = T.cast(attention_mask, "float32")
            mask = T.reshape((m - 1.0) * 1e30,
                             [m.shape[0], 1, 1, m.shape[1]])
        x = self.embeddings(ids, token_type_ids)
        if self.cfg.scan_layers:
            x = self.layers(x, mask)  # None mask passes through safely
            return x, self.pooler(x)
        for layer in self.layers:
            x = layer(x, mask)
        return x, self.pooler(x)


class ErnieModel(BertModel):
    """ERNIE-3.0-class encoder — same architecture, relu-FFN configs."""


class BertForSequenceClassification(Layer):
    """Fine-tune head (config 2: ERNIE-3.0/BERT-base fine-tune)."""

    def __init__(self, cfg: BertConfig, num_classes: int = 2):
        super().__init__()
        self.bert = BertModel(cfg)
        self.dropout = Dropout(cfg.dropout)
        self.classifier = Linear(cfg.hidden_size, num_classes,
                                 weight_attr=I.Normal(
                                     0.0, cfg.initializer_range))

    def forward(self, ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.bert(ids, token_type_ids, attention_mask)
        return self.classifier(self.dropout(pooled))

    @staticmethod
    def loss_fn(logits, labels):
        return T.mean(F.cross_entropy(logits, labels))


class BertForMaskedLM(Layer):
    """MLM head; the decoder is weight-tied to the (vocab-parallel) input
    embedding — the sharded-logits matmul pattern GPT uses for
    tie_embeddings — with an untied output bias, as in reference BERT."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.bert = BertModel(cfg)
        self.transform = Linear(cfg.hidden_size, cfg.hidden_size,
                                weight_attr=I.Normal(
                                    0.0, cfg.initializer_range))
        self.layer_norm = LayerNorm(cfg.hidden_size,
                                    epsilon=cfg.layer_norm_eps)
        self.decoder_bias = self.create_parameter([cfg.vocab_size],
                                                  is_bias=True)
        self.decoder_bias.sharding_axes = ("mp",)

    def forward(self, ids, token_type_ids=None, attention_mask=None):
        x, _ = self.bert(ids, token_type_ids, attention_mask)
        x = self.layer_norm(F.gelu(self.transform(x)))
        w = self.bert.embeddings.word_embeddings.weight
        return T.matmul(x, T.transpose(w, [1, 0])) + self.decoder_bias

    @staticmethod
    def loss_fn(logits, labels, ignore_index: int = -100):
        """MLM loss over positions where labels != ignore_index."""
        V = logits.shape[-1]
        return T.mean(F.cross_entropy(
            T.reshape(logits, [-1, V]), T.reshape(labels, [-1]),
            ignore_index=ignore_index))
