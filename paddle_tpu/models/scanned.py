"""Scan-over-layers: a homogeneous decoder stack as ONE set of stacked
parameters applied with `jax.lax.scan`.

TPU-first compile-time scaling. An unrolled block list emits
O(num_layers) copies of identical HLO, so XLA compile time grows
linearly with depth — the 24-layer GPT-1.3B whole-step program exceeded
a 25-minute compile budget through the remote-compile tunnel, and the
6.7B ZeRO-3 AOT compile took 209s. Scanned, the block body is compiled
ONCE regardless of depth (6.7B: 7.4s, identical per-device memory).
This is the idiom flax calls scan-over-layers; the reference has no
analog — its executor re-dispatches per-op per-layer at runtime
(SURVEY.md §3.3), which is why its "compile time" doesn't grow but its
dispatch overhead does.

Semantics are identical to the unrolled stack: the scan body swaps the
i-th parameter slice into a template block (built abstract under
LazyGuard — zero resident bytes) and runs its ordinary ``forward``.
Per-block rematerialisation becomes ``jax.checkpoint`` on the scan
body. Eager autograd works — the scan is recorded on the tape as one op
via ``tape.apply`` — and under TrainStep/ParallelTrainStep the stacked
leaves are ordinary donated parameters whose sharding annotations keep
the block's TP axes with the layer axis unsharded. KV-cache decode
rotates stacked `[L, B, M, heads, hd]` caches through the same scan
(``forward_cached``).

Used by `GPTConfig.scan_layers` and `LlamaConfig.scan_layers`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..jit.functional import functional_call
from ..nn import initializer as I
from ..nn.layer_base import Layer

__all__ = ["ScannedStack"]


class ScannedStack(Layer):
    """num_layers copies of block_factory() as stacked-leaf parameters.

    Initialization rule (matches the transformer blocks this serves):
    rank>=2 leaves draw Normal(0, initializer_range) — L independent
    draws == one draw of the stacked shape; rank-1 ``*.weight`` leaves
    are norm scales (ones); everything else is a bias (zeros).

    Blocks that report auxiliary losses (MoE) are supported — see
    ``forward``. Restrictions (loud): blocks with buffers are rejected
    (buffers are not stacked, same rule as PipelineLayer body blocks).
    Stochastic blocks (dropout>0) must be rejected by the CALLER — the
    scan body is traced once, so every layer would reuse one RNG draw.

    Initializer restriction: the rule above REPLACES the template
    block's own initializers (a LazyGuard template holds no values to
    stack). A block with a custom ``weight_attr`` (scaled residual
    init, non-Normal draws) or a rank-1 parameter not named
    ``*.weight``/bias would initialize differently from its unrolled
    counterpart — such blocks must either use ``load_from_blocks`` to
    import real values, or extend the rule here. Today's GPT/LLaMA/BERT
    blocks all follow the rule exactly.
    """

    def __init__(self, block_factory, num_layers: int,
                 initializer_range: float, recompute: bool = False,
                 recompute_policy: str = "full"):
        super().__init__()
        from ..distributed.recompute import resolve_checkpoint_policy
        self.num_layers = num_layers
        self.recompute = recompute
        # resolve eagerly: a typo'd policy fails at construction
        self._ckpt_policy = resolve_checkpoint_policy(recompute_policy)
        # plain-list attribute: provides structure + forward only — built
        # abstract (LazyGuard) so its parameters are ShapeDtypeStructs,
        # not resident arrays that compute never touches
        from ..framework.lazy_init import LazyGuard
        with LazyGuard():
            self._template = [block_factory()]
        tmpl = self._template[0]
        if list(tmpl.named_buffers()):
            raise NotImplementedError(
                "scan_layers with buffered blocks: buffers are not "
                "stacked across layers (same restriction as "
                "PipelineLayer body blocks)")
        # static: does any sublayer report aux losses (MoE gates)?
        # decided here so aux-free stacks keep the single-output path
        self._has_aux = any(hasattr(l, "aux_loss_weight")
                            for l in tmpl.sublayers(include_self=True))
        w_init = I.Normal(0.0, initializer_range)
        self._names = []
        for name, p in tmpl.named_parameters():
            shape = [num_layers] + list(p.shape)
            if len(p.shape) >= 2:
                value = w_init(shape, "float32")
            elif name.endswith(".weight"):  # norm scales
                value = I.Constant(1.0)(shape, "float32")
            else:  # biases
                value = I.Constant(0.0)(shape, "float32")
            sp = type(p)(value)
            # stacked leaf keeps the block's TP annotation with the layer
            # axis unsharded (same pattern as PipelineLayer._stack_params,
            # which prepends "pp"); scan runs every layer on every chip
            inner = p.sharding_axes
            if inner is not None:
                sp.sharding_axes = (None,) + tuple(inner)
            sp.is_distributed = p.is_distributed
            self.add_parameter(self._mangle(name), sp)
            self._names.append(name)

    @staticmethod
    def reject_dropout(p: float) -> None:
        """Caller-side guard: stochastic blocks cannot scan — the body is
        traced once, so every layer would reuse one RNG draw."""
        if p:
            raise NotImplementedError(
                "scan_layers requires dropout=0.0: the scan body is "
                "traced once, so every layer would reuse the same "
                "dropout mask")

    @staticmethod
    def _mangle(name: str) -> str:
        # parameter-dict keys must not contain "." (named_parameters
        # joins hierarchy with "."); keep a reversible encoding
        return name.replace(".", "__")

    def _scan_leaves(self):
        """(template, names, stacked leaves) — the ONE definition of the
        leaf ordering fed to lax.scan; train and decode must agree."""
        return (self._template[0], self._names,
                [self._parameters[self._mangle(n)] for n in self._names])

    def load_from_blocks(self, blocks) -> None:
        """Stack per-layer params from an unrolled block list (checkpoint
        interop: unrolled state_dicts convert mechanically)."""
        blocks = list(blocks)
        if len(blocks) != self.num_layers:
            raise ValueError(
                f"load_from_blocks: got {len(blocks)} blocks for a "
                f"num_layers={self.num_layers} model")
        per_layer = [dict(b.named_parameters()) for b in blocks]
        for name in self._names:
            vals = [d[name].value for d in per_layer]
            if any(isinstance(v, jax.ShapeDtypeStruct) for v in vals):
                raise ValueError(
                    "load_from_blocks: source blocks hold abstract "
                    "(LazyGuard) parameters — materialize them first")
            target = self._parameters[self._mangle(name)]
            # keep the scanned model's precision (e.g. after .bfloat16())
            target.value = jnp.stack(vals).astype(target.value.dtype)

    def forward(self, x, *extra):
        """Apply the stack to x. ``extra`` are layer-INVARIANT positional
        args handed to every block unchanged (e.g. an attention mask for
        encoder blocks) — they ride along as differentiable inputs.

        Blocks that report auxiliary losses (MoE load balancing) work:
        each scan iteration collects its block's aux losses in a private
        scope and returns their sum as a scan output; the per-layer sums
        are re-reported ONCE to the active outer scope after the tape op
        (the report-after-apply pattern MoELayer itself uses), so the
        training engines add them to the objective and gate gradients
        flow through the scan."""
        from ..autograd import tape as _tape
        from ..framework.aux_loss import (add_aux_loss, aux_loss_scope,
                                          total)
        tmpl, names, leaves = self._scan_leaves()
        training = self.training
        recompute = self.recompute and training
        n_extra = len(extra)
        has_aux = self._has_aux  # static (decided at construction)

        def run(h, *rest):
            ex, stacked = rest[:n_extra], rest[n_extra:]

            def body(h, psl):
                # private scope even when has_aux is False: an aux report
                # from inside the scan trace must never reach an outer
                # bucket (tracer leak)
                with aux_loss_scope() as bucket:
                    out, _ = functional_call(tmpl, dict(zip(names, psl)),
                                             {}, h, *ex,
                                             training=training)
                if not has_aux:
                    return out
                return out, jnp.asarray(total(bucket), jnp.float32)
            if recompute:
                body = jax.checkpoint(body, policy=self._ckpt_policy)

            if not has_aux:
                def scan_body(h, psl):
                    return body(h, psl), None
                out, _ = jax.lax.scan(scan_body, h, list(stacked))
                return out
            out, auxs = jax.lax.scan(body, h, list(stacked))
            return out, jnp.sum(auxs)

        if not has_aux:
            return _tape.apply(run, x, *extra, *leaves,
                               _op_name="scanned_stack")
        out, aux_sum = _tape.apply(run, x, *extra, *leaves,
                                   _op_name="scanned_stack")
        add_aux_loss(aux_sum.value if hasattr(aux_sum, "value")
                     else aux_sum)
        return out

    def forward_cached(self, x, caches, pos):
        """Decode step: caches is (k_stack, v_stack), each [L, B, M,
        heads, hd]; every layer's slice rotates through the scan body."""
        from ..autograd import tape as _tape
        from ..framework.aux_loss import aux_loss_scope
        tmpl, names, leaves = self._scan_leaves()
        k_stack, v_stack = caches
        pos_raw = pos.value if isinstance(pos, Tensor) else pos

        def run(h, kst, vst, *stacked):
            def body(carry, xs):
                psl_leaves, kc, vc = xs
                psl = dict(zip(names, psl_leaves))
                # private scope: a decode-time aux report (MoE gates fire
                # regardless of training mode) must not leak scan-trace
                # tracers into an outer bucket; decode discards aux
                with aux_loss_scope():
                    out, _ = functional_call(tmpl, psl, {}, carry,
                                             (kc, vc), pos_raw,
                                             training=False)
                h2, (kc2, vc2) = out
                return h2, (kc2, vc2)

            h2, (knew, vnew) = jax.lax.scan(
                body, h, (list(stacked), kst, vst))
            return h2, knew, vnew

        if isinstance(k_stack, dict) or isinstance(v_stack, dict):
            # int8 (dict-pytree) caches: the tape cannot wrap dicts and
            # quantized writes are not differentiable — run raw
            from ..core.tensor import as_raw
            h2, k2, v2 = run(as_raw(x), k_stack, v_stack,
                             *[l.value for l in leaves])
            return Tensor(h2, stop_gradient=True), (k2, v2)
        h_t, k_t, v_t = _tape.apply(run, x, k_stack, v_stack, *leaves,
                                    _op_name="scanned_stack_decode")
        return h_t, (k_t, v_t)
