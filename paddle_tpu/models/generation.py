"""Autoregressive generation with static-shape KV caches.

Serving-path role parity: the reference's inference transformer stack
(fused_multi_transformer_op.cu CacheKV decode, §2.4) and the beam/sampling
decode helpers. TPU-native design: ONE jitted prefill program + ONE jitted
whole-decode program — the entire token loop is a `lax.scan` inside the
compiled program (eos masking included), so generating N tokens costs a
single host->device dispatch instead of N round-trips. Over a tunneled
or remote chip the per-step host sync would otherwise dominate decode.
Caches are donated so XLA updates them in place in HBM.

Works with any model exposing:
  forward(ids, caches, pos) -> (logits, caches)   (cache-threaded forward)
  new_cache(batch, max_len, dtype) -> caches
where `caches` is ANY pytree the model's forward threads through —
per-layer [(k, v), ...] for unrolled stacks, a stacked
(k_stack, v_stack) pair for scan_layers models. GPTForCausalLM and
LlamaForCausalLM both do; `model.generate(...)` delegates here.
"""
from __future__ import annotations

import os
import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.tensor import Tensor
from ..jit.functional import functional_call, raw_state

__all__ = ["generate", "new_kv_caches", "new_paged_kv_caches",
           "build_generate_programs"]


def _prog_cache_size() -> int:
    """Bounded-LRU size for the per-model compiled-program cache. A
    long-lived server with drifting prompt lengths must not pin
    executables forever; bucket prompt lengths server-side (the
    continuous-batching engine does) to hit this cache reliably."""
    try:
        return max(1, int(os.environ.get("PADDLE_TPU_GEN_PROG_CACHE",
                                         16)))
    except ValueError:
        return 16


def _prog_cache_for(model):
    """(OrderedDict, Lock) compiled-program LRU attached to `model`.

    The lock matters: server threads call generate() concurrently, and
    OrderedDict get/move_to_end/popitem are NOT safe under concurrent
    mutation (observed: KeyError out of move_to_end racing popitem).
    Creation is double-checked so two first-callers agree on one dict.
    """
    cache = getattr(model, "_gen_prog_cache", None)
    lock = getattr(model, "_gen_prog_lock", None)
    if cache is None or lock is None:
        with _PROG_CACHE_INIT_LOCK:
            cache = getattr(model, "_gen_prog_cache", None)
            lock = getattr(model, "_gen_prog_lock", None)
            if cache is None:
                import collections
                cache = collections.OrderedDict()
                object.__setattr__(model, "_gen_prog_cache", cache)
            if lock is None:
                lock = threading.Lock()
                object.__setattr__(model, "_gen_prog_lock", lock)
    return cache, lock


_PROG_CACHE_INIT_LOCK = threading.Lock()


def new_kv_caches(num_layers, batch, max_len, kv_heads, head_dim, dtype,
                  scan_layers):
    """KV caches for generate(): per-layer [(k, v), ...] (unrolled) or a
    stacked (k_stack, v_stack) pair (scan_layers models). dtype "int8"
    selects the dynamically-quantized cache (quantized_kv_cache) — the
    TPU-native role of the reference's int8 CacheKV
    (fused_multi_transformer_op.cu)."""
    from ..nn.functional.flash_attention import quantized_kv_cache
    if dtype == "int8":
        def one():
            return quantized_kv_cache(batch, max_len, kv_heads, head_dim)
    else:
        def one():
            return jnp.zeros((batch, max_len, kv_heads, head_dim), dtype)
    if scan_layers:
        def stack(trees):
            return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                          *trees)
        return (stack([one() for _ in range(num_layers)]),
                stack([one() for _ in range(num_layers)]))
    return [(one(), one()) for _ in range(num_layers)]


def new_paged_kv_caches(num_layers, num_pages, page_size, kv_heads,
                        head_dim, dtype, scan_layers):
    """Paged KV caches for the continuous-batching engine's paged mode:
    per-layer (k_pool, v_pool) page pools (flash_attention.paged_kv_cache
    dicts, dtype "int8" selects the quantized pool), or — scan_layers —
    ONE stacked (k_stack, v_stack) pair whose leaves carry a leading
    layer axis. A physical page id means "that page in EVERY layer's
    pool" — one shared block table indexes them all, so host-side page
    accounting stays per-request, not per-layer. Block tables are
    per-request state the engine attaches per program call; they are NOT
    part of this pytree."""
    from ..nn.functional.flash_attention import paged_kv_cache
    if scan_layers:
        # Stacked pools [L, num_pages, page_size, ...]:
        # ScannedStack.forward_cached slices every cache-dict leaf along
        # the layer axis inside its scan, so each layer's body sees an
        # ordinary per-layer pool dict. The shared block table has no
        # layer axis of its own — the ENGINE broadcasts its per-program
        # metadata (bt/live/wlen) with a leading L before attaching
        # (ISSUE 20, the PR 9 follow-up), which gives the scan a
        # per-layer [B, PM] slice of one host-side table; paging.py's
        # allocator/trie/COW accounting stays per-request, layer-blind.
        def stack(trees):
            return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                          *trees)
        return (stack([paged_kv_cache(num_pages, page_size, kv_heads,
                                      head_dim, dtype)
                       for _ in range(num_layers)]),
                stack([paged_kv_cache(num_pages, page_size, kv_heads,
                                      head_dim, dtype)
                       for _ in range(num_layers)]))
    return [(paged_kv_cache(num_pages, page_size, kv_heads, head_dim,
                            dtype),
             paged_kv_cache(num_pages, page_size, kv_heads, head_dim,
                            dtype))
            for _ in range(num_layers)]


def _select_token(logits, key, do_sample, temperature, top_k, top_p):
    """logits [B, V] -> token [B] (greedy or filtered sampling)."""
    if not do_sample:
        return jnp.argmax(logits, axis=-1)
    logits = logits.astype(jnp.float32) / jnp.maximum(temperature, 1e-6)
    if top_k:
        kth = jnp.sort(logits, axis=-1)[:, -int(top_k)][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest logit value still inside the nucleus
        keep = cum - probs < top_p
        cutoff = jnp.min(jnp.where(keep, sorted_logits, jnp.inf), axis=-1,
                         keepdims=True)
        logits = jnp.where(logits < cutoff, -1e30, logits)
    return jax.random.categorical(key, logits, axis=-1)


def build_generate_programs(model, P: int, max_new_tokens: int,
                            eos: Optional[int], do_sample: bool,
                            temperature: float, top_k: int,
                            top_p: float):
    """(prefill, decode_all) jitted programs for one generate()
    configuration — the exact programs generate() caches per prog_key.

    Module-level (not a generate() closure) so the static analyzer
    (paddle_tpu.analysis) lints the REAL serving programs by lowering
    them directly, without executing a token. Signatures:

        prefill(params, buffers, ids[B,P]i64, caches, key) -> (tok, caches)
        decode_all(params, buffers, tok0[B]i, caches, key) -> (toks, caches)

    Caches are donated (argument 3 of both).
    """
    def prefill(params, buffers, ids, caches, key):
        (logits, caches), _ = functional_call(
            model, params, buffers, ids, caches,
            jnp.int32(0), training=False)
        nxt = _select_token(logits[:, -1, :], key, do_sample,
                            temperature, top_k, top_p)
        return nxt, caches

    def decode_all(params, buffers, tok0, caches, key):
        """The whole token loop as one scan: emits tok0 then
        max_new_tokens-1 successors, eos rows frozen."""
        fin0 = (tok0 == eos) if eos is not None \
            else jnp.zeros(tok0.shape, bool)

        def body(carry, i):
            tok, caches, fin, key = carry
            key, sub = jax.random.split(key)
            (logits, caches), _ = functional_call(
                model, params, buffers, tok[:, None], caches,
                (P + i).astype(jnp.int32), training=False)
            nxt = _select_token(logits[:, -1, :], sub, do_sample,
                                temperature, top_k, top_p)
            if eos is not None:
                nxt = jnp.where(fin, eos, nxt)
                fin = fin | (nxt == eos)
            return (nxt, caches, fin, key), nxt

        (_, caches, _, _), toks = lax.scan(
            body, (tok0, caches, fin0, key),
            jnp.arange(max_new_tokens - 1))
        # [B, max_new_tokens]: the prefill token + scan
        # emissions (int32 in-program; the host widens to int64).
        # caches are returned solely so the donated inputs have
        # an output to alias — callers discard them.
        out = jnp.concatenate(
            [tok0[:, None], toks.T.astype(tok0.dtype)], axis=1)
        return out, caches

    return (jax.jit(prefill, donate_argnums=(3,)),
            jax.jit(decode_all, donate_argnums=(3,)))


def generate(model, input_ids, max_new_tokens: int = 32,
             do_sample: bool = False, temperature: float = 1.0,
             top_k: int = 0, top_p: float = 1.0,
             eos_token_id: Optional[int] = None, seed: int = 0,
             cache_dtype: str = "bfloat16"):
    """Generate up to `max_new_tokens` continuations of `input_ids`.

    Returns an int64 numpy array [B, prompt_len + max_new_tokens]; after a
    row hits eos_token_id it is padded with eos.
    """
    ids = np.asarray(input_ids.numpy() if isinstance(input_ids, Tensor)
                     else input_ids).astype(np.int64)
    if ids.ndim == 1:
        ids = ids[None]
    B, P = ids.shape
    if max_new_tokens <= 0:
        return ids
    total = P + max_new_tokens
    max_len = getattr(getattr(model, "cfg", None), "max_seq_len", None)
    if max_len is not None and total > max_len:
        # position embeddings/RoPE are undefined past max_seq_len; the
        # OOB lookup would silently clamp, not error
        raise ValueError(
            f"prompt ({P}) + max_new_tokens ({max_new_tokens}) = {total} "
            f"exceeds the model's max_seq_len {max_len}")
    was_training = model.training
    model.eval()
    try:
        params, buffers = raw_state(model)
        caches = model.new_cache(B, total, cache_dtype)

        # One compiled prefill + decode program per (shape, sampling)
        # configuration, cached ON the model — a fresh jax.jit per
        # generate() call would re-trace and re-compile every request
        # (measured: ~1.5 s per call at GPT-tiny scale, dwarfing the
        # actual decode), which is fatal for the serving path.
        prog_cache, prog_lock = _prog_cache_for(model)
        # greedy ignores the sampling knobs — don't let them split the key
        sampling = ((float(temperature), int(top_k), float(top_p))
                    if do_sample else None)
        # total already encodes max_new_tokens (= P + new); eos is baked
        # into the compiled scan, so it distinguishes programs too
        prog_key = (B, P, total, str(cache_dtype), sampling,
                    None if eos_token_id is None else int(eos_token_id))
        eos = eos_token_id
        with prog_lock:
            progs = prog_cache.get(prog_key)
            if progs is not None:
                prog_cache.move_to_end(prog_key)
        if progs is None:
            progs = build_generate_programs(
                model, P, max_new_tokens, eos, do_sample, temperature,
                top_k, top_p)
            # jit wrapper creation is cheap (compilation happens at the
            # first call, outside the lock); insertion races resolve in
            # favor of the first writer so every thread runs ONE program
            with prog_lock:
                existing = prog_cache.get(prog_key)
                if existing is not None:
                    progs = existing
                    prog_cache.move_to_end(prog_key)
                else:
                    prog_cache[prog_key] = progs
                    while len(prog_cache) > _prog_cache_size():
                        prog_cache.popitem(last=False)
        prefill_c, decode_c = progs

        key = jax.random.PRNGKey(seed)
        key, sub = jax.random.split(key)
        tok, caches = prefill_c(params, buffers, ids, caches, sub)
        if max_new_tokens == 1:
            new = np.asarray(tok)[:, None]
        else:
            toks, _ = decode_c(params, buffers, tok, caches, key)
            new = np.asarray(toks)
        return np.concatenate([ids, new.astype(np.int64)], axis=1)
    finally:
        if was_training:
            model.train()
