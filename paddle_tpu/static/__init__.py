"""paddle.static parity shim (SURVEY.md §2.8 static API row).

The reference's static-graph stack (Program/Block/Operator protobuf IR +
StandaloneExecutor, SURVEY.md L3/L5) does not exist here by design: "static
graph" IS the traced XLA program (SURVEY.md §7 design stance — one runtime,
not four). This module keeps the API names ported code reaches for:

- InputSpec — shared with paddle.jit.
- save_inference_model / load_inference_model — the deployment artifact
  (StableHLO + params), same files paddle_tpu.inference.Predictor loads
  (reference: python/paddle/static/io.py).
- default_main_program/Program/Executor — thin objects for code that only
  touches them ceremonially (guard scopes, exe.run over a to_static'd
  callable); anything deeper raises with guidance to paddle.jit.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

from ..core.tensor import Tensor
from ..jit.api import InputSpec, TranslatedLayer
from ..jit.api import load as _jit_load
from ..jit.api import save as _jit_save
from ..nn.layer_base import Layer

__all__ = ["InputSpec", "save_inference_model", "load_inference_model",
           "Program", "Executor", "default_main_program",
           "default_startup_program", "program_guard", "data"]


def save_inference_model(path_prefix: str, feed_vars, fetch_vars=None,
                         executor=None, program=None, **kwargs):
    """Parity: paddle.static.save_inference_model (static/io.py).

    TPU-native signature: `feed_vars` is the Layer to export (or a list of
    InputSpec when `program` carries the layer); `fetch_vars` may be the
    input_spec list. Writes <path>.pdmodel + <path>.pdiparams.
    """
    if isinstance(feed_vars, Layer):
        layer, input_spec = feed_vars, fetch_vars
    elif isinstance(program, Layer):
        layer, input_spec = program, feed_vars
    else:
        raise TypeError(
            "save_inference_model here exports a Layer traced to StableHLO:"
            " pass save_inference_model(path, layer, [InputSpec(...)]) — "
            "there is no ProgramDesc IR in this framework (jit tracing "
            "replaces it; see paddle_tpu.jit.save)")
    _jit_save(layer, path_prefix, input_spec=input_spec)
    return path_prefix


def load_inference_model(path_prefix: str, executor=None, **kwargs):
    """Parity: paddle.static.load_inference_model — returns the loaded
    program (a TranslatedLayer callable)."""
    return _jit_load(path_prefix)


class Program:
    """Ceremonial Program object (reference: framework.py Program). The
    traced-program runtime has no mutable graph to expose."""

    def __init__(self):
        self._callable = None

    def global_block(self):
        raise NotImplementedError(
            "Program.global_block: there is no op-level IR — build models "
            "as Layers and compile with paddle.jit.to_static")

    def clone(self, for_test=False):
        return self


_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


class program_guard:
    """Ceremonial context manager (static-graph code often wraps model
    construction in it; construction here is ordinary eager python)."""

    def __init__(self, main_program=None, startup_program=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def data(name: str, shape, dtype="float32", lod_level=0) -> InputSpec:
    """Parity: paddle.static.data — returns an InputSpec usable with
    jit.save/to_static input_spec."""
    return InputSpec(shape, dtype=dtype, name=name)


class Executor:
    """Parity shim: paddle.static.Executor (fluid/executor.py:921).

    run() executes a compiled callable (TranslatedLayer or a to_static'd
    Layer) over a feed dict — covering the exe.run(program, feed, fetch)
    pattern for inference-style code. Training-style Program mutation has
    no analog; use paddle.jit.TrainStep.
    """

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True):
        import numpy as np
        if not callable(program):
            raise TypeError(
                "Executor.run needs a callable program (TranslatedLayer "
                "from load_inference_model, or a @to_static Layer)")
        feed = feed or {}
        args = [v for v in feed.values()]
        out = program(*[Tensor(a) if not isinstance(a, Tensor) else a
                        for a in args])
        outs = out if isinstance(out, (list, tuple)) else [out]
        if return_numpy:
            return [o.numpy() if isinstance(o, Tensor) else np.asarray(o)
                    for o in outs]
        return list(outs)
