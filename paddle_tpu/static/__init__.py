"""paddle.static parity shim (SURVEY.md §2.8 static API row).

The reference's static-graph stack (Program/Block/Operator protobuf IR +
StandaloneExecutor, SURVEY.md L3/L5) does not exist here by design: "static
graph" IS the traced XLA program (SURVEY.md §7 design stance — one runtime,
not four). This module keeps the API names ported code reaches for:

- InputSpec — shared with paddle.jit.
- save_inference_model / load_inference_model — the deployment artifact
  (StableHLO + params), same files paddle_tpu.inference.Predictor loads
  (reference: python/paddle/static/io.py).
- default_main_program/Program/Executor — thin objects for code that only
  touches them ceremonially (guard scopes, exe.run over a to_static'd
  callable); anything deeper raises with guidance to paddle.jit.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

from ..core.tensor import Tensor
from ..jit.api import InputSpec, TranslatedLayer
from ..jit.api import load as _jit_load
from ..jit.api import save as _jit_save
from ..nn.layer_base import Layer, ParamAttr
from . import nn

__all__ = ["nn", "InputSpec", "save_inference_model", "load_inference_model",
           "Program", "Executor", "default_main_program",
           "default_startup_program", "program_guard", "data",
           "Variable", "BuildStrategy", "ExecutionStrategy", "CompiledProgram", "ParallelExecutor", "IpuCompiledProgram", "IpuStrategy", "ipu_shard_guard", "set_ipu_shard", "WeightNormParamAttr", "ExponentialMovingAverage", "create_parameter", "create_global_var", "accuracy", "auc", "ctr_metric_bundle", "Print", "py_func", "cpu_places", "cuda_places", "npu_places", "xpu_places", "mlu_places", "global_scope", "scope_guard", "name_scope", "device_guard", "append_backward", "gradients", "exponential_decay", "serialize_program", "deserialize_program", "serialize_persistables", "deserialize_persistables", "normalize_program", "save", "load", "load_program_state", "set_program_state", "save_to_file", "load_from_file"]


def save_inference_model(path_prefix: str, feed_vars, fetch_vars=None,
                         executor=None, program=None, **kwargs):
    """Parity: paddle.static.save_inference_model (static/io.py).

    TPU-native signature: `feed_vars` is the Layer to export (or a list of
    InputSpec when `program` carries the layer); `fetch_vars` may be the
    input_spec list. Writes <path>.pdmodel + <path>.pdiparams.
    """
    if isinstance(feed_vars, Layer):
        layer, input_spec = feed_vars, fetch_vars
    elif isinstance(program, Layer):
        layer, input_spec = program, feed_vars
    else:
        raise TypeError(
            "save_inference_model here exports a Layer traced to StableHLO:"
            " pass save_inference_model(path, layer, [InputSpec(...)]) — "
            "there is no ProgramDesc IR in this framework (jit tracing "
            "replaces it; see paddle_tpu.jit.save)")
    _jit_save(layer, path_prefix, input_spec=input_spec)
    return path_prefix


def load_inference_model(path_prefix: str, executor=None, **kwargs):
    """Parity: paddle.static.load_inference_model — returns the loaded
    program (a TranslatedLayer callable)."""
    return _jit_load(path_prefix)


class Program:
    """Ceremonial Program object (reference: framework.py Program). The
    traced-program runtime has no mutable graph to expose."""

    def __init__(self):
        self._callable = None

    def global_block(self):
        raise NotImplementedError(
            "Program.global_block: there is no op-level IR — build models "
            "as Layers and compile with paddle.jit.to_static")

    def clone(self, for_test=False):
        return self


_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


class program_guard:
    """Ceremonial context manager (static-graph code often wraps model
    construction in it; construction here is ordinary eager python)."""

    def __init__(self, main_program=None, startup_program=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def data(name: str, shape, dtype="float32", lod_level=0) -> InputSpec:
    """Parity: paddle.static.data — returns an InputSpec usable with
    jit.save/to_static input_spec."""
    return InputSpec(shape, dtype=dtype, name=name)


class Executor:
    """Parity shim: paddle.static.Executor (fluid/executor.py:921).

    run() executes a compiled callable (TranslatedLayer or a to_static'd
    Layer) over a feed dict — covering the exe.run(program, feed, fetch)
    pattern for inference-style code. Training-style Program mutation has
    no analog; use paddle.jit.TrainStep.
    """

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True):
        import numpy as np
        if not callable(program):
            raise TypeError(
                "Executor.run needs a callable program (TranslatedLayer "
                "from load_inference_model, or a @to_static Layer)")
        feed = feed or {}
        args = [v for v in feed.values()]
        out = program(*[Tensor(a) if not isinstance(a, Tensor) else a
                        for a in args])
        outs = out if isinstance(out, (list, tuple)) else [out]
        if return_numpy:
            return [o.numpy() if isinstance(o, Tensor) else np.asarray(o)
                    for o in outs]
        return list(outs)


# ---------------------------------------------------------------------------
# static API long tail (reference: python/paddle/static/__init__.py).
# The Program-IR machinery is collapsed into jit tracing (SURVEY §7), so
# these fall into three groups: real dygraph-equivalent implementations
# (EMA, create_parameter, save/load state, py_func, metrics), harmless
# ceremony (scopes/guards/places), and Program-surgery entry points that
# raise with guidance.
# ---------------------------------------------------------------------------

Variable = Tensor  # static Variable == Tensor in the collapsed runtime


class BuildStrategy:
    """Parity shim: framework BuildStrategy — XLA owns fusion/scheduling;
    attributes are accepted and recorded."""

    def __init__(self):
        self.__dict__["_opts"] = {}

    def __setattr__(self, k, v):
        self._opts[k] = v

    def __getattr__(self, k):
        try:
            return self.__dict__["_opts"][k]
        except KeyError:
            raise AttributeError(k)


class ExecutionStrategy(BuildStrategy):
    """Parity shim: ExecutionStrategy."""


class CompiledProgram:
    """Parity shim: CompiledProgram — jit compilation happens per call;
    wraps the program/layer unchanged."""

    def __init__(self, program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy


class ParallelExecutor:
    def __init__(self, *a, **kw):
        raise NotImplementedError(
            "ParallelExecutor is superseded: multi-device execution is "
            "expressed with paddle_tpu.distributed (mesh + "
            "ParallelTrainStep), not a graph executor")


class IpuCompiledProgram:
    def __init__(self, *a, **kw):
        raise NotImplementedError("IPU support is not part of this build")


class IpuStrategy(IpuCompiledProgram):
    pass


def ipu_shard_guard(*a, **kw):
    raise NotImplementedError("IPU support is not part of this build")


def set_ipu_shard(*a, **kw):
    raise NotImplementedError("IPU support is not part of this build")


class WeightNormParamAttr(ParamAttr):
    """Parity: static WeightNormParamAttr — records the weight-norm dim
    (apply weight norm with nn.utils in the dygraph runtime)."""

    def __init__(self, dim=None, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim


class ExponentialMovingAverage:
    """Parity: static/ema.py ExponentialMovingAverage — shadow variables
    with bias-corrected decay, apply/restore context."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._thres_steps = thres_steps
        self._step = 0
        self._shadow = {}
        self._backup = {}
        self._params = []

    def _collect(self, parameters=None):
        if parameters is not None:
            self._params = list(parameters)
        return self._params

    def update(self, parameters=None):
        import jax.numpy as jnp
        params = self._collect(parameters)
        assert params, ("pass `parameters` on the first update() — the "
                        "static Program scan does not exist here")
        self._step += 1
        # the (1+t)/(10+t) warmup ramp only applies when thres_steps is
        # given (reference static/ema.py); default is fixed decay
        d = self._decay if self._thres_steps is None else min(
            self._decay, (1.0 + self._step) / (10.0 + self._step))
        import jax.numpy as jnp
        for p in params:
            pid = id(p)
            prev = self._shadow.get(pid)
            # jnp.copy: donated optimizer buffers must not be retained
            self._shadow[pid] = jnp.copy(p.value) if prev is None else (
                d * prev + (1.0 - d) * p.value)

    def apply(self, executor=None, need_restore=True):
        class _Ctx:
            def __init__(ctx):
                pass

            def __enter__(ctx):
                import jax.numpy as jnp
                for p in self._params:
                    self._backup[id(p)] = p.value
                    if id(p) in self._shadow:
                        p.value = jnp.copy(self._shadow[id(p)])
                return ctx

            def __exit__(ctx, *exc):
                if need_restore:
                    self.restore()
                return False

        return _Ctx()

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p.value = self._backup.pop(id(p))


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    from ..tensor.parity_extras import create_parameter as _cp
    return _cp(shape, dtype, name, attr, is_bias, default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """Parity: static create_global_var — a named persistent tensor."""
    import jax.numpy as jnp
    from ..framework.dtype import convert_dtype
    t = Tensor(jnp.full(tuple(shape), value, convert_dtype(dtype)))
    t.name = name or "global_var"
    t.persistable = persistable
    return t


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    from ..metric import accuracy as _acc
    return _acc(input, label, k)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1, name=None):
    """Parity: static auc — batch AUC of predictions vs labels."""
    from ..metric import Auc as _Auc
    import numpy as np
    m = _Auc(curve=curve, num_thresholds=num_thresholds)
    m.update(np.asarray(input.value), np.asarray(label.value))
    import jax.numpy as jnp
    return Tensor(jnp.asarray(m.accumulate(), jnp.float32))


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    raise NotImplementedError(
        "ctr_metric_bundle belongs to the PS/CTR pipeline, which is "
        "deferred in this build (SURVEY §2.6 PS row)")


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """Parity: static.Print — identity that logs the tensor."""
    import jax
    def cb(v):
        print(f"{message or 'Print'}: shape={list(v.shape)} "
              f"dtype={v.dtype}\n{v}")
        return v
    jax.debug.callback(lambda v: cb(v), input.value)
    return input


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Parity: static.py_func — host python inside a traced program via
    pure_callback. `out` may be one spec/Tensor or a list of them
    (reference supports multiple outputs; common.py py_func)."""
    import jax
    xs = x if isinstance(x, (list, tuple)) else [x]
    raw = [t.value for t in xs]

    def _spec(o):
        return (jax.ShapeDtypeStruct(tuple(o.shape), o.value.dtype)
                if hasattr(o, "value") else o)

    multi = isinstance(out, (list, tuple))
    spec = ([_spec(o) for o in out] if multi else _spec(out))
    res = jax.pure_callback(
        lambda *vs: func(*vs), spec, *raw, vmap_method=None)
    if multi:
        return [Tensor(r) for r in res]
    return Tensor(res)


def cpu_places(device_count=None):
    from ..tensor.parity_extras import CPUPlace
    import os
    n = device_count or int(os.environ.get("CPU_NUM", 1))
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    from ..tensor.parity_extras import CUDAPlace
    ids = device_ids if device_ids is not None else [0]
    return [CUDAPlace(i) for i in ids]


def npu_places(device_ids=None):
    from ..tensor.parity_extras import NPUPlace
    ids = device_ids if device_ids is not None else [0]
    return [NPUPlace(i) for i in ids]


def xpu_places(device_ids=None):
    from ..device import XPUPlace
    ids = device_ids if device_ids is not None else [0]
    return [XPUPlace(i) for i in ids]


def mlu_places(device_ids=None):
    from ..device import MLUPlace
    ids = device_ids if device_ids is not None else [0]
    return [MLUPlace(i) for i in ids]


class _Scope(dict):
    def var(self, name):
        return self.setdefault(name, Tensor.__new__(Tensor))

    def find_var(self, name):
        return self.get(name)


_global_scope = _Scope()


def global_scope():
    """Parity: static.global_scope."""
    return _global_scope


class scope_guard:
    """Parity: static.scope_guard."""

    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        global _global_scope
        self._prev = _global_scope
        _global_scope = self.scope
        return self.scope

    def __exit__(self, *exc):
        global _global_scope
        _global_scope = self._prev
        return False


class name_scope:
    """Parity: static.name_scope — names traced programs for debugging
    (jax.named_scope under jit)."""

    def __init__(self, prefix=None):
        import jax
        self._ctx = jax.named_scope(prefix or "scope")

    def __enter__(self):
        return self._ctx.__enter__()

    def __exit__(self, *exc):
        return self._ctx.__exit__(*exc)


class device_guard:
    """Parity: static.device_guard — placement is PJRT's; accepted and
    ignored with a note."""

    def __init__(self, device=None):
        self.device = device

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    raise NotImplementedError(
        "append_backward rewrites a static Program; this runtime has no "
        "Program IR — use loss.backward() (eager) or jax gradients "
        "inside jit (jit.TrainStep)")


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Parity: static.gradients — eager equivalent via autograd.grad."""
    from ..autograd import grad as _grad
    return _grad(targets, inputs, grad_outputs=target_gradients)


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """Parity: static exponential_decay — staircase holds the LR within
    each decay_steps bucket (StepDecay); continuous applies the per-step
    root of decay_rate (ExponentialDecay)."""
    from ..optimizer import lr as _lr
    if staircase:
        return _lr.StepDecay(learning_rate=learning_rate,
                             step_size=decay_steps, gamma=decay_rate)
    return _lr.ExponentialDecay(
        learning_rate=learning_rate,
        gamma=decay_rate ** (1.0 / decay_steps))


# ---- program/state serialization over the jit StableHLO path ----------

def serialize_program(feed_vars, fetch_vars, program=None, **kwargs):
    raise NotImplementedError(
        "program serialization is the jit path here: use "
        "paddle_tpu.jit.save / static.save_inference_model (StableHLO)")


def deserialize_program(data):
    raise NotImplementedError(
        "use paddle_tpu.jit.load / static.load_inference_model")


def serialize_persistables(feed_vars, fetch_vars, program=None, **kwargs):
    raise NotImplementedError(
        "use static.save / paddle_tpu.save for parameter state")


def deserialize_persistables(program, data, executor=None):
    raise NotImplementedError(
        "use static.load / paddle_tpu.load for parameter state")


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    return program  # traced programs are already normalized


def save(program_or_layer, model_path, protocol=4, **configs):
    """Parity: static.save — persist a Layer/Program's parameter state."""
    from .. import io as io_mod
    target = getattr(program_or_layer, "layer", program_or_layer)
    state = target.state_dict() if hasattr(target, "state_dict") else {}
    io_mod.save(state, model_path + ".pdparams")


def load(program_or_layer, model_path, executor=None, var_list=None):
    """Parity: static.load."""
    from .. import io as io_mod
    state = io_mod.load(model_path + ".pdparams")
    target = getattr(program_or_layer, "layer", program_or_layer)
    if hasattr(target, "set_state_dict"):
        target.set_state_dict(state)
    return state


def load_program_state(model_path, var_list=None):
    """Parity: static.load_program_state."""
    from .. import io as io_mod
    return io_mod.load(model_path + ".pdparams")


def set_program_state(program_or_layer, state_dict):
    """Parity: static.set_program_state."""
    target = getattr(program_or_layer, "layer", program_or_layer)
    if hasattr(target, "set_state_dict"):
        target.set_state_dict(state_dict)


def save_to_file(path, content):
    """Parity: static.save_to_file."""
    with open(path, "wb") as f:
        f.write(content if isinstance(content, bytes) else bytes(content))


def load_from_file(path):
    """Parity: static.load_from_file."""
    with open(path, "rb") as f:
        return f.read()
