"""paddle.static.nn control-flow combinators, TPU-native.

Parity: python/paddle/static/nn/control_flow.py — `cond` (:873),
`while_loop` (:401), `case` (:564), `switch_case` (:697), `Assert` (:43),
backed in the reference by the conditional_block/while ops
(paddle/fluid/operators/controlflow/conditional_block_op.cc, while_op.cc).

TPU-first design: there is no Program IR to splice sub-blocks into. With
concrete (eager) values the chosen branch simply runs — the define-by-run
tape records it, so gradients flow through whichever branch executed
(matching the reference's dygraph fast path). Inside a traced program
(`paddle.jit.to_static`, `TrainStep`, `jax.jit`) the predicate is an
abstract tracer, and the combinators lower to XLA's native control flow:
`lax.cond` / `lax.switch` for branches (reverse-differentiable) and
`lax.while_loop` for data-dependent loops (forward-differentiable only —
reverse through a dynamic-trip-count loop needs eager unrolling, same
restriction XLA itself has).

Branch/body callables may close over any Tensors in scope; their outputs
must share one tree structure across branches, like the reference requires.
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor

__all__ = ["cond", "while_loop", "case", "switch_case", "Assert"]


def _raw(x):
    return x.value if isinstance(x, Tensor) else x


def _is_tracer(x) -> bool:
    return isinstance(_raw(x), jax.core.Tracer)


def _is_tensor_leaf(x) -> bool:
    return isinstance(x, Tensor)


def _flatten(out) -> Tuple[list, Any]:
    """Flatten a branch output into raw jax leaves + treedef."""
    leaves, tree = jax.tree_util.tree_flatten(out, is_leaf=_is_tensor_leaf)
    return [jnp.asarray(_raw(l)) for l in leaves], tree


def _unflatten(tree, raw_leaves, wrap=True):
    leaves = [Tensor(v, stop_gradient=True) if wrap else v
              for v in raw_leaves]
    return jax.tree_util.tree_unflatten(tree, leaves)


def _scalar_bool(v, api: str):
    v = jnp.asarray(_raw(v))
    if v.size != 1:
        raise ValueError(
            f"The pred/condition of {api} must be a boolean tensor with "
            f"one element (shape [] or [1]), got shape {list(v.shape)}.")
    return v.reshape(()).astype(jnp.bool_)


def cond(pred, true_fn: Optional[Callable] = None,
         false_fn: Optional[Callable] = None, name: Optional[str] = None,
         return_names=None):
    """Run ``true_fn()`` if ``pred`` else ``false_fn()``.

    Parity: paddle.static.nn.cond (static/nn/control_flow.py:873).
    Concrete pred: executes ONE branch eagerly (dygraph semantics,
    tape-differentiable). Tracer pred: lowers to `lax.cond`, both branches
    traced into the program, reverse-differentiable through `jax.vjp`.
    """
    if true_fn is not None and not callable(true_fn):
        raise TypeError("The true_fn in cond must be callable.")
    if false_fn is not None and not callable(false_fn):
        raise TypeError("The false_fn in cond must be callable.")
    true_fn = true_fn or (lambda: None)
    false_fn = false_fn or (lambda: None)

    if not _is_tracer(pred):
        p = bool(_scalar_bool(pred, "cond"))
        return true_fn() if p else false_fn()

    p = _scalar_bool(pred, "cond")
    trees: List[Any] = []

    def _branch(fn):
        def run(_):
            raw, tree = _flatten(fn())
            trees.append(tree)
            return tuple(raw)
        return run

    try:
        out = lax.cond(p, _branch(true_fn), _branch(false_fn), None)
    except TypeError as e:
        if len(trees) == 2 and trees[0] != trees[1]:
            raise TypeError(
                "Incompatible return values of true_fn and false_fn in "
                f"cond: {trees[0]} vs {trees[1]} (the two branches must "
                "return one common structure of Tensors, reference "
                "control_flow.py:873)") from e
        raise
    if len(trees) == 2 and trees[0] != trees[1]:
        raise TypeError(
            "Incompatible return values of true_fn and false_fn in cond: "
            f"{trees[0]} vs {trees[1]}")
    return _unflatten(trees[0], out)


def while_loop(cond: Callable, body: Callable, loop_vars: Sequence,
               is_test: bool = False, name: Optional[str] = None):
    """``while cond(*loop_vars): loop_vars = body(*loop_vars)``.

    Parity: paddle.static.nn.while_loop (static/nn/control_flow.py:401;
    runtime op paddle/fluid/operators/controlflow/while_op.cc). Concrete
    values: a Python loop, each iteration recorded on the tape (so
    reverse-mode works by unrolling). Traced values: `lax.while_loop`
    (forward-differentiable; reverse-mode through a dynamic trip count is
    structurally impossible in one XLA program — run eagerly for that).
    """
    if not callable(cond):
        raise TypeError("The cond in while_loop must be callable.")
    if not callable(body):
        raise TypeError("The body in while_loop must be callable.")
    if not isinstance(loop_vars, (list, tuple)) or not loop_vars:
        raise ValueError("loop_vars in while_loop must be a non-empty "
                         "list/tuple.")
    loop_vars = list(loop_vars)

    first = cond(*loop_vars)
    traced = _is_tracer(first) or any(
        _is_tracer(l) for l in jax.tree_util.tree_leaves(
            loop_vars, is_leaf=_is_tensor_leaf))

    if not traced:
        vals = loop_vars
        keep = bool(jnp.asarray(_raw(first)).reshape(()))
        while keep:
            out = body(*vals)
            out = list(out) if isinstance(out, (list, tuple)) else [out]
            if len(out) != len(vals):
                raise ValueError(
                    f"body in while_loop returned {len(out)} values, "
                    f"expected {len(vals)} (must match loop_vars).")
            vals = out
            keep = bool(jnp.asarray(_raw(cond(*vals))).reshape(()))
        return vals

    flat0, tree = _flatten(loop_vars)

    def c(flat):
        vars_ = _unflatten(tree, flat)
        return _scalar_bool(cond(*vars_), "while_loop")

    def b(flat):
        vars_ = _unflatten(tree, flat)
        out = body(*vars_)
        out = list(out) if isinstance(out, (list, tuple)) else [out]
        raw, tree2 = _flatten(out)
        if tree2 != tree:
            raise TypeError(
                "body in while_loop must return the same structure as "
                f"loop_vars: got {tree2}, expected {tree}")
        return tuple(raw)

    res = lax.while_loop(c, b, tuple(flat0))
    return _unflatten(tree, res)


def case(pred_fn_pairs, default: Optional[Callable] = None,
         name: Optional[str] = None):
    """if-elif-else chain: first fn whose pred is True runs.

    Parity: paddle.static.nn.case (static/nn/control_flow.py:564) — when
    ``default`` is None the LAST fn in ``pred_fn_pairs`` serves as the
    default, exactly like the reference. Built as a fold of `cond`, so it
    inherits cond's eager/traced duality.
    """
    if not isinstance(pred_fn_pairs, (list, tuple)):
        raise TypeError("pred_fn_pairs in case must be a list or tuple.")
    pairs = []
    for item in pred_fn_pairs:
        if not isinstance(item, tuple) or len(item) != 2:
            raise TypeError("each element of pred_fn_pairs must be a "
                            "(pred, fn) 2-tuple.")
        pred, fn = item
        if not callable(fn):
            raise TypeError("The fn of each pred_fn_pair in case must be "
                            "callable.")
        pairs.append((pred, fn))
    if not pairs:
        raise ValueError("pred_fn_pairs in case must be non-empty.")
    if default is None:
        default = pairs[-1][1]
        pairs = pairs[:-1]
    elif not callable(default):
        raise TypeError("The default in case must be callable.")

    chain = default
    for pred, fn in reversed(pairs):
        def chain(p=pred, tf=fn, ff=chain):
            return cond(p, tf, ff)
    return chain()


def switch_case(branch_index, branch_fns, default: Optional[Callable] = None,
                name: Optional[str] = None):
    """Run the fn whose key matches ``branch_index``.

    Parity: paddle.static.nn.switch_case (static/nn/control_flow.py:697):
    ``branch_fns`` is a list of callables (keys 0..n-1) or of (int, fn)
    pairs; a missing ``default`` means the fn with the MAX key. Concrete
    index: direct dispatch. Tracer index: one `lax.switch` (native XLA
    multi-way branch; reverse-differentiable).
    """
    if not isinstance(branch_fns, (list, tuple)):
        raise TypeError("branch_fns in switch_case must be a list or tuple.")
    items = list(branch_fns)
    if items and not isinstance(items[0], tuple):
        items = list(enumerate(items))
    keys, fns = [], []
    for item in items:
        if not isinstance(item, tuple) or len(item) != 2:
            raise TypeError("each element of branch_fns must be an "
                            "(int, callable) 2-tuple or a plain callable.")
        k, fn = item
        if not isinstance(k, int):
            raise TypeError(f"branch key must be int, got {type(k)}.")
        if k in keys:
            raise ValueError(f"duplicate branch key {k} in switch_case.")
        if not callable(fn):
            raise TypeError("each branch fn in switch_case must be callable.")
        keys.append(k)
        fns.append(fn)
    if not keys:
        raise ValueError("branch_fns in switch_case must be non-empty.")
    if default is not None and not callable(default):
        raise TypeError("The default in switch_case must be callable.")
    # reference semantics: a missing default means the fn with the MAX key
    i_max = max(range(len(keys)), key=lambda i: keys[i])

    idx_raw = _raw(branch_index)
    if not _is_tracer(branch_index):
        k = int(jnp.asarray(idx_raw).reshape(()))
        for key, fn in zip(keys, fns):
            if key == k:
                return fn()
        return default() if default is not None else fns[i_max]()

    idx = jnp.asarray(idx_raw).reshape(()).astype(jnp.int32)
    # map the user key space onto dense positions; unmatched keys fall back
    # to the default slot (an extra branch, or the max-key branch — never
    # traced twice)
    branches = fns + ([default] if default is not None else [])
    sel = jnp.int32(len(fns) if default is not None else i_max)
    for pos, key in enumerate(keys):
        sel = jnp.where(idx == key, jnp.int32(pos), sel)

    trees: List[Any] = []

    def _branch(fn):
        def run(_):
            raw, tree = _flatten(fn())
            trees.append(tree)
            return tuple(raw)
        return run

    out = lax.switch(sel, [_branch(f) for f in branches], None)
    if any(t != trees[0] for t in trees[1:]):
        raise TypeError(
            "all branch fns of switch_case must return one common "
            f"structure of Tensors, got {trees}")
    return _unflatten(trees[0], out)


def Assert(cond, data=None, summarize: int = 20, name: Optional[str] = None):
    """Assert ``cond`` holds at runtime; on failure print ``data`` and raise.

    Parity: paddle.static.nn.Assert (static/nn/control_flow.py:43;
    paddle/fluid/operators/assert_op.cc). Concrete cond raises directly;
    a traced cond checks on the host via `jax.debug.callback` when the
    program runs.
    """
    vals = [jnp.asarray(_raw(d)) for d in (data or [])]

    def _fail(*ds):
        shown = []
        for d in ds:
            flat = jnp.ravel(d)
            head = flat[:summarize] if summarize >= 0 else flat
            shown.append(str(head))
        raise ValueError(
            "Assert failed" + (f" ({name})" if name else "") +
            (": " + "; ".join(shown) if shown else ""))

    if not _is_tracer(cond) and not any(isinstance(v, jax.core.Tracer)
                                        for v in vals):
        if not bool(jnp.asarray(_raw(cond)).reshape(())):
            _fail(*vals)
        return None

    def _check(c, *ds):
        if not bool(c):
            _fail(*ds)

    jax.debug.callback(_check, _scalar_bool(cond, "Assert"), *vals)
    return None
