"""paddle.static.nn control-flow combinators, TPU-native.

Parity: python/paddle/static/nn/control_flow.py — `cond` (:873),
`while_loop` (:401), `case` (:564), `switch_case` (:697), `Assert` (:43),
backed in the reference by the conditional_block/while ops
(paddle/fluid/operators/controlflow/conditional_block_op.cc, while_op.cc).

TPU-first design: there is no Program IR to splice sub-blocks into. With
concrete (eager) values the chosen branch simply runs — the define-by-run
tape records it, so gradients flow through whichever branch executed
(matching the reference's dygraph fast path). Inside a traced program
(`paddle.jit.to_static`, `TrainStep`, `jax.jit`) the predicate is an
abstract tracer, and the combinators lower to XLA's native control flow:
`lax.cond` / `lax.switch` for branches (reverse-differentiable) and
`lax.while_loop` for data-dependent loops (forward-differentiable only —
reverse through a dynamic-trip-count loop needs eager unrolling, same
restriction XLA itself has).

Branch/body callables may close over any Tensors in scope; their outputs
must share one tree structure across branches, like the reference requires.
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor

__all__ = ["cond", "while_loop", "case", "switch_case", "Assert"]


def _raw(x):
    return x.value if isinstance(x, Tensor) else x


def _is_tracer(x) -> bool:
    return isinstance(_raw(x), jax.core.Tracer)


def _is_tensor_leaf(x) -> bool:
    return isinstance(x, Tensor)


def _flatten(out) -> Tuple[list, Any]:
    """Flatten a branch output into raw jax leaves + treedef."""
    leaves, tree = jax.tree_util.tree_flatten(out, is_leaf=_is_tensor_leaf)
    return [jnp.asarray(_raw(l)) for l in leaves], tree


def _unflatten(tree, raw_leaves, wrap=True):
    leaves = [Tensor(v, stop_gradient=True) if wrap else v
              for v in raw_leaves]
    return jax.tree_util.tree_unflatten(tree, leaves)


def _scalar_bool(v, api: str):
    v = jnp.asarray(_raw(v))
    if v.size != 1:
        raise ValueError(
            f"The pred/condition of {api} must be a boolean tensor with "
            f"one element (shape [] or [1]), got shape {list(v.shape)}.")
    return v.reshape(()).astype(jnp.bool_)


def cond(pred, true_fn: Optional[Callable] = None,
         false_fn: Optional[Callable] = None, name: Optional[str] = None,
         return_names=None):
    """Run ``true_fn()`` if ``pred`` else ``false_fn()``.

    Parity: paddle.static.nn.cond (static/nn/control_flow.py:873).
    Concrete pred: executes ONE branch eagerly (dygraph semantics,
    tape-differentiable). Tracer pred: lowers to `lax.cond`, both branches
    traced into the program, reverse-differentiable through `jax.vjp`.
    """
    if true_fn is not None and not callable(true_fn):
        raise TypeError("The true_fn in cond must be callable.")
    if false_fn is not None and not callable(false_fn):
        raise TypeError("The false_fn in cond must be callable.")
    true_fn = true_fn or (lambda: None)
    false_fn = false_fn or (lambda: None)

    if not _is_tracer(pred):
        p = bool(_scalar_bool(pred, "cond"))
        return true_fn() if p else false_fn()

    p = _scalar_bool(pred, "cond")
    trees: List[Any] = []

    def _branch(fn):
        def run(_):
            raw, tree = _flatten(fn())
            trees.append(tree)
            return tuple(raw)
        return run

    try:
        out = lax.cond(p, _branch(true_fn), _branch(false_fn), None)
    except TypeError as e:
        if len(trees) == 2 and trees[0] != trees[1]:
            raise TypeError(
                "Incompatible return values of true_fn and false_fn in "
                f"cond: {trees[0]} vs {trees[1]} (the two branches must "
                "return one common structure of Tensors, reference "
                "control_flow.py:873)") from e
        raise
    if len(trees) == 2 and trees[0] != trees[1]:
        raise TypeError(
            "Incompatible return values of true_fn and false_fn in cond: "
            f"{trees[0]} vs {trees[1]}")
    return _unflatten(trees[0], out)


def while_loop(cond: Callable, body: Callable, loop_vars: Sequence,
               is_test: bool = False, name: Optional[str] = None):
    """``while cond(*loop_vars): loop_vars = body(*loop_vars)``.

    Parity: paddle.static.nn.while_loop (static/nn/control_flow.py:401;
    runtime op paddle/fluid/operators/controlflow/while_op.cc). Concrete
    values: a Python loop, each iteration recorded on the tape (so
    reverse-mode works by unrolling). Traced values: `lax.while_loop`
    (forward-differentiable; reverse-mode through a dynamic trip count is
    structurally impossible in one XLA program — run eagerly for that).
    """
    if not callable(cond):
        raise TypeError("The cond in while_loop must be callable.")
    if not callable(body):
        raise TypeError("The body in while_loop must be callable.")
    if not isinstance(loop_vars, (list, tuple)) or not loop_vars:
        raise ValueError("loop_vars in while_loop must be a non-empty "
                         "list/tuple.")
    loop_vars = list(loop_vars)

    first = cond(*loop_vars)
    traced = _is_tracer(first) or any(
        _is_tracer(l) for l in jax.tree_util.tree_leaves(
            loop_vars, is_leaf=_is_tensor_leaf))

    if not traced:
        vals = loop_vars
        keep = bool(jnp.asarray(_raw(first)).reshape(()))
        while keep:
            out = body(*vals)
            out = list(out) if isinstance(out, (list, tuple)) else [out]
            if len(out) != len(vals):
                raise ValueError(
                    f"body in while_loop returned {len(out)} values, "
                    f"expected {len(vals)} (must match loop_vars).")
            vals = out
            keep = bool(jnp.asarray(_raw(cond(*vals))).reshape(()))
        return vals

    flat0, tree = _flatten(loop_vars)

    def c(flat):
        vars_ = _unflatten(tree, flat)
        return _scalar_bool(cond(*vars_), "while_loop")

    def b(flat):
        vars_ = _unflatten(tree, flat)
        out = body(*vars_)
        out = list(out) if isinstance(out, (list, tuple)) else [out]
        raw, tree2 = _flatten(out)
        if tree2 != tree:
            raise TypeError(
                "body in while_loop must return the same structure as "
                f"loop_vars: got {tree2}, expected {tree}")
        return tuple(raw)

    res = lax.while_loop(c, b, tuple(flat0))
    return _unflatten(tree, res)


def case(pred_fn_pairs, default: Optional[Callable] = None,
         name: Optional[str] = None):
    """if-elif-else chain: first fn whose pred is True runs.

    Parity: paddle.static.nn.case (static/nn/control_flow.py:564) — when
    ``default`` is None the LAST fn in ``pred_fn_pairs`` serves as the
    default, exactly like the reference. Built as a fold of `cond`, so it
    inherits cond's eager/traced duality.
    """
    if not isinstance(pred_fn_pairs, (list, tuple)):
        raise TypeError("pred_fn_pairs in case must be a list or tuple.")
    pairs = []
    for item in pred_fn_pairs:
        if not isinstance(item, tuple) or len(item) != 2:
            raise TypeError("each element of pred_fn_pairs must be a "
                            "(pred, fn) 2-tuple.")
        pred, fn = item
        if not callable(fn):
            raise TypeError("The fn of each pred_fn_pair in case must be "
                            "callable.")
        pairs.append((pred, fn))
    if not pairs:
        raise ValueError("pred_fn_pairs in case must be non-empty.")
    if default is None:
        default = pairs[-1][1]
        pairs = pairs[:-1]
    elif not callable(default):
        raise TypeError("The default in case must be callable.")

    chain = default
    for pred, fn in reversed(pairs):
        def chain(p=pred, tf=fn, ff=chain):
            return cond(p, tf, ff)
    return chain()


def switch_case(branch_index, branch_fns, default: Optional[Callable] = None,
                name: Optional[str] = None):
    """Run the fn whose key matches ``branch_index``.

    Parity: paddle.static.nn.switch_case (static/nn/control_flow.py:697):
    ``branch_fns`` is a list of callables (keys 0..n-1) or of (int, fn)
    pairs; a missing ``default`` means the fn with the MAX key. Concrete
    index: direct dispatch. Tracer index: one `lax.switch` (native XLA
    multi-way branch; reverse-differentiable).
    """
    if not isinstance(branch_fns, (list, tuple)):
        raise TypeError("branch_fns in switch_case must be a list or tuple.")
    items = list(branch_fns)
    if items and not isinstance(items[0], tuple):
        items = list(enumerate(items))
    keys, fns = [], []
    for item in items:
        if not isinstance(item, tuple) or len(item) != 2:
            raise TypeError("each element of branch_fns must be an "
                            "(int, callable) 2-tuple or a plain callable.")
        k, fn = item
        if not isinstance(k, int):
            raise TypeError(f"branch key must be int, got {type(k)}.")
        if k in keys:
            raise ValueError(f"duplicate branch key {k} in switch_case.")
        if not callable(fn):
            raise TypeError("each branch fn in switch_case must be callable.")
        keys.append(k)
        fns.append(fn)
    if not keys:
        raise ValueError("branch_fns in switch_case must be non-empty.")
    if default is not None and not callable(default):
        raise TypeError("The default in switch_case must be callable.")
    # reference semantics: a missing default means the fn with the MAX key
    i_max = max(range(len(keys)), key=lambda i: keys[i])

    idx_raw = _raw(branch_index)
    if not _is_tracer(branch_index):
        k = int(jnp.asarray(idx_raw).reshape(()))
        for key, fn in zip(keys, fns):
            if key == k:
                return fn()
        return default() if default is not None else fns[i_max]()

    idx = jnp.asarray(idx_raw).reshape(()).astype(jnp.int32)
    # map the user key space onto dense positions; unmatched keys fall back
    # to the default slot (an extra branch, or the max-key branch — never
    # traced twice)
    branches = fns + ([default] if default is not None else [])
    sel = jnp.int32(len(fns) if default is not None else i_max)
    for pos, key in enumerate(keys):
        sel = jnp.where(idx == key, jnp.int32(pos), sel)

    trees: List[Any] = []

    def _branch(fn):
        def run(_):
            raw, tree = _flatten(fn())
            trees.append(tree)
            return tuple(raw)
        return run

    out = lax.switch(sel, [_branch(f) for f in branches], None)
    if any(t != trees[0] for t in trees[1:]):
        raise TypeError(
            "all branch fns of switch_case must return one common "
            f"structure of Tensors, got {trees}")
    return _unflatten(trees[0], out)


def Assert(cond, data=None, summarize: int = 20, name: Optional[str] = None):
    """Assert ``cond`` holds at runtime; on failure print ``data`` and raise.

    Parity: paddle.static.nn.Assert (static/nn/control_flow.py:43;
    paddle/fluid/operators/assert_op.cc). Concrete cond raises directly;
    a traced cond checks on the host via `jax.debug.callback` when the
    program runs.
    """
    vals = [jnp.asarray(_raw(d)) for d in (data or [])]

    def _fail(*ds):
        shown = []
        for d in ds:
            flat = jnp.ravel(d)
            head = flat[:summarize] if summarize >= 0 else flat
            shown.append(str(head))
        raise ValueError(
            "Assert failed" + (f" ({name})" if name else "") +
            (": " + "; ".join(shown) if shown else ""))

    if not _is_tracer(cond) and not any(isinstance(v, jax.core.Tracer)
                                        for v in vals):
        if not bool(jnp.asarray(_raw(cond)).reshape(())):
            _fail(*vals)
        return None

    def _check(c, *ds):
        if not bool(c):
            _fail(*ds)

    jax.debug.callback(_check, _scalar_bool(cond, "Assert"), *vals)
    return None


# ---------------------------------------------------------------------------
# Layer functions (reference: python/paddle/static/nn/common.py). The
# reference's versions splice ops + parameters into the static Program via
# LayerHelper; here each call instantiates the corresponding nn Layer and
# registers it in a module registry so its parameters persist. Calls are
# keyed by `name`: a named call reuses its layer (so a static-style build
# function can run per step), an unnamed call creates a fresh layer under
# an auto-counter name. `paddle.static.nn.build_registry()` exposes the
# created layers (their parameters feed optimizers the way
# Program.all_parameters does in the reference).
# ---------------------------------------------------------------------------

_BUILD_REGISTRY: dict = {}
_AUTO_COUNT: dict = {}

def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None):
    """static.nn re-export of static.py_func (lazy: the package is still
    initializing when this module loads)."""
    from . import py_func as _pf
    return _pf(func, x, out, backward_func, skip_vars_in_backward_input)


__all__ += ["py_func", "fc", "embedding", "batch_norm", "layer_norm", "group_norm",
            "instance_norm", "data_norm", "conv2d", "conv2d_transpose",
            "conv3d", "conv3d_transpose", "prelu",
            "bilinear_tensor_product", "spectral_norm", "deform_conv2d",
            "row_conv", "nce", "sparse_embedding", "StaticRNN",
            "build_registry", "reset_build_registry"]


def build_registry() -> dict:
    """name -> Layer created by the functions below (the role of
    Program.global_block().all_parameters() for optimizer wiring)."""
    return dict(_BUILD_REGISTRY)


def reset_build_registry():
    _BUILD_REGISTRY.clear()
    _AUTO_COUNT.clear()


def _layer(kind: str, name, factory):
    # composite key: the same user `name` on two DIFFERENT layer
    # functions must not collide into one layer
    if name is None:
        n = _AUTO_COUNT.get(kind, 0)
        _AUTO_COUNT[kind] = n + 1
        key = f"{kind}_{n}"
    else:
        key = f"{kind}/{name}"
    layer = _BUILD_REGISTRY.get(key)
    if layer is None:
        layer = factory()
        _BUILD_REGISTRY[key] = layer
    return layer


def _require_nchw(fmt: str, fn: str):
    if fmt not in ("NCHW", "NCDHW", "NCL"):
        raise NotImplementedError(
            f"static.nn.{fn}: only channel-first layouts are wired "
            f"(got {fmt!r}); transpose the input or use the nn Layer "
            "classes directly")


def _act(out, activation):
    if activation is None:
        return out
    from .. import nn as _nn
    fn = getattr(_nn.functional, activation, None)
    if fn is None:
        raise ValueError(f"unknown activation {activation!r}")
    return fn(out)


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """Parity: static.nn.fc (static/nn/common.py) — flattens trailing
    dims, multiplies, sums multiple inputs, optional activation."""
    from .. import nn as _nn
    from ..tensor import manipulation as _m
    xs = x if isinstance(x, (list, tuple)) else [x]
    out = None
    for i, t in enumerate(xs):
        shape = t.shape
        flat = 1
        for d in shape[num_flatten_dims:]:
            flat *= d
        t2 = _m.reshape(t, list(shape[:num_flatten_dims]) + [flat])
        lin = _layer("fc", f"{name}_in{i}" if name else None,
                     lambda: _nn.Linear(flat, size,
                                        weight_attr=weight_attr,
                                        bias_attr=bias_attr))
        y = lin(t2)
        out = y if out is None else out + y
    return _act(out, activation)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32",
              name=None):
    """Parity: static.nn.embedding."""
    from .. import nn as _nn
    emb = _layer("embedding", name,
                 lambda: _nn.Embedding(size[0], size[1],
                                       padding_idx=padding_idx,
                                       weight_attr=param_attr))
    return emb(input)


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=True,
               use_global_stats=False):
    """Parity: static.nn.batch_norm — dimensionality from the input."""
    from .. import nn as _nn
    _require_nchw(data_layout, "batch_norm")
    C = input.shape[1]
    cls = {2: _nn.BatchNorm1D, 3: _nn.BatchNorm1D, 4: _nn.BatchNorm2D,
           5: _nn.BatchNorm3D}[len(input.shape)]
    bn = _layer("batch_norm", name,
                lambda: cls(C, momentum=momentum, epsilon=epsilon))
    # mode follows THIS call: a name-reused layer must not stay stuck in
    # a previous build's is_test mode
    if is_test or use_global_stats:
        bn.eval()
    else:
        bn.train()
    return _act(bn(input), act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    """Parity: static.nn.layer_norm — normalizes dims from
    begin_norm_axis to the end; scale/shift=False drop the affine
    parameters like the reference."""
    from .. import nn as _nn
    shape = list(input.shape[begin_norm_axis:])
    ln = _layer("layer_norm", name, lambda: _nn.LayerNorm(
        shape, epsilon,
        weight_attr=(param_attr if scale else False),
        bias_attr=(bias_attr if shift else False)))
    return _act(ln(input), act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None,
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    from .. import nn as _nn
    _require_nchw(data_layout, "group_norm")
    gn = _layer("group_norm", name,
                lambda: _nn.GroupNorm(groups, input.shape[1], epsilon))
    return _act(gn(input), act)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    from .. import nn as _nn
    C = input.shape[1]
    cls = {3: _nn.InstanceNorm1D, 4: _nn.InstanceNorm2D,
           5: _nn.InstanceNorm3D}[len(input.shape)]
    inorm = _layer("instance_norm", name, lambda: cls(C, epsilon=epsilon))
    return inorm(input)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              enable_scale_and_shift=False, name=None, **kwargs):
    """Parity: static.nn.data_norm (common.py:431) — normalization from
    accumulated batch statistics (batch_size/batch_sum/batch_square_sum
    buffers), the CTR-model normalizer. Stats update eagerly in train
    mode; is_test freezes them."""
    import jax.numpy as jnp
    from .. import nn as _nn
    from ..core.tensor import Tensor

    class _DataNorm(_nn.Layer):
        def __init__(self, C):
            super().__init__()
            self.register_buffer("batch_size",
                                 Tensor(jnp.full((C,), 1e4, jnp.float32)))
            self.register_buffer("batch_sum",
                                 Tensor(jnp.zeros((C,), jnp.float32)))
            self.register_buffer("batch_square_sum",
                                 Tensor(jnp.full((C,), 1e4, jnp.float32)))
            if enable_scale_and_shift:
                self.scale_w = self.create_parameter([C])
                self.bias = self.create_parameter([C], is_bias=True)

        def forward(self, x):
            mean = self.batch_sum.value / self.batch_size.value
            var = (self.batch_square_sum.value / self.batch_size.value
                   - mean * mean)
            y = (x.value - mean) / jnp.sqrt(var + epsilon)
            if enable_scale_and_shift:
                y = y * self.scale_w.value + self.bias.value
            if self.training:
                n = x.shape[0]
                self.batch_size.value = self.batch_size.value + n
                self.batch_sum.value = self.batch_sum.value + \
                    jnp.sum(x.value, axis=0)
                self.batch_square_sum.value = self.batch_square_sum.value \
                    + jnp.sum(x.value * x.value, axis=0)
            return Tensor(y)

    dn = _layer("data_norm", name, lambda: _DataNorm(input.shape[-1]))
    return _act(dn(input), act)


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None, data_format="NCHW"):
    from .. import nn as _nn
    _require_nchw(data_format, "conv2d")
    conv = _layer("conv2d", name,
                  lambda: _nn.Conv2D(input.shape[1], num_filters,
                                     filter_size, stride=stride,
                                     padding=padding, dilation=dilation,
                                     groups=groups,
                                     weight_attr=param_attr,
                                     bias_attr=bias_attr))
    return _act(conv(input), act)


def _deconv_filter_size(output_size, in_hw, stride, padding, dilation, n):
    """filter_size from a requested output_size (reference
    conv2d_transpose semantics): out = (in-1)*s - 2*p + d*(f-1) + 1,
    solved for f."""
    outs = (output_size if isinstance(output_size, (list, tuple))
            else [output_size] * n)
    ss = stride if isinstance(stride, (list, tuple)) else [stride] * n
    ps = padding if isinstance(padding, (list, tuple)) else [padding] * n
    ds = (dilation if isinstance(dilation, (list, tuple))
          else [dilation] * n)
    return [(o - (i - 1) * s + 2 * p - 1) // d + 1
            for o, i, s, p, d in zip(outs, in_hw, ss, ps, ds)]


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCHW"):
    from .. import nn as _nn
    _require_nchw(data_format, "conv2d_transpose")
    if filter_size is None:
        if output_size is None:
            raise ValueError("conv2d_transpose needs filter_size or "
                             "output_size")
        filter_size = _deconv_filter_size(output_size, input.shape[2:],
                                          stride, padding, dilation, 2)
    conv = _layer("conv2d_transpose", name,
                  lambda: _nn.Conv2DTranspose(input.shape[1], num_filters,
                                              filter_size, stride=stride,
                                              padding=padding,
                                              dilation=dilation,
                                              groups=groups,
                                              weight_attr=param_attr,
                                              bias_attr=bias_attr))
    return _act(conv(input), act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None, data_format="NCDHW"):
    from .. import nn as _nn
    _require_nchw(data_format, "conv3d")
    conv = _layer("conv3d", name,
                  lambda: _nn.Conv3D(input.shape[1], num_filters,
                                     filter_size, stride=stride,
                                     padding=padding, dilation=dilation,
                                     groups=groups,
                                     weight_attr=param_attr,
                                     bias_attr=bias_attr))
    return _act(conv(input), act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCDHW"):
    from .. import nn as _nn
    _require_nchw(data_format, "conv3d_transpose")
    if filter_size is None:
        if output_size is None:
            raise ValueError("conv3d_transpose needs filter_size or "
                             "output_size")
        filter_size = _deconv_filter_size(output_size, input.shape[2:],
                                          stride, padding, dilation, 3)
    conv = _layer("conv3d_transpose", name,
                  lambda: _nn.Conv3DTranspose(input.shape[1], num_filters,
                                              filter_size, stride=stride,
                                              padding=padding,
                                              dilation=dilation,
                                              groups=groups,
                                              weight_attr=param_attr,
                                              bias_attr=bias_attr))
    return _act(conv(input), act)


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    """Parity: static.nn.prelu — mode all|channel|element."""
    from .. import nn as _nn
    _require_nchw(data_format, "prelu")
    if mode == "all":
        num = 1
    elif mode == "channel":
        num = x.shape[1]
    elif mode == "element":
        import math
        num = 1
        for d in x.shape[1:]:
            num *= d
    else:
        raise ValueError(f"prelu mode {mode!r} not in all|channel|element")
    layer = _layer("prelu", name,
                   lambda: _nn.PReLU(num_parameters=num,
                                     weight_attr=param_attr))
    return layer(x)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """Parity: static.nn.bilinear_tensor_product (common.py:2536)."""
    from .. import nn as _nn
    bl = _layer("bilinear", name,
                lambda: _nn.Bilinear(x.shape[-1], y.shape[-1], size,
                                     weight_attr=param_attr,
                                     bias_attr=bias_attr))
    return _act(bl(x, y), act)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Parity: static.nn.spectral_norm — returns the spectrally
    normalized weight via power iteration."""
    from ..nn.layer.norm import SpectralNorm as _SN
    sn = _layer("spectral_norm", name,
                lambda: _SN(list(weight.shape), dim=dim,
                            power_iters=power_iters, epsilon=eps))
    return sn(weight)


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None,
                  name=None):
    """Parity: static.nn.deform_conv2d — over vision.ops' jnp/lax
    deformable conv. Weight+bias live in ONE registry entry so unnamed
    calls get fresh parameters (auto-counter) like every other function."""
    from .. import nn as _nn
    from ..vision.ops import deform_conv2d as _dc
    k = (filter_size if isinstance(filter_size, (list, tuple))
         else (filter_size, filter_size))

    class _DeformParams(_nn.Layer):
        def __init__(self):
            super().__init__()
            self.weight = self.create_parameter(
                [num_filters, x.shape[1] // groups, k[0], k[1]],
                attr=param_attr)
            self.bias = (None if bias_attr is False else
                         self.create_parameter([num_filters],
                                               attr=bias_attr,
                                               is_bias=True))

    holder = _layer("deform_conv2d", name, _DeformParams)
    return _dc(x, offset, holder.weight, bias=holder.bias, stride=stride,
               padding=padding, dilation=dilation,
               deformable_groups=deformable_groups, groups=groups,
               mask=mask)


def row_conv(input, future_context_size, param_attr=None, act=None,
             name=None):
    """Parity: static.nn.row_conv (common.py:3332) — lookahead row
    convolution for streaming models: out[t] = sum_{k=0..K}
    W[k] * in[t+k], per feature channel."""
    import jax.numpy as jnp
    from ..autograd.tape import apply as _apply
    from ..tensor.parity_extras import create_parameter
    D = input.shape[-1]
    K = future_context_size
    w = _layer("row_conv", name,
               lambda: create_parameter([K + 1, D], "float32",
                                        attr=param_attr))

    def f(xv, wv):
        # pad K future steps on the time axis (axis=-2), then window-sum
        pad = [(0, 0)] * xv.ndim
        pad[-2] = (0, K)
        xp = jnp.pad(xv, pad)
        T = xv.shape[-2]
        out = 0.0
        for k in range(K + 1):
            sl = [slice(None)] * xv.ndim
            sl[-2] = slice(k, k + T)
            out = out + xp[tuple(sl)] * wv[k]
        return out

    return _act(_apply(f, input, w, _op_name="row_conv"), act)


def nce(*a, **kw):
    raise NotImplementedError(
        "static.nn.nce (sampled NCE loss) belongs to the deferred "
        "PS/CTR family (SURVEY §2.6 PS row); use "
        "F.cross_entropy/softmax_with_cross_entropy")


def sparse_embedding(*a, **kw):
    raise NotImplementedError(
        "static.nn.sparse_embedding is the parameter-server sparse table "
        "path, deferred per SURVEY §2.6; use static.nn.embedding / "
        "nn.Embedding")


class StaticRNN:
    """Parity stub: static.nn.StaticRNN — the step-by-step static-graph
    RNN builder has no Program to build into; nn.RNN / nn.LSTM / nn.GRU
    (lax.scan-backed) are the runtime equivalents."""

    def __init__(self, *a, **kw):
        raise NotImplementedError(
            "StaticRNN builds a static Program block; use nn.RNN/LSTM/GRU "
            "(lax.scan over the sequence) or paddle.static.nn.while_loop")


def _sequence_stub(op):
    def f(*a, **kw):
        raise NotImplementedError(
            f"static.nn.{op}: LoD (ragged) sequence tensors are collapsed "
            "in this runtime by design — use padded dense tensors + masks "
            "(nn ops) or ragged alltoall in distributed code")
    f.__name__ = op
    return f


for _op in ("sequence_conv", "sequence_softmax", "sequence_pool",
            "sequence_concat", "sequence_first_step", "sequence_last_step",
            "sequence_slice", "sequence_expand", "sequence_expand_as",
            "sequence_pad", "sequence_unpad", "sequence_reshape",
            "sequence_scatter", "sequence_enumerate", "sequence_reverse"):
    globals()[_op] = _sequence_stub(_op)
    __all__.append(_op)
