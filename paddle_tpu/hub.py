"""paddle.hub parity (python/paddle/hub.py: list/help/load).

Local and installed-module sources are fully supported (a hubconf.py
exposing entrypoint callables); the github/gitee remote sources require
network, which this build does not have — they raise with guidance.
"""
from __future__ import annotations

import importlib
import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]



def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, "hubconf.py")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no hubconf.py under {repo_dir!r}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["paddle_tpu_hubconf"] = mod
    spec.loader.exec_module(mod)
    return mod


def _resolve(repo_dir: str, source: str):
    if source in ("github", "gitee"):
        raise RuntimeError(
            f"hub source {source!r} needs network access, unavailable in "
            "this build; clone the repo and use source='local'")
    if source == "local":
        return _load_hubconf(repo_dir)
    raise ValueError(f"unknown hub source {source!r} "
                     "(expected 'github', 'gitee' or 'local')")


def list(repo_dir, source="github", force_reload=False):  # noqa: A001
    """Entrypoint names exposed by the repo's hubconf.py."""
    mod = _resolve(repo_dir, source)
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir, model, source="github", force_reload=False):  # noqa: A001
    """Docstring of one entrypoint."""
    mod = _resolve(repo_dir, source)
    return getattr(mod, model).__doc__


def load(repo_dir, model, source="github", force_reload=False, **kwargs):
    """Call entrypoint `model` with kwargs and return the result."""
    mod = _resolve(repo_dir, source)
    fn = getattr(mod, model, None)
    if fn is None or not callable(fn):
        raise ValueError(f"hubconf has no callable entrypoint {model!r}")
    return fn(**kwargs)
