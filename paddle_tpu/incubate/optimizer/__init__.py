"""incubate optimizers. Parity: python/paddle/incubate/optimizer/
{lookahead.py, modelaverage.py} — wrappers over an inner optimizer."""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead:
    """Parity: incubate/optimizer/lookahead.py — every k inner steps,
    pull the fast weights toward slow weights: slow += alpha*(fast-slow),
    fast = slow."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        assert inner_optimizer is not None
        assert 0.0 <= alpha <= 1.0
        assert k >= 1 and isinstance(k, int)
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._step = 0
        self._slow = {}
        self._params = list(
            getattr(inner_optimizer, "_parameter_list", []) or [])

    def __getattr__(self, item):
        return getattr(self.inner_optimizer, item)

    def step(self):
        self.inner_optimizer.step()
        self._step += 1
        if self._step % self.k:
            return
        for p in self._params:
            slow = self._slow.get(id(p))
            if slow is None:
                # jnp.copy: the fused optimizer step donates parameter
                # buffers, which would invalidate a retained reference
                slow = jnp.copy(p.value)
            else:
                slow = slow + self.alpha * (p.value - slow)
            self._slow[id(p)] = slow
            p.value = jnp.copy(slow)

    def clear_grad(self, set_to_zero=True):
        self.inner_optimizer.clear_grad(set_to_zero=set_to_zero)

    def minimize(self, loss):
        loss.backward()
        self.step()
        self.clear_grad()


class ModelAverage:
    """Parity: incubate/optimizer/modelaverage.py — maintain a running
    average of parameters; apply()/restore() swap it in and out."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self.rate = average_window_rate
        self.min_w = min_average_window
        self.max_w = max_average_window
        self._params = list(parameters or [])
        self._sum = {}
        self._cnt = 0
        self._backup = {}

    def step(self):
        """Accumulate the current parameter values."""
        self._cnt += 1
        for p in self._params:
            acc = self._sum.get(id(p))
            self._sum[id(p)] = jnp.copy(p.value) if acc is None \
                else acc + p.value
        # bounded window: restart accumulation when it grows too long
        if self._cnt > self.max_w and \
                self._cnt > self.min_w / max(self.rate, 1e-12):
            self._sum = {id(p): jnp.copy(p.value) for p in self._params}
            self._cnt = 1

    def apply(self, executor=None, need_restore=True):
        outer = self

        class _Ctx:
            def __enter__(ctx):
                for p in outer._params:
                    outer._backup[id(p)] = p.value
                    if id(p) in outer._sum and outer._cnt:
                        p.value = outer._sum[id(p)] / outer._cnt
                return ctx

            def __exit__(ctx, *exc):
                if need_restore:
                    outer.restore()
                return False

        return _Ctx()

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p.value = self._backup.pop(id(p))

    def clear_grad(self, set_to_zero=True):
        """Parity: ModelAverage extends Optimizer in the reference, so
        trainers call its clear_grad alongside the inner optimizer's.
        set_to_zero=True zero-fills existing grads; False releases."""
        for p in self._params:
            if set_to_zero and p._grad is not None:
                p._grad = jnp.zeros_like(p._grad)
            else:
                p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss):
        self.step()


from .lbfgs import LBFGS  # noqa: E402,F401
from . import functional  # noqa: E402,F401

__all__ += ["LBFGS", "functional"]
