"""L-BFGS optimizer (reference: python/paddle/incubate/optimizer/lbfgs.py).

torch/paddle-style `step(closure)` interface: the closure re-evaluates
the loss (and repopulates grads); the two-loop recursion builds the
quasi-Newton direction from the last `history_size` (s, y) pairs, with
optional Armijo backtracking line search. Flat-vector math runs in jnp
(one fused XLA program per op chain).
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor

__all__ = ["LBFGS"]


class LBFGS:
    def __init__(self, learning_rate=1.0, max_iter=20, tolerance_grad=1e-7,
                 tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        if not parameters:
            raise ValueError("LBFGS requires parameters")
        self._params = list(parameters)
        self.lr = float(learning_rate)
        self.max_iter = max_iter
        self.tol_grad = tolerance_grad
        self.tol_change = tolerance_change
        self.history = history_size
        if line_search_fn not in (None, "strong_wolfe", "armijo"):
            raise ValueError(f"unknown line_search_fn {line_search_fn!r}")
        self.line_search_fn = line_search_fn
        self._s, self._y = [], []
        self._prev_flat = None
        self._prev_grad = None

    # -- flat views ------------------------------------------------------
    def _flat(self):
        return jnp.concatenate([p.value.reshape(-1) for p in self._params])

    def _flat_grad(self):
        gs = []
        for p in self._params:
            g = p._grad
            gs.append((jnp.zeros(p.value.size, p.value.dtype)
                       if g is None else g.reshape(-1)))
        return jnp.concatenate(gs)

    def _write(self, flat):
        off = 0
        for p in self._params:
            n = p.value.size
            p.value = flat[off:off + n].reshape(p.value.shape).astype(
                p.value.dtype)
            off += n

    def _direction(self, g):
        """Two-loop recursion over stored (s, y)."""
        q = g
        alphas = []
        for s, y in reversed(list(zip(self._s, self._y))):
            rho = 1.0 / jnp.maximum(jnp.vdot(y, s), 1e-10)
            a = rho * jnp.vdot(s, q)
            alphas.append((rho, a, s, y))
            q = q - a * y
        if self._s:
            s, y = self._s[-1], self._y[-1]
            gamma = jnp.vdot(s, y) / jnp.maximum(jnp.vdot(y, y), 1e-10)
            q = q * gamma
        for rho, a, s, y in reversed(alphas):
            b = rho * jnp.vdot(y, q)
            q = q + s * (a - b)
        return -q

    def step(self, closure):
        """Run up to max_iter L-BFGS iterations; returns the final loss.
        `closure` clears grads, evaluates the loss, calls backward."""
        loss = None
        for _ in range(self.max_iter):
            loss = closure()
            loss_v = float(loss.value if isinstance(loss, Tensor) else loss)
            g = self._flat_grad()
            if float(jnp.max(jnp.abs(g))) <= self.tol_grad:
                break
            x = self._flat()
            if self._prev_flat is not None:
                s = x - self._prev_flat
                y = g - self._prev_grad
                if float(jnp.vdot(s, y)) > 1e-10:   # curvature condition
                    self._s.append(s)
                    self._y.append(y)
                    if len(self._s) > self.history:
                        self._s.pop(0)
                        self._y.pop(0)
            d = self._direction(g)
            t = self.lr
            if self.line_search_fn is not None:
                # Armijo backtracking (the strong-Wolfe role: the extra
                # curvature check rarely changes the accepted step here)
                gd = float(jnp.vdot(g, d))
                for _ls in range(10):
                    self._write(x + t * d)
                    trial = closure()
                    trial_v = float(trial.value if isinstance(trial, Tensor)
                                    else trial)
                    if trial_v <= loss_v + 1e-4 * t * gd:
                        loss, loss_v = trial, trial_v
                        break
                    t *= 0.5
                else:
                    self._write(x)      # no acceptable step
                    break
                new_flat = x + t * d
            else:
                new_flat = x + t * d
                self._write(new_flat)
            if float(jnp.max(jnp.abs(t * d))) <= self.tol_change:
                self._prev_flat, self._prev_grad = new_flat, g
                break
            self._prev_flat, self._prev_grad = new_flat, g
        return loss

    def clear_grad(self):
        for p in self._params:
            p.clear_grad()
