"""Functional minimizers (reference:
python/paddle/incubate/optimizer/functional/{bfgs,lbfgs}.py).

Self-contained BFGS (dense inverse-Hessian update + Armijo backtracking)
and two-loop L-BFGS over a pure objective. jax.scipy's BFGS is NOT used:
its zoom line search fails in f32 even on 2x2 SPD quadratics (status 3,
verified on this jax build). Both return the reference's result tuple
ordering (is_converge, num_func_calls, x, f, g).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor

__all__ = ["minimize_bfgs", "minimize_lbfgs"]


def _pure(objective_func):
    def f(x):
        out = objective_func(Tensor(x))
        return out.value if isinstance(out, Tensor) else out
    return f


def minimize_bfgs(objective_func, initial_position, max_iters=50,
                  tolerance_grad=1e-7, tolerance_change=1e-9,
                  initial_inverse_hessian_estimate=None, line_search_fn=None,
                  max_line_search_iters=50, initial_step_length=1.0,
                  dtype="float32", name=None):
    f = _pure(objective_func)
    grad_f = jax.grad(f)
    x = (initial_position.value if isinstance(initial_position, Tensor)
         else jnp.asarray(initial_position)).astype(jnp.float32)
    n = x.size
    H = (initial_inverse_hessian_estimate.value
         if isinstance(initial_inverse_hessian_estimate, Tensor)
         else initial_inverse_hessian_estimate)
    H = jnp.eye(n, dtype=jnp.float32) if H is None else jnp.asarray(H)
    g = grad_f(x)
    nfev = 1
    converged = False
    for _ in range(max_iters):
        if float(jnp.max(jnp.abs(g))) <= tolerance_grad:
            converged = True
            break
        d = -(H @ g)
        t = initial_step_length
        fx = f(x)
        gd = float(jnp.vdot(g, d))
        accepted = False
        for _ls in range(max_line_search_iters):
            x_new = x + t * d
            f_new = f(x_new)
            nfev += 1
            if float(f_new) <= float(fx) + 1e-4 * t * gd:
                accepted = True
                break
            t *= 0.5
        if not accepted:
            break
        g_new = grad_f(x_new)
        s, y = x_new - x, g_new - g
        sy = float(jnp.vdot(s, y))
        if sy > 1e-10:     # curvature holds: BFGS inverse update
            rho = 1.0 / sy
            I = jnp.eye(n, dtype=jnp.float32)
            V = I - rho * jnp.outer(s, y)
            H = V @ H @ V.T + rho * jnp.outer(s, s)
        if float(jnp.max(jnp.abs(s))) <= tolerance_change:
            x, g = x_new, g_new
            converged = True
            break
        x, g = x_new, g_new
    return (Tensor(jnp.asarray(converged)), Tensor(jnp.asarray(nfev)),
            Tensor(x), Tensor(f(x)), Tensor(g))


def minimize_lbfgs(objective_func, initial_position, history_size=100,
                   max_iters=50, tolerance_grad=1e-8,
                   tolerance_change=1e-8, initial_inverse_hessian_estimate=None,
                   line_search_fn=None, max_line_search_iters=50,
                   initial_step_length=1.0, dtype="float32", name=None):
    f = _pure(objective_func)
    grad_f = jax.grad(f)
    x = (initial_position.value if isinstance(initial_position, Tensor)
         else jnp.asarray(initial_position)).astype(jnp.float32)
    s_hist, y_hist = [], []
    g = grad_f(x)
    nfev = 1
    converged = False
    for _ in range(max_iters):
        if float(jnp.max(jnp.abs(g))) <= tolerance_grad:
            converged = True
            break
        q = g
        alphas = []
        for s, y in reversed(list(zip(s_hist, y_hist))):
            rho = 1.0 / jnp.maximum(jnp.vdot(y, s), 1e-10)
            a = rho * jnp.vdot(s, q)
            alphas.append((rho, a, s, y))
            q = q - a * y
        if s_hist:
            s, y = s_hist[-1], y_hist[-1]
            q = q * (jnp.vdot(s, y) / jnp.maximum(jnp.vdot(y, y), 1e-10))
        for rho, a, s, y in reversed(alphas):
            q = q + s * (a - rho * jnp.vdot(y, q))
        d = -q
        # backtracking Armijo
        t = initial_step_length
        fx = f(x)
        gd = float(jnp.vdot(g, d))
        accepted = False
        for _ls in range(max_line_search_iters):
            x_new = x + t * d
            f_new = f(x_new)
            nfev += 1
            if float(f_new) <= float(fx) + 1e-4 * t * gd:
                accepted = True
                break
            t *= 0.5
        if not accepted:
            break
        g_new = grad_f(x_new)
        s, y = x_new - x, g_new - g
        if float(jnp.vdot(s, y)) > 1e-10:
            s_hist.append(s)
            y_hist.append(y)
            if len(s_hist) > history_size:
                s_hist.pop(0)
                y_hist.pop(0)
        if float(jnp.max(jnp.abs(s))) <= tolerance_change:
            x, g = x_new, g_new
            converged = True
            break
        x, g = x_new, g_new
    return (Tensor(jnp.asarray(converged)), Tensor(jnp.asarray(nfev)),
            Tensor(x), Tensor(f(x)), Tensor(g))
