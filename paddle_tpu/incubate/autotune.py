"""paddle.incubate.autotune parity.

Reference: python/paddle/incubate/autotune.py set_config:24 — kernel,
layout and dataloader auto-tuning knobs. On TPU the kernel search is
XLA's own autotuner (SURVEY.md §2.1 "kernel autotune: subsumed"), so
`kernel.enable` toggles the XLA autotune level env knob; layout tuning
is XLA's layout assignment (always on); the dataloader knob adjusts the
DataLoader prefetch depth default.
"""
from __future__ import annotations

import json
import os
import warnings

__all__ = ["set_config"]

_config = {
    "kernel": {"enable": True, "tuning_range": [1, 10]},
    "layout": {"enable": True},
    "dataloader": {"enable": False, "tuning_steps": 500},
}


def set_config(config=None):
    """Parity: incubate/autotune.py:24. Accepts a dict or a path to a
    JSON file with any of the 'kernel'/'layout'/'dataloader' sections."""
    if config is None:
        for section in _config.values():
            section["enable"] = True
        return
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    if not isinstance(config, dict):
        raise ValueError("config should be a dict or a json file path")
    for key, val in config.items():
        if key not in _config:
            warnings.warn(f"autotune: unknown section {key!r} ignored")
            continue
        _config[key].update(val)
    if "kernel" in config:
        # XLA exhaustive-search level: 0 = off, 4 = full search. XLA
        # reads XLA_FLAGS once at backend init, so this only affects
        # child processes (spawn/launch workers) — which is where the
        # tuning iteration actually runs; replace any previous setting
        # rather than appending duplicates.
        level = "4" if _config["kernel"]["enable"] else "0"
        flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
                 if not f.startswith("--xla_gpu_autotune_level=")]
        flags.append(f"--xla_gpu_autotune_level={level}")
        os.environ["XLA_FLAGS"] = " ".join(flags)


def get_config():
    return {k: dict(v) for k, v in _config.items()}
