"""paddle.incubate parity (SURVEY.md §2.8 incubate row): ASP 2:4
sparsity, autotune config, and the MoE models re-export (the MoE
implementation itself lives in distributed/moe.py)."""
from . import asp
from . import autotune


class _MoENamespace:
    """paddle.incubate.distributed.models.moe path parity."""

    def __getattr__(self, name):
        from ..distributed import moe
        return getattr(moe, name)


class _DistributedNamespace:
    class models:
        pass


distributed = _DistributedNamespace()
distributed.models.moe = _MoENamespace()

__all__ = ["asp", "autotune", "distributed"]
