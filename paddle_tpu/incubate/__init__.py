"""paddle.incubate parity (SURVEY.md §2.8 incubate row): ASP 2:4
sparsity, autotune config, and the MoE models re-export (the MoE
implementation itself lives in distributed/moe.py)."""
from . import asp
from . import autograd
from . import autotune
from . import checkpoint
from . import distributed
from . import nn
from . import optimizer




__all__ = ["asp", "autograd", "autotune", "checkpoint", "distributed", "nn", "optimizer", "LookAhead",
           "ModelAverage",
           "graph_khop_sampler", "graph_reindex", "graph_sample_neighbors",
           "graph_send_recv", "identity_loss", "segment_max",
           "segment_mean", "segment_min", "segment_sum",
           "softmax_mask_fuse", "softmax_mask_fuse_upper_triangle"]


def __dir__():
    return sorted(set(globals()) | set(__all__))


# ---------------------------------------------------------------------------
# incubate long tail (reference: python/paddle/incubate/__init__.py):
# graph ops (aliases of the geometric implementations, which is also
# what the reference's incubate versions became), fused softmax masks,
# identity_loss, and the LookAhead / ModelAverage optimizer wrappers.
# ---------------------------------------------------------------------------

def __getattr__(name):
    out = _resolve(name)
    globals()[name] = out  # cache: stable identity for mock/caching
    return out


def _resolve(name):
    if name in ("segment_sum", "segment_mean", "segment_min",
                "segment_max"):
        from .. import geometric
        return getattr(geometric, name)
    if name == "graph_send_recv":
        from ..geometric import send_u_recv

        def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                            out_size=None, name=None):
            return send_u_recv(x, src_index, dst_index, pool_type,
                               out_size)

        return graph_send_recv
    if name == "graph_reindex":
        from ..geometric import reindex_graph

        def graph_reindex(x, neighbors, count, value_buffer=None,
                          index_buffer=None, flag_buffer_hashtable=False,
                          name=None):
            return reindex_graph(x, neighbors, count)

        return graph_reindex
    if name == "graph_sample_neighbors":
        from ..geometric import sample_neighbors

        def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                                   perm_buffer=None, sample_size=-1,
                                   return_eids=False,
                                   flag_perm_buffer=False, name=None):
            return sample_neighbors(row, colptr, input_nodes,
                                    sample_size, eids, return_eids,
                                    perm_buffer)

        return graph_sample_neighbors
    if name == "graph_khop_sampler":
        from ..geometric import sample_neighbors

        def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                               sorted_eids=None, return_eids=False,
                               name=None):
            import numpy as np
            import jax.numpy as jnp
            from ..core.tensor import Tensor
            nodes = input_nodes
            all_nb, all_cnt, centers = [], [], []
            for sz in sample_sizes:
                nb, cnt = sample_neighbors(row, colptr, nodes, sz)
                centers.append(np.asarray(
                    nodes.value if isinstance(nodes, Tensor) else nodes
                ).reshape(-1))
                all_nb.append(np.asarray(nb.value))
                all_cnt.append(np.asarray(cnt.value))
                nodes = nb
            # one shared id space; edge dst = reindexed id of the CENTER
            # node each sampled neighbor belongs to (not its position)
            base = np.asarray(input_nodes.value
                              if isinstance(input_nodes, Tensor)
                              else input_nodes).reshape(-1)
            uniq = {int(v): i for i, v in enumerate(base)}
            out_nodes = list(base)

            def rid(v):
                v = int(v)
                if v not in uniq:
                    uniq[v] = len(out_nodes)
                    out_nodes.append(v)
                return uniq[v]

            src, dst = [], []
            for ctr, nb, cnt in zip(centers, all_nb, all_cnt):
                ctr_ids = [rid(c) for c in ctr]
                pos = 0
                for ci, k in zip(ctr_ids, cnt):
                    for v in nb[pos:pos + int(k)]:
                        src.append(rid(v))
                        dst.append(ci)
                    pos += int(k)
            cnt_cat = np.concatenate(all_cnt) if all_cnt else \
                np.empty(0, np.int32)
            return (Tensor(jnp.asarray(np.asarray(src, np.int64))),
                    Tensor(jnp.asarray(np.asarray(dst, np.int64))),
                    Tensor(jnp.asarray(np.asarray(out_nodes))),
                    Tensor(jnp.asarray(cnt_cat)))

        return graph_khop_sampler
    if name == "identity_loss":
        def identity_loss(x, reduction="none"):
            """Parity: incubate identity_loss (IPU loss anchor)."""
            import jax.numpy as jnp
            from ..autograd.tape import apply
            red = {0: "sum", 1: "mean", 2: "none"}.get(reduction,
                                                       reduction)
            def f(v):
                if red == "mean":
                    return jnp.mean(v)
                if red == "sum":
                    return jnp.sum(v)
                return v
            return apply(f, x, _op_name="identity_loss")

        return identity_loss
    if name == "softmax_mask_fuse":
        def softmax_mask_fuse(x, mask, name=None):
            """Parity: incubate softmax_mask_fuse — softmax(x + mask);
            XLA fuses (the reference's point was avoiding a CUDA
            roundtrip)."""
            import jax
            from ..autograd.tape import apply
            return apply(lambda v, m: jax.nn.softmax(v + m, -1), x, mask,
                         _op_name="softmax_mask_fuse")

        return softmax_mask_fuse
    if name == "softmax_mask_fuse_upper_triangle":
        def softmax_mask_fuse_upper_triangle(x):
            """Parity: causal-masked softmax."""
            import jax
            import jax.numpy as jnp
            from ..autograd.tape import apply

            def f(v):
                s = v.shape[-1]
                cm = jnp.tril(jnp.ones((v.shape[-2], s), bool))
                return jax.nn.softmax(
                    jnp.where(cm, v, jnp.asarray(-1e30, v.dtype)), -1)

            return apply(f, x, _op_name="softmax_mask_fuse_upper_triangle")

        return softmax_mask_fuse_upper_triangle
    if name in ("LookAhead", "ModelAverage"):
        from . import optimizer as _opt
        return getattr(_opt, name)
    raise AttributeError(f"module 'paddle_tpu.incubate' has no attribute {name!r}")
