"""paddle.incubate.distributed namespace."""
from . import fleet  # noqa: F401
from . import models  # noqa: F401

__all__ = ["fleet", "models"]
