"""paddle.incubate.distributed.fleet parity — the recompute entry points
(reference: python/paddle/incubate/distributed/fleet/__init__.py) map to
the jax.checkpoint-backed implementations in distributed.recompute."""
from ...distributed.recompute import (recompute_sequential)  # noqa: F401


def recompute_hybrid(ctx, function, *args, **kwargs):
    """Parity: recompute_hybrid(ctx, fn, ...) — mp-aware activation
    partitioning is GSPMD's job here (rematerialized values inherit
    their shardings), so this is recompute with the ctx accepted."""
    from ...distributed.recompute import recompute
    return recompute(function, *args, **kwargs)


__all__ = ["recompute_sequential", "recompute_hybrid"]
