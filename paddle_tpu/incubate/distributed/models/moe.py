"""paddle.incubate.distributed.models.moe parity — re-export of the MoE
implementation (gates/capacity/dispatch live in distributed/moe.py)."""
from ....distributed.moe import *  # noqa: F401,F403
from ....distributed.moe import __all__  # noqa: F401
