"""paddle.incubate.autograd parity (reference:
python/paddle/incubate/autograd/__init__.py) — the functional transforms
over the jax primitive AD (the role of the reference's prim/composite
operator machinery, which this runtime subsumes: SURVEY §2.8 prim row).
"""
from ..autograd.functional import hessian as _hessian
from ..autograd.functional import jacobian as _jacobian
from ..autograd.functional import jvp, vjp  # noqa: F401


class Jacobian:
    """Parity: incubate.autograd.Jacobian — class wrapper whose value is
    materialized once and indexed like the reference's lazy matrix."""

    def __init__(self, func, xs, is_batched=False):
        self._j = _jacobian(func, xs,
                            batch_axis=0 if is_batched else None)

    def __getitem__(self, idx):
        j = self._j
        return (j[idx] if not isinstance(j, (list, tuple))
                else [ji[idx] for ji in j])

    @property
    def shape(self):
        j = self._j
        return j.shape if not isinstance(j, (list, tuple)) else \
            [ji.shape for ji in j]


class Hessian(Jacobian):
    """Parity: incubate.autograd.Hessian."""

    def __init__(self, func, xs, is_batched=False):
        self._j = _hessian(func, xs,
                           batch_axis=0 if is_batched else None)

__all__ = ["vjp", "jvp", "Jacobian", "Hessian", "enable_prim",
           "disable_prim", "forward_grad", "grad"]

_prim_enabled = False


def enable_prim():
    """Parity: prim-mode toggle. jax always differentiates through
    primitive rules (the end state the reference's prim mode builds
    toward), so this only records the flag."""
    global _prim_enabled
    _prim_enabled = True


def disable_prim():
    global _prim_enabled
    _prim_enabled = False


def prim_enabled():
    return _prim_enabled


def forward_grad(outputs, inputs, grad_inputs=None):
    """Parity note: the reference's forward_grad rewrites a static prim
    Program; a define-by-run tape cannot replay forward-mode from output
    tensors alone. The functional equivalent is provided instead."""
    raise NotImplementedError(
        "forward_grad consumes a static prim Program in the reference; "
        "use paddle.incubate.autograd.jvp(fn, xs, v) — same derivative, "
        "functional form")


def grad(outputs, inputs, grad_outputs=None):
    """Parity: incubate.autograd.grad (prim-mode reverse) — same result
    as paddle.grad here (one AD engine)."""
    from ..autograd import grad as _grad
    return _grad(outputs, inputs, grad_outputs=grad_outputs,
                 allow_unused=True)
