"""TrainEpochRange — see package docstring. Reference:
fluid/incubate/checkpoint/auto_checkpoint.py:284 (TrainEpochRange),
:72 (AutoCheckpointChecker)."""
from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Optional

from ...io.state import load as _load
from ...io.state import save as _save

__all__ = ["AutoCheckpointChecker", "TrainEpochRange", "train_epoch_range"]


class AutoCheckpointChecker:
    """Resolves whether auto-checkpointing is on and where it lives.

    Reference: auto_checkpoint.py:72 — reads job env. Here:
    PADDLE_JOB_ID names the job, PADDLE_CHECKPOINT_DIR the storage root
    (the reference's PADDLE_EDL_HDFS_CHECKPOINT_PATH role); absent dir
    means disabled unless one is passed explicitly.
    """

    def __init__(self, checkpoint_dir: Optional[str] = None,
                 job_id: Optional[str] = None):
        self.job_id = job_id or os.environ.get("PADDLE_JOB_ID", "default")
        self.checkpoint_dir = checkpoint_dir or \
            os.environ.get("PADDLE_CHECKPOINT_DIR")
        # env.ParallelEnv falls back to jax.process_index() so every host
        # of a JAX-native multi-host job gets its true rank
        from ...distributed.env import ParallelEnv
        self.rank = ParallelEnv().rank

    @property
    def enabled(self) -> bool:
        return self.checkpoint_dir is not None

    def job_dir(self) -> str:
        return os.path.join(self.checkpoint_dir, self.job_id)


class TrainEpochRange:
    """Iterate epochs, skipping those a previous (killed) run completed.

    Usage::

        r = TrainEpochRange(10, checkpoint_dir="/ckpt", name="job7")
        r.attach(model=model, optimizer=opt)     # what to snapshot
        for epoch in r:
            train_one_epoch(...)
            # on loop bottom the epoch is marked complete + snapshotted

    On restart with the same dir/name, finished epochs are skipped and
    the attached objects are restored from the newest snapshot.
    Rank-0 writes snapshots; every rank reads them (shared storage for
    multi-host, as the reference's HDFS path).
    """

    def __init__(self, max_epoch_num: int,
                 checkpoint_dir: Optional[str] = None,
                 name: Optional[str] = None, save_checkpoint_inter=1):
        self.max_epoch_num = int(max_epoch_num)
        self.checker = AutoCheckpointChecker(checkpoint_dir, name)
        self.save_inter = max(1, int(save_checkpoint_inter))
        self._attached = {}

    # ------------------------------------------------------------------
    def attach(self, **named_objects):
        """Register state-dict-bearing objects (model=..., optimizer=...)."""
        for k, v in named_objects.items():
            if not hasattr(v, "state_dict"):
                raise TypeError(f"{k} has no state_dict()")
            self._attached[k] = v
        return self

    # ------------------------------------------------------------------
    def _meta_path(self) -> str:
        return os.path.join(self.checker.job_dir(), "range_meta.json")

    def _read_meta(self):
        try:
            with open(self._meta_path()) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {"last_epoch": -1}

    def _write_meta(self, meta) -> None:
        # atomic publish: epoch counts only after the snapshot is durable
        d = self.checker.job_dir()
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".meta")
        with os.fdopen(fd, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, self._meta_path())

    def _snap_path(self, key: str) -> str:
        return os.path.join(self.checker.job_dir(), f"{key}.pdparams")

    def _save_snapshot(self, epoch: int) -> None:
        if self.checker.rank != 0:
            return
        d = self.checker.job_dir()
        os.makedirs(d, exist_ok=True)
        for key, obj in self._attached.items():
            # atomic: a crash mid-save must not destroy the previous
            # durable snapshot the meta still points at
            fd, tmp = tempfile.mkstemp(dir=d, suffix=".snap")
            os.close(fd)
            _save(obj.state_dict(), tmp)
            os.replace(tmp, self._snap_path(key))
        self._write_meta({"last_epoch": epoch, "time": time.time(),
                          "job": self.checker.job_id})

    def _restore(self) -> int:
        meta = self._read_meta()
        last = int(meta.get("last_epoch", -1))
        if last < 0:
            return last
        if not self._attached:
            import warnings
            warnings.warn(
                f"auto-checkpoint meta says epoch {last} completed but "
                "nothing is attach()ed to restore — skipped epochs will "
                "resume from the CURRENT in-memory state", stacklevel=3)
            return last
        for key, obj in self._attached.items():
            path = self._snap_path(key)
            if not os.path.exists(path):
                raise RuntimeError(
                    f"auto-checkpoint meta records epoch {last} complete "
                    f"but snapshot {path!r} for attached object "
                    f"{key!r} is missing — refusing to skip epochs "
                    "without restoring (attach with the same names as "
                    "the run that wrote the checkpoint, or clear the "
                    "checkpoint dir)")
            obj.set_state_dict(_load(path))
        return last

    # ------------------------------------------------------------------
    def __iter__(self):
        if not self.checker.enabled:
            yield from range(self.max_epoch_num)
            return
        # honor the on-disk meta on EVERY iteration: a second pass over a
        # finished range yields nothing instead of silently retraining
        last = self._restore()
        for epoch in range(last + 1, self.max_epoch_num):
            yield epoch
            if (epoch + 1) % self.save_inter == 0 \
                    or epoch == self.max_epoch_num - 1:
                self._save_snapshot(epoch)


def train_epoch_range(max_epoch_num, checkpoint_dir=None, name=None,
                      save_checkpoint_inter=1):
    """Functional spelling matching the reference's
    acp.train_epoch_range(...) usage."""
    return TrainEpochRange(max_epoch_num, checkpoint_dir, name,
                           save_checkpoint_inter)
