"""Auto-checkpoint: epoch-boundary snapshots with resume-on-restart.

Parity: python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py
(TrainEpochRange:284, AutoCheckpointChecker:72) — the piece that pairs
with elastic recovery (SURVEY.md §5.3/§5.4): a job that is killed and
relaunched resumes from the last completed epoch instead of epoch 0.

TPU-native simplifications: snapshots go through paddle.save (pickle
state_dict protocol, io/state.py) to a local/NFS dir instead of HDFS;
the job identity comes from PADDLE_JOB_ID (fallback: checkpoint dir), and
epoch bookkeeping is one small JSON sidecar. Rank-0 writes, everyone
reads — multi-host jobs point at shared storage, exactly the reference's
HDFS contract.
"""
from .auto_checkpoint import (AutoCheckpointChecker, TrainEpochRange,
                              train_epoch_range)

__all__ = ["TrainEpochRange", "train_epoch_range", "AutoCheckpointChecker"]
