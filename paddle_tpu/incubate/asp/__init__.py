"""ASP (Automatic SParsity) — 2:4 structured sparsity utilities.

Parity: python/paddle/incubate/asp/ (utils.py mask algorithms:
get_mask_1d:179, get_mask_2d_greedy:313, check_mask_1d:135,
calculate_density:81; asp.py prune_model:302, decorate:216).

TPU note: the reference targets Ampere sparse tensor cores; the TPU MXU
has no 2:4 hardware path, so here ASP is a *pruning* facility — masks
are computed the same way, applied to weights, and re-applied after
each optimizer step by the decorated optimizer so pruned weights stay
zero through training.
"""
from __future__ import annotations

from enum import Enum
from typing import Dict

import numpy as np

__all__ = ["calculate_density", "check_mask_1d", "get_mask_1d",
           "check_mask_2d", "get_mask_2d_greedy", "create_mask",
           "check_sparsity", "MaskAlgo", "CheckMethod", "prune_model",
           "decorate", "set_excluded_layers", "reset_excluded_layers"]


class MaskAlgo(Enum):
    MASK_1D = "get_mask_1d"
    MASK_2D_GREEDY = "get_mask_2d_greedy"
    MASK_2D_BEST = "get_mask_2d_greedy"  # best-pattern search ≈ greedy here


class CheckMethod(Enum):
    CHECK_1D = "check_mask_1d"
    CHECK_2D = "check_mask_2d"

    @staticmethod
    def get_checking_method(mask_algo):
        if mask_algo == MaskAlgo.MASK_1D:
            return CheckMethod.CHECK_1D
        return CheckMethod.CHECK_2D


def calculate_density(x) -> float:
    """Parity: asp/utils.py:81 — nnz / size."""
    arr = np.asarray(x.value if hasattr(x, "value") else x)
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


def _pad_cols(mat, m):
    pad = (-mat.shape[1]) % m
    if pad:
        mat = np.concatenate([mat, np.zeros((mat.shape[0], pad),
                                            mat.dtype)], 1)
    return mat, pad


def get_mask_1d(mat, n, m):
    """Parity: asp/utils.py:179 — keep the n largest |values| of every m
    consecutive elements along rows. Vectorized via argpartition."""
    mat = np.asarray(mat)
    shape = mat.shape
    flat = mat.reshape(-1, shape[-1])
    padded, pad = _pad_cols(flat, m)
    g = padded.reshape(padded.shape[0], -1, m)
    order = np.argsort(-np.abs(g), axis=-1)
    mask = np.zeros_like(g, dtype=bool)
    np.put_along_axis(mask, order[..., :n], True, axis=-1)
    mask = mask.reshape(padded.shape)
    if pad:
        mask = mask[:, :-pad]
    return mask.reshape(shape).astype(mat.dtype)


def check_mask_1d(mat, n, m) -> bool:
    """Parity: asp/utils.py:135 — every m-group has at most n nonzeros."""
    mat = np.asarray(mat)
    flat = mat.reshape(-1, mat.shape[-1])
    padded, _ = _pad_cols(flat, m)
    g = padded.reshape(padded.shape[0], -1, m)
    return bool((np.count_nonzero(g, axis=-1) <= n).all())


def get_mask_2d_greedy(mat, n, m):
    """Parity: asp/utils.py:313 — n:m constraint on both rows and
    columns of each m x m block, greedy by magnitude."""
    mat = np.asarray(mat)
    h, w = mat.shape
    ph, pw = (-h) % m, (-w) % m
    padded = np.zeros((h + ph, w + pw), mat.dtype)
    padded[:h, :w] = mat
    mask = np.zeros_like(padded, dtype=bool)
    for bi in range(0, padded.shape[0], m):
        for bj in range(0, padded.shape[1], m):
            blk = np.abs(padded[bi:bi + m, bj:bj + m])
            order = np.argsort(-blk.ravel())
            rows = np.zeros(m, np.int64)
            cols = np.zeros(m, np.int64)
            for flat_idx in order:
                r, c = divmod(int(flat_idx), m)
                if rows[r] < n and cols[c] < n:
                    mask[bi + r, bj + c] = True
                    rows[r] += 1
                    cols[c] += 1
    return mask[:h, :w].astype(mat.dtype)


def check_mask_2d(mat, n, m) -> bool:
    """Parity: asp/utils.py:262."""
    mat = np.asarray(mat)
    h, w = mat.shape
    for bi in range(0, h, m):
        for bj in range(0, w, m):
            blk = mat[bi:bi + m, bj:bj + m]
            if (np.count_nonzero(blk, axis=0) > n).any() or \
                    (np.count_nonzero(blk, axis=1) > n).any():
                return False
    return True


def create_mask(tensor, func_name=MaskAlgo.MASK_1D, n=2, m=4):
    """Parity: asp/utils.py create_mask — mask for a 2D-reshaped view."""
    arr = np.asarray(tensor.value if hasattr(tensor, "value") else tensor)
    shape = arr.shape
    mat = arr.reshape(shape[0], -1) if arr.ndim > 1 else arr.reshape(1, -1)
    if func_name in (MaskAlgo.MASK_2D_GREEDY, MaskAlgo.MASK_2D_BEST):
        mask = get_mask_2d_greedy(mat, n, m)
    else:
        mask = get_mask_1d(mat, n, m)
    return mask.reshape(shape)


def check_sparsity(tensor, func_name=CheckMethod.CHECK_1D, n=2, m=4):
    """Parity: asp/utils.py check_sparsity."""
    arr = np.asarray(tensor.value if hasattr(tensor, "value") else tensor)
    mat = arr.reshape(arr.shape[0], -1) if arr.ndim > 1 \
        else arr.reshape(1, -1)
    if func_name == CheckMethod.CHECK_2D:
        return check_mask_2d(mat, n, m)
    return check_mask_1d(mat, n, m)


# ---------------------------------------------------------------------------
# model-level API
# ---------------------------------------------------------------------------

_excluded: set = set()
_masks: Dict[int, tuple] = {}  # id(param) -> (param, mask ndarray)


def set_excluded_layers(param_names, main_program=None):
    """Parity: asp.py:40."""
    _excluded.update(param_names)


def reset_excluded_layers(main_program=None):
    """Parity: asp.py:127."""
    _excluded.clear()


_CUSTOM_SUPPORTED = set()


def _supported(p, name=""):
    if len(p.shape) not in (2, 4):
        return False
    # explicitly registered layers (add_supported_layer) bypass the
    # min-dim heuristic; the n:m mask only needs the last dim to split
    if any(key in name for key in _CUSTOM_SUPPORTED):
        return True
    return min(p.shape) >= 4


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Parity: asp.py:302 — mask every supported weight in place and
    remember the mask so a decorated optimizer keeps it applied."""
    import jax.numpy as jnp
    algo = {"mask_1d": MaskAlgo.MASK_1D,
            "mask_2d_greedy": MaskAlgo.MASK_2D_GREEDY,
            "mask_2d_best": MaskAlgo.MASK_2D_BEST}[mask_algo]
    out = {}
    for name, p in model.named_parameters():
        if name in _excluded or not _supported(p, name):
            continue
        mask = create_mask(p, algo, n, m).astype(np.float32)
        p.value = p.value * jnp.asarray(mask, p.value.dtype)
        if with_mask:
            _masks[id(p)] = (p, mask)
        out[name] = mask
    return out


def decorate(optimizer):
    """Parity: asp.py:216 — after each step, re-apply the masks recorded
    by prune_model so pruned weights stay exactly zero."""

    class OptimizerWithSparsityGuarantee:
        def __init__(self, inner):
            self._inner = inner

        def __getattr__(self, item):
            return getattr(self._inner, item)

        def step(self):
            import jax.numpy as jnp
            self._inner.step()
            for p, mask in _masks.values():
                p.value = p.value * jnp.asarray(mask, p.value.dtype)

    return OptimizerWithSparsityGuarantee(optimizer)


def add_supported_layer(layer, pruning_func=None):
    """Parity: incubate.asp.add_supported_layer — register an extra layer
    type (or layer-name string) whose weights prune_model should mask."""
    key = layer if isinstance(layer, str) else getattr(
        layer, "__name__", str(layer))
    _CUSTOM_SUPPORTED.add(key)
    return key


def supported_layers():
    return set(_CUSTOM_SUPPORTED)


__all__ += ["add_supported_layer", "supported_layers"]
