"""Fused-layer implementations — see package docstring for the parity map.

Reference semantics followed exactly (fused_attention_op.cu contract):
  normalize_before=True (pre-LN):  out = x + drop(sub(LN(x)))
  normalize_before=False (post-LN): out = LN(x + drop(sub(x)))
where sub is the attention or FFN block.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ... import tensor as T
from ...autograd.tape import apply
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer_base import Layer
from ...nn import Dropout, LayerNorm, Linear

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer", "FusedMultiTransformer",
           "FusedLinear", "FusedBiasDropoutResidualLayerNorm",
           "FusedEcMoe", "FusedDropoutAdd"]


def _split_qkv(qkv, B, S, nh, hd):
    """[B, S, 3E] fused projection -> q/k/v [B, S, nh, hd] (contiguous
    last-dim slices, free reshapes)."""
    E = nh * hd
    q = T.reshape(T.slice(qkv, [2], [0], [E]), [B, S, nh, hd])
    k = T.reshape(T.slice(qkv, [2], [E], [2 * E]), [B, S, nh, hd])
    v = T.reshape(T.slice(qkv, [2], [2 * E], [3 * E]), [B, S, nh, hd])
    return q, k, v


class FusedMultiHeadAttention(Layer):
    """Self-attention block with residual + LN fused in.

    Parity: incubate/nn/layer/fused_transformer.py FusedMultiHeadAttention
    over fused_attention_op.cu. forward(x, attn_mask=None) — mask is
    additive [B, 1, S, S] or boolean (True = keep)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 weight_attr=None, bias_attr=None, epsilon=1e-5,
                 name=None):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError("embed_dim must divide num_heads")
        if need_weights:
            raise NotImplementedError(
                "need_weights=True is unsupported (the reference fused op "
                "rejects it too)")
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.qkv = Linear(embed_dim, 3 * embed_dim,
                          weight_attr=weight_attr, bias_attr=bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim,
                               weight_attr=weight_attr,
                               bias_attr=bias_attr)
        self.ln = LayerNorm(embed_dim, epsilon=epsilon)
        self.attn_dropout_rate = attn_dropout_rate
        self.dropout = Dropout(dropout_rate)

    def _attn(self, x, attn_mask):
        B, S, E = x.shape
        q, k, v = _split_qkv(self.qkv(x), B, S, self.num_heads,
                             self.head_dim)
        ctx = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate, is_causal=False,
            training=self.training)
        return self.out_proj(T.reshape(ctx, [B, S, E]))

    def forward(self, x, attn_mask=None, cache=None):
        if cache is not None:
            raise NotImplementedError(
                "FusedMultiHeadAttention does not implement CacheKV "
                "decode; use FusedMultiTransformer (caches=..., pos=...)")
        if self.normalize_before:
            return x + self.dropout(self._attn(self.ln(x), attn_mask))
        return self.ln(x + self.dropout(self._attn(x, attn_mask)))


class FusedFeedForward(Layer):
    """FFN block with residual + LN fused in (fused_feedforward role)."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.fc1 = Linear(d_model, dim_feedforward,
                          weight_attr=linear1_weight_attr,
                          bias_attr=linear1_bias_attr)
        self.fc2 = Linear(dim_feedforward, d_model,
                          weight_attr=linear2_weight_attr,
                          bias_attr=linear2_bias_attr)
        self.ln = LayerNorm(d_model, epsilon=epsilon)
        self.act = getattr(F, activation)
        self.dropout = Dropout(dropout_rate)
        self.act_dropout = Dropout(
            dropout_rate if act_dropout_rate is None else act_dropout_rate)

    def _ffn(self, x):
        return self.fc2(self.act_dropout(self.act(self.fc1(x))))

    def forward(self, x):
        if self.normalize_before:
            return x + self.dropout(self._ffn(self.ln(x)))
        return self.ln(x + self.dropout(self._ffn(x)))


class FusedTransformerEncoderLayer(Layer):
    """Attention + FFN blocks (fused_transformer.py
    FusedTransformerEncoderLayer)."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=(dropout_rate if attn_dropout_rate is None
                               else attn_dropout_rate),
            normalize_before=normalize_before, weight_attr=weight_attr,
            bias_attr=bias_attr)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None):
        return self.ffn(self.fused_attn(src, src_mask))


class FusedMultiTransformer(Layer):
    """Inference-oriented decoder stack with CacheKV incremental decode.

    Parity: fused_multi_transformer_op.cu (§2.4) / FusedMultiTransformer —
    the serving transformer. forward(x, caches=None, pos=None): with
    caches (list of per-layer (k, v) [B, L, nh, hd]) runs incremental
    causal attention at position pos and returns (out, new_caches);
    without caches runs full causal attention. Pre-LN, as the reference
    defaults (normalize_before=True)."""

    def __init__(self, embed_dim, num_heads, dim_feedforward, num_layers,
                 dropout_rate=0.0, activation="gelu", epsilon=1e-5,
                 normalize_before=True, name=None):
        super().__init__()
        if not normalize_before:
            raise NotImplementedError(
                "FusedMultiTransformer is pre-LN only, like the "
                "reference op")
        if embed_dim % num_heads:
            raise ValueError("embed_dim must divide num_heads")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.num_layers = num_layers
        self.layers = []
        for i in range(num_layers):
            blk = _FMTBlock(embed_dim, num_heads, dim_feedforward,
                            dropout_rate, activation, epsilon)
            self.add_sublayer(f"layer_{i}", blk)
            self.layers.append(blk)

    def new_cache(self, batch_size, max_len, dtype="float32"):
        shape = (batch_size, max_len, self.num_heads, self.head_dim)
        return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
                for _ in range(self.num_layers)]

    def forward(self, x, caches=None, pos=None):
        if caches is not None:
            new_caches = []
            for blk, c in zip(self.layers, caches):
                x, c = blk(x, c, pos)
                new_caches.append(c)
            return x, new_caches
        for blk in self.layers:
            x = blk(x)
        return x


class _FMTBlock(Layer):
    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate, activation, epsilon):
        super().__init__()
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.ln1 = LayerNorm(embed_dim, epsilon=epsilon)
        self.qkv = Linear(embed_dim, 3 * embed_dim)
        self.out_proj = Linear(embed_dim, embed_dim)
        self.ln2 = LayerNorm(embed_dim, epsilon=epsilon)
        self.fc1 = Linear(embed_dim, dim_feedforward)
        self.fc2 = Linear(dim_feedforward, embed_dim)
        self.act = getattr(F, activation)
        self.dropout = Dropout(dropout_rate)

    def forward(self, x, cache=None, pos=None):
        B, S, E = x.shape
        h = self.ln1(x)
        q, k, v = _split_qkv(self.qkv(h), B, S, self.num_heads,
                             self.head_dim)
        if cache is not None:
            from ...nn.functional.flash_attention import cached_attention
            ctx, kc, vc = cached_attention(q, k, v, cache[0], cache[1],
                                           pos)
            att = self.out_proj(T.reshape(ctx, [B, S, E]))
            x = x + self.dropout(att)
            x = x + self.dropout(
                self.fc2(self.act(self.fc1(self.ln2(x)))))
            return x, (kc, vc)
        ctx, _ = F.flash_attention(q, k, v, causal=True,
                                   training=self.training)
        x = x + self.dropout(self.out_proj(T.reshape(ctx, [B, S, E])))
        x = x + self.dropout(self.fc2(self.act(self.fc1(self.ln2(x)))))
        return x


class FusedLinear(Linear):
    """Parity: incubate FusedLinear (fused gemm_epilogue) — on TPU the
    bias epilogue is XLA's fusion; identical math to Linear."""


class FusedBiasDropoutResidualLayerNorm(Layer):
    """y = LN(residual + dropout(x + bias)) — the fused epilogue of the
    attention op exposed standalone."""

    def __init__(self, embed_dim, dropout_rate=0.5, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.bias = self.create_parameter([embed_dim], attr=bias_attr,
                                          is_bias=True)
        self.ln = LayerNorm(embed_dim, epsilon=epsilon,
                            weight_attr=weight_attr)
        self.dropout = Dropout(dropout_rate)

    def forward(self, x, residual):
        return self.ln(residual + self.dropout(x + self.bias))


class FusedDropoutAdd(Layer):
    """y = dropout(x) + y (incubate FusedDropoutAdd)."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.dropout = Dropout(p, mode=mode)

    def forward(self, x, y):
        return self.dropout(x) + y


class FusedEcMoe(Layer):
    """Expert-choice MoE (incubate FusedEcMoe): each EXPERT selects its
    top-capacity tokens (k = S * capacity_factor / E), so load balance is
    structural rather than auxiliary-loss-driven.

    forward(x [B, S, H], gate_logits [B, S, E]) -> [B, S, H].
    """

    def __init__(self, hidden_size, inter_size, num_experts,
                 act_type="gelu", capacity_factor=2.0, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        self.num_experts = num_experts
        self.capacity_factor = float(capacity_factor)
        init = weight_attr or I.XavierNormal()
        self.w1 = self.create_parameter(
            [num_experts, hidden_size, inter_size], attr=init)
        self.b1 = self.create_parameter([num_experts, inter_size],
                                        attr=bias_attr, is_bias=True)
        self.w2 = self.create_parameter(
            [num_experts, inter_size, hidden_size], attr=init)
        self.b2 = self.create_parameter([num_experts, hidden_size],
                                        attr=bias_attr, is_bias=True)
        self.act = act_type

    def forward(self, x, gate_logits):
        E = self.num_experts
        cap = self.capacity_factor
        act = self.act

        def f(xv, gl, w1, b1, w2, b2):
            B, S, H = xv.shape
            k = max(1, int(S * cap / E))
            probs = jax.nn.softmax(gl.astype(jnp.float32), axis=-1)
            # expert-choice: per (batch, expert) pick top-k tokens
            pe = jnp.transpose(probs, (0, 2, 1))          # [B, E, S]
            gate, idx = jax.lax.top_k(pe, k)              # [B, E, k]
            tok = jnp.take_along_axis(
                xv[:, None], idx[..., None], axis=2)      # [B, E, k, H]
            h = jnp.einsum("bekh,ehi->beki", tok, w1) + b1[None, :, None]
            h = getattr(jax.nn, act)(h)
            out = jnp.einsum("beki,eih->bekh", h, w2) + b2[None, :, None]
            out = out * gate[..., None].astype(out.dtype)
            # scatter-add the expert outputs back to token positions
            y = jnp.zeros_like(xv)
            bidx = jnp.arange(B)[:, None, None]
            y = y.at[bidx, idx].add(out)
            return y

        return apply(f, x, gate_logits, self.w1, self.b1, self.w2,
                     self.b2, _op_name="fused_ec_moe")
