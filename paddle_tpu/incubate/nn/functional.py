"""paddle.incubate.nn.functional parity (reference:
python/paddle/incubate/nn/functional/ — fused_transformer.py etc.).

The reference exposes monolithic CUDA megakernels; the TPU-native
equivalents are jnp/F compositions that XLA fuses (the reason these
kernels exist — avoiding kernel-launch and HBM round-trips — is what the
XLA fusion pass already does on TPU). Signatures follow the reference's
weight layouts (e.g. qkv_weight [3, nheads, head_dim, embed_dim]).
"""
from __future__ import annotations

import jax.numpy as jnp

import paddle_tpu.nn.functional as F

from ...autograd.tape import apply
from ...core.tensor import Tensor

__all__ = ["fused_multi_head_attention", "fused_feedforward",
           "fused_multi_transformer", "fused_matmul_bias", "fused_linear",
           "fused_bias_dropout_residual_layer_norm", "fused_ec_moe",
           "fused_dropout_add"]


def _t(x):
    return x if isinstance(x, Tensor) or x is None else Tensor(jnp.asarray(x))


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """Parity: incubate.nn.functional.fused_linear."""
    w = _t(weight)
    if transpose_weight:
        from ...tensor import linalg as L
        w = L.transpose(w, [1, 0])
    return F.linear(_t(x), w, _t(bias))


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """Parity: fused_matmul_bias — cublasLt epilogue in the reference;
    one XLA fusion here."""
    def f(xv, yv, *b):
        xv2 = jnp.swapaxes(xv, -1, -2) if transpose_x else xv
        yv2 = jnp.swapaxes(yv, -1, -2) if transpose_y else yv
        out = xv2 @ yv2
        return out + b[0] if b else out
    args = [_t(x), _t(y)] + ([_t(bias)] if bias is not None else [])
    return apply(f, *args, _op_name="fused_matmul_bias")


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """Parity: fused_dropout_add — dropout(x) + y."""
    return F.dropout(_t(x), p=p, training=training, mode=mode) + _t(y)


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True, mode=
        "upscale_in_train", name=None):
    """Parity: fused_bias_dropout_residual_layer_norm:
    LN(residual + dropout(x + bias))."""
    h = _t(x)
    if bias is not None:
        h = h + _t(bias)
    h = F.dropout(h, p=dropout_rate, training=training, mode=mode)
    h = h + _t(residual)
    shape = [h.shape[-1]]
    return F.layer_norm(h, shape, _t(ln_scale), _t(ln_bias), ln_epsilon)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, mode=
                      "upscale_in_train", name=None):
    """Parity: fused_feedforward (fused_transformer.py) — residual FFN
    with pre- or post-LN."""
    x = _t(x)
    shape = [x.shape[-1]]
    h = x
    if pre_layer_norm:
        h = F.layer_norm(h, shape, _t(ln1_scale), _t(ln1_bias), ln1_epsilon)
    h = F.linear(h, _t(linear1_weight), _t(linear1_bias))
    h = getattr(F, activation)(h)
    h = F.dropout(h, p=dropout1_rate, training=training, mode=mode)
    h = F.linear(h, _t(linear2_weight), _t(linear2_bias))
    h = F.dropout(h, p=dropout2_rate, training=training, mode=mode)
    out = x + h
    if not pre_layer_norm:
        out = F.layer_norm(out, shape, _t(ln2_scale), _t(ln2_bias),
                           ln2_epsilon)
    return out


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.5,
                               attn_dropout_rate=0.5, ln_epsilon=1e-5,
                               training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, name=None):
    """Parity: fused_multi_head_attention — reference weight layout
    qkv_weight [3, nheads, head_dim, embed], linear_weight [embed, embed].
    residual + dropout(proj(attn(qkv(ln? x)))) then post-LN."""
    if cache_kv is not None:
        raise NotImplementedError(
            "cache_kv decode runs through models.generation's compiled "
            "decode program")
    x = _t(x)
    B, S, E = x.shape
    qkvw = _t(qkv_weight)
    three, nh, hd, _ = qkvw.shape
    shape = [E]
    h = x
    if pre_layer_norm:
        h = F.layer_norm(h, shape, _t(pre_ln_scale), _t(pre_ln_bias),
                         pre_ln_epsilon)

    def project(hv, wv, *b):
        qkv = jnp.einsum("bse,tnde->tbnsd", hv, wv)
        if b:
            qkv = qkv + b[0].reshape(three, 1, nh, 1, hd)
        return qkv

    args = [h, qkvw] + ([_t(qkv_bias)] if qkv_bias is not None else [])
    qkv = apply(project, *args, _op_name="fused_qkv")

    def scores(qkvv, *m):
        q, k = qkvv[0], qkvv[1]                   # [B, nh, S, hd]
        s = jnp.einsum("bnqd,bnkd->bnqk", q, k) / jnp.sqrt(float(hd))
        if m:
            s = s + m[0]
        p = jnp.exp(s - jnp.max(s, -1, keepdims=True))
        return p / jnp.sum(p, -1, keepdims=True)

    margs = [qkv] + ([_t(attn_mask)] if attn_mask is not None else [])
    probs = apply(scores, *margs, _op_name="fused_attn_scores")
    # attention dropout on the probabilities, like the reference kernel
    probs = F.dropout(probs, p=attn_dropout_rate, training=training,
                      mode=mode)

    def mix(pv, qkvv):
        ctx = jnp.einsum("bnqk,bnkd->bqnd", pv, qkvv[2])
        return ctx.reshape(B, S, nh * hd)

    ctx = apply(mix, probs, qkv, _op_name="fused_attn_mix")
    out = F.linear(ctx, _t(linear_weight), _t(linear_bias))
    out = F.dropout(out, p=dropout_rate, training=training, mode=mode)
    if add_residual:
        out = x + out
    if not pre_layer_norm:
        out = F.layer_norm(out, shape, _t(ln_scale), _t(ln_bias), ln_epsilon)
    return out


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights, qkv_biases,
                            linear_weights, linear_biases, ffn_ln_scales,
                            ffn_ln_biases, ffn1_weights, ffn1_biases,
                            ffn2_weights, ffn2_biases, pre_layer_norm=True,
                            epsilon=1e-5, cache_kvs=None, time_step=None,
                            attn_mask=None, dropout_rate=0.0,
                            activation="gelu", training=False, mode=
                            "upscale_in_train", trans_qkvw=True,
                            ring_id=-1, name=None):
    """Parity: fused_multi_transformer — a stack of pre-LN blocks; the
    CacheKV decode path lives in incubate.nn.FusedMultiTransformer."""
    if cache_kvs is not None or time_step is not None:
        raise NotImplementedError(
            "cache_kvs decode: use incubate.nn.FusedMultiTransformer (the "
            "layer owns the cache buffers) or models.generation")
    if not pre_layer_norm:
        raise NotImplementedError("reference kernel is pre-LN only")
    if not trans_qkvw:
        raise NotImplementedError(
            "trans_qkvw=False ([embed, 3*nh*hd]-layout qkv weights) is "
            "not wired; pass the default [3, nh, hd, embed] layout")
    h = _t(x)
    n = len(qkv_weights)
    for i in range(n):
        h = fused_multi_head_attention(
            h, qkv_weights[i], linear_weights[i], pre_layer_norm=True,
            pre_ln_scale=ln_scales[i],
            pre_ln_bias=ln_biases[i] if ln_biases else None,
            qkv_bias=qkv_biases[i] if qkv_biases else None,
            linear_bias=linear_biases[i] if linear_biases else None,
            attn_mask=attn_mask, dropout_rate=dropout_rate,
            attn_dropout_rate=dropout_rate, training=training, mode=mode)
        h = fused_feedforward(
            h, ffn1_weights[i], ffn2_weights[i],
            linear1_bias=ffn1_biases[i] if ffn1_biases else None,
            linear2_bias=ffn2_biases[i] if ffn2_biases else None,
            ln1_scale=ffn_ln_scales[i],
            ln1_bias=ffn_ln_biases[i] if ffn_ln_biases else None,
            dropout1_rate=dropout_rate, dropout2_rate=dropout_rate,
            activation=activation, pre_layer_norm=True, training=training,
            mode=mode)
    return h


def fused_ec_moe(x, gate, bmm0_weight, bmm0_bias, bmm1_weight, bmm1_bias,
                 act_type="gelu", name=None):
    """Parity: fused_ec_moe — dense expert mixture: every token runs all
    experts' FFNs (batched on the MXU) weighted by softmax(gate).
    x: [B, S, d]; gate: [B, S, e]; bmm0: [e, d, d_ff]; bmm1: [e, d_ff, d]."""
    if act_type not in ("gelu", "relu"):
        raise ValueError("fused_ec_moe act_type must be gelu|relu")

    def f(xv, gv, w0, b0, w1, b1):
        p = jnp.exp(gv - jnp.max(gv, -1, keepdims=True))
        p = p / jnp.sum(p, -1, keepdims=True)          # [B, S, e]
        h = jnp.einsum("bsd,edf->besf", xv, w0) + b0[None, :, None, :]
        h = (jnp.maximum(h, 0) if act_type == "relu"
             else 0.5 * h * (1 + jnp.tanh(0.7978845608 *
                                          (h + 0.044715 * h ** 3))))
        y = jnp.einsum("besf,efd->besd", h, w1) + b1[None, :, None, :]
        return jnp.einsum("besd,bse->bsd", y, p)

    return apply(f, _t(x), _t(gate), _t(bmm0_weight), _t(bmm0_bias),
                 _t(bmm1_weight), _t(bmm1_bias), _op_name="fused_ec_moe")
