"""paddle.incubate.nn — fused transformer building blocks.

Parity: python/paddle/incubate/nn/__init__.py (FusedMultiHeadAttention,
FusedFeedForward, FusedTransformerEncoderLayer, FusedMultiTransformer,
FusedLinear, FusedBiasDropoutResidualLayerNorm, FusedEcMoe,
FusedDropoutAdd) over the fused CUDA ops (operators/fused/
fused_attention_op.cu, fused_feedforward, fused_multi_transformer_op.cu —
SURVEY.md §2.4). TPU-native stance: "fused" is the compiler's job — these
layers express the same math through the flash-attention dispatch and
plain jnp compositions, and XLA fuses the elementwise chains; the API
surface (normalize_before semantics, CacheKV decode on
FusedMultiTransformer) is what carries over.
"""
from .layers import (FusedBiasDropoutResidualLayerNorm, FusedDropoutAdd,
                     FusedEcMoe, FusedFeedForward, FusedLinear,
                     FusedMultiHeadAttention, FusedMultiTransformer,
                     FusedTransformerEncoderLayer)

from . import functional  # noqa: F401
__all__ = ["functional", "FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer", "FusedMultiTransformer",
           "FusedLinear", "FusedBiasDropoutResidualLayerNorm",
           "FusedEcMoe", "FusedDropoutAdd"]
