"""paddle.vision.datasets parity — file-format parsers for the classic
vision datasets.

Reference: python/paddle/vision/datasets/{mnist,cifar,flowers,folder,
voc2012}.py. The reference downloads archives from paddle-dataset URLs;
this build runs with zero network egress, so every dataset takes local
file paths (same constructor parameters) and raises a clear error when
asked to download. File formats match the originals exactly (idx
ubyte/gzip for MNIST, pickled batches in tar.gz for CIFAR, .mat labels
for Flowers), so locally present copies of the standard archives load
unchanged.
"""
from __future__ import annotations

import gzip
import io as _io
import os
import pickle
import struct
import tarfile
import threading

import numpy as np

from ...io.dataloader import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "Flowers",
           "DatasetFolder", "ImageFolder", "VOC2012"]

_NO_DOWNLOAD = (
    "{name}: automatic download is unavailable in this build (no network "
    "egress); pass {args} pointing at a local copy of the standard archive")


def _open_maybe_gzip(path):
    with open(path, "rb") as f:
        head = f.read(2)
    if head == b"\x1f\x8b":
        return gzip.open(path, "rb")
    return open(path, "rb")


class MNIST(Dataset):
    """Parity: vision/datasets/mnist.py:104 — idx-ubyte image/label files
    (optionally gzipped). Yields (image HW1 float32 numpy, label int64)."""

    NAME = "MNIST"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        mode = mode.lower()
        assert mode in ("train", "test"), (
            f"mode should be 'train' or 'test', but got {mode}")
        if backend is None:
            backend = "pil"
        if backend not in ("pil", "cv2"):
            raise ValueError(
                f"Expected backend are one of ['pil', 'cv2'], but got "
                f"{backend}")
        if image_path is None or label_path is None:
            raise RuntimeError(_NO_DOWNLOAD.format(
                name=self.NAME, args="image_path/label_path"))
        self.mode = mode
        self.transform = transform
        self.backend = backend
        with _open_maybe_gzip(image_path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            assert magic == 2051, f"bad idx image magic {magic}"
            self.images = np.frombuffer(
                f.read(n * rows * cols), np.uint8).reshape(n, rows, cols)
        with _open_maybe_gzip(label_path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            assert magic == 2049, f"bad idx label magic {magic}"
            self.labels = np.frombuffer(f.read(n), np.uint8).astype(
                np.int64)[:, None]

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[:, :, None]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    """Parity: vision/datasets/mnist.py FashionMNIST — same idx format."""

    NAME = "FashionMNIST"


class Cifar10(Dataset):
    """Parity: vision/datasets/cifar.py:106 — pickled batches inside the
    standard cifar-10-python.tar.gz. Yields (image 32x32x3, label)."""

    _mode_pat = {"train": "data_batch", "test": "test_batch"}
    _label_key = b"labels"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        mode = mode.lower()
        assert mode in ("train", "test"), (
            f"mode should be 'train' or 'test', but got {mode}")
        if data_file is None:
            raise RuntimeError(_NO_DOWNLOAD.format(
                name=type(self).__name__, args="data_file"))
        self.mode = mode
        self.transform = transform
        self.backend = backend or "pil"
        images, labels = [], []
        pat = self._mode_pat[mode]
        with tarfile.open(data_file, "r:*") as tf:
            names = [m for m in tf.getmembers()
                     if pat in os.path.basename(m.name)]
            names.sort(key=lambda m: m.name)
            for m in names:
                batch = pickle.load(tf.extractfile(m), encoding="bytes")
                images.append(np.asarray(batch[b"data"], np.uint8))
                labels.extend(batch[self._label_key])
        self.data = np.concatenate(images, 0).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        img = self.data[idx].transpose(1, 2, 0).astype(np.float32)
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    """Parity: vision/datasets/cifar.py:255 — cifar-100-python.tar.gz."""

    _mode_pat = {"train": "train", "test": "test"}
    _label_key = b"fine_labels"


class Flowers(Dataset):
    """Parity: vision/datasets/flowers.py:110 — 102 Category Flowers:
    images tarball + imagelabels.mat + setid.mat."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        mode = mode.lower()
        assert mode in ("train", "valid", "test"), (
            f"mode should be 'train', 'valid' or 'test', but got {mode}")
        if data_file is None or label_file is None or setid_file is None:
            raise RuntimeError(_NO_DOWNLOAD.format(
                name="Flowers", args="data_file/label_file/setid_file"))
        import scipy.io
        self.transform = transform
        self.backend = backend or "pil"
        labels = scipy.io.loadmat(label_file)["labels"].ravel()
        setid = scipy.io.loadmat(setid_file)
        key = {"train": "trnid", "valid": "valid", "test": "tstid"}[mode]
        self.indexes = setid[key].ravel()
        self.labels = labels
        self._tar = tarfile.open(data_file, "r:*")
        # TarFile shares one seekable fileobj — serialize reads so the
        # thread-pool DataLoader (num_workers>0) can't interleave them
        self._tar_lock = threading.Lock()
        self._members = {os.path.basename(m.name): m
                         for m in self._tar.getmembers() if m.isfile()}

    def __getitem__(self, idx):
        from PIL import Image
        img_id = int(self.indexes[idx])
        name = "image_%05d.jpg" % img_id
        with self._tar_lock:
            data = self._tar.extractfile(self._members[name]).read()
        img = Image.open(_io.BytesIO(data)).convert("RGB")
        img = np.asarray(img, np.float32)
        if self.transform is not None:
            img = self.transform(img)
        label = np.asarray([self.labels[img_id - 1]], np.int64)
        return img, label

    def __len__(self):
        return len(self.indexes)


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp")


def _default_loader(path):
    from PIL import Image
    with open(path, "rb") as f:
        return Image.open(f).convert("RGB")


class DatasetFolder(Dataset):
    """Parity: vision/datasets/folder.py:203 — class-per-subdirectory
    layout; samples are (image, class_index)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        if extensions is None and is_valid_file is None:
            extensions = IMG_EXTENSIONS
        classes = sorted(d.name for d in os.scandir(root) if d.is_dir())
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        if is_valid_file is None:
            def is_valid_file(p):
                return p.lower().endswith(tuple(extensions))
        samples = []
        for c in classes:
            d = os.path.join(root, c)
            for base, _, files in sorted(os.walk(d)):
                for fn in sorted(files):
                    p = os.path.join(base, fn)
                    if is_valid_file(p):
                        samples.append((p, self.class_to_idx[c]))
        if not samples:
            raise RuntimeError(
                f"Found 0 files in subfolders of: {root}\nSupported "
                f"extensions are: {','.join(extensions or ())}")
        self.samples = samples
        self.targets = [s[1] for s in samples]
        self.loader = loader or _default_loader
        self.extensions = extensions

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        img = np.asarray(img, np.float32)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Parity: vision/datasets/folder.py:426 — flat folder of images,
    samples are just images (no labels)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        if extensions is None and is_valid_file is None:
            extensions = IMG_EXTENSIONS
        if is_valid_file is None:
            def is_valid_file(p):
                return p.lower().endswith(tuple(extensions))
        samples = []
        for base, _, files in sorted(os.walk(root)):
            for fn in sorted(files):
                p = os.path.join(base, fn)
                if is_valid_file(p):
                    samples.append(p)
        if not samples:
            raise RuntimeError(
                f"Found 0 files in subfolders of: {root}\nSupported "
                f"extensions are: {','.join(extensions or ())}")
        self.samples = samples
        self.loader = loader or _default_loader

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        img = np.asarray(img, np.float32)
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


class VOC2012(Dataset):
    """Parity: vision/datasets/voc2012.py:106 — segmentation pairs from a
    local VOCtrainval tar. Yields (image, label-mask) numpy arrays."""

    _base = "VOCdevkit/VOC2012"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        mode = mode.lower()
        assert mode in ("train", "valid", "test"), (
            f"mode should be 'train', 'valid' or 'test', but got {mode}")
        if data_file is None:
            raise RuntimeError(_NO_DOWNLOAD.format(
                name="VOC2012", args="data_file"))
        self.transform = transform
        self.backend = backend or "pil"
        self._tar = tarfile.open(data_file, "r:*")
        self._tar_lock = threading.Lock()  # see Flowers note
        names = {m.name: m for m in self._tar.getmembers()}
        # reference voc2012.py:36 MODE_FLAG_MAP:
        # train → trainval, test → train, valid → val
        setname = {"train": "trainval.txt", "valid": "val.txt",
                   "test": "train.txt"}[mode]
        listpath = f"{self._base}/ImageSets/Segmentation/{setname}"
        ids = self._tar.extractfile(names[listpath]).read().decode() \
            .split()
        self._pairs = [
            (f"{self._base}/JPEGImages/{i}.jpg",
             f"{self._base}/SegmentationClass/{i}.png") for i in ids]
        self._members = names

    def __getitem__(self, idx):
        from PIL import Image
        ip, lp = self._pairs[idx]
        with self._tar_lock:
            img_bytes = self._tar.extractfile(self._members[ip]).read()
            lbl_bytes = self._tar.extractfile(self._members[lp]).read()
        img = Image.open(_io.BytesIO(img_bytes)).convert("RGB")
        lbl = Image.open(_io.BytesIO(lbl_bytes))
        img = np.asarray(img, np.float32)
        lbl = np.asarray(lbl, np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, lbl

    def __len__(self):
        return len(self._pairs)
