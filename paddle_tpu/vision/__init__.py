"""paddle.vision parity (SURVEY.md §2.8 vision row): model zoo +
transforms + datasets scaffolding."""
from . import models, transforms  # noqa: F401
from . import ops  # noqa: F401
from . import datasets  # noqa: F401

__all__ = ["models", "transforms", "ops", "datasets",
           "set_image_backend", "get_image_backend", "image_load"]

_image_backend = "pil"


def set_image_backend(backend):
    """Parity: vision/image.py set_image_backend ('pil' or 'cv2')."""
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(
            f"Expected backend are one of ['pil', 'cv2', 'tensor'], "
            f"but got {backend}")
    _image_backend = backend


def get_image_backend():
    """Parity: vision/image.py get_image_backend."""
    return _image_backend


def image_load(path, backend=None):
    """Parity: vision/image.py image_load — PIL-backed (cv2 absent in
    this environment; numpy array returned for backend='cv2', Tensor
    for backend='tensor')."""
    import numpy as _np
    from PIL import Image
    backend = backend or _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(
            f"Expected backend are one of ['pil', 'cv2', 'tensor'], "
            f"but got {backend}")
    img = Image.open(path)
    if backend == "cv2":
        return _np.asarray(img)
    if backend == "tensor":
        from ..core.tensor import Tensor
        import jax.numpy as _jnp
        arr = _np.asarray(img)
        if arr.ndim == 3:
            arr = arr.transpose(2, 0, 1)  # CHW, reference tensor layout
        return Tensor(_jnp.asarray(arr), stop_gradient=True)
    return img
