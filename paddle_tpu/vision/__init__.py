"""paddle.vision parity (SURVEY.md §2.8 vision row): model zoo +
transforms + datasets scaffolding."""
from . import models, transforms  # noqa: F401

__all__ = ["models", "transforms"]
