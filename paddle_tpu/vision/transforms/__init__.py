"""paddle.vision.transforms parity — numpy/host-side image transforms
(the reference's transforms operate on PIL/numpy before the device;
SURVEY.md §2.8 vision row). Minimal functional core; Compose pipelines
plug into paddle_tpu.io.DataLoader workers.
"""
from __future__ import annotations

import numpy as np

__all__ = ["Compose", "Normalize", "Resize", "CenterCrop", "RandomCrop",
           "RandomHorizontalFlip", "ToTensor", "Transpose"]


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    def __init__(self, mean, std, data_format="CHW", **kw):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, x):
        x = np.asarray(x, np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        return (x - self.mean.reshape(shape)) / self.std.reshape(shape)


def _resize_np(img, size):
    """Nearest-neighbor host resize (HWC uint8/float)."""
    h, w = img.shape[:2]
    if isinstance(size, int):
        if h < w:
            nh, nw = size, int(w * size / h)
        else:
            nh, nw = int(h * size / w), size
    else:
        nh, nw = size
    ys = (np.arange(nh) * (h / nh)).astype(np.int64).clip(0, h - 1)
    xs = (np.arange(nw) * (w / nw)).astype(np.int64).clip(0, w - 1)
    return img[ys][:, xs]


class Resize:
    def __init__(self, size, interpolation="nearest", **kw):
        self.size = size

    def __call__(self, img):
        return _resize_np(np.asarray(img), self.size)


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        th, tw = self.size
        i, j = max((h - th) // 2, 0), max((w - tw) // 2, 0)
        return img[i:i + th, j:j + tw]


class RandomCrop:
    def __init__(self, size, **kw):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, max(h - th, 0) + 1)
        j = np.random.randint(0, max(w - tw, 0) + 1)
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class ToTensor:
    """HWC uint8 -> CHW float32 in [0,1]."""

    def __init__(self, data_format="CHW", **kw):
        self.data_format = data_format

    def __call__(self, img):
        x = np.asarray(img, np.float32) / 255.0
        if x.ndim == 2:
            x = x[:, :, None]
        if self.data_format == "CHW":
            x = x.transpose(2, 0, 1)
        return x


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)
