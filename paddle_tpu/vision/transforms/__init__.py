"""paddle.vision.transforms parity — numpy/host-side image transforms
(the reference's transforms operate on PIL/numpy before the device;
SURVEY.md §2.8 vision row). Minimal functional core; Compose pipelines
plug into paddle_tpu.io.DataLoader workers.
"""
from __future__ import annotations

import numpy as np

__all__ = ["Compose", "Normalize", "Resize", "CenterCrop", "RandomCrop",
           "RandomHorizontalFlip", "ToTensor", "Transpose"]


def _keyed(keys, fn, inputs):
    """Apply fn to 'image' entries when keys are declared (BaseTransform
    contract), else to the single input."""
    if keys is None:
        return fn(inputs)
    return tuple(fn(v) if k == "image" else v
                 for k, v in zip(keys, inputs))


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    def __init__(self, mean, std, data_format="CHW", to_rgb=False,
                 keys=None, **kw):
        self.mean, self.std = mean, std
        self.data_format = data_format
        self.to_rgb = to_rgb
        self.keys = keys

    def _apply_image(self, x):
        from .functional import normalize
        return normalize(x, self.mean, self.std, self.data_format,
                         to_rgb=self.to_rgb)

    def __call__(self, x):
        if self.keys is None:
            return self._apply_image(x)
        return tuple(self._apply_image(v) if k == "image" else v
                     for k, v in zip(self.keys, x))


def _target_hw(img, size):
    h, w = img.shape[:2]
    if isinstance(size, int):
        if h < w:
            return size, int(w * size / h)
        return int(h * size / w), size
    return size


def _resize_nearest(img, nh, nw):
    h, w = img.shape[:2]
    ys = (np.arange(nh) * (h / nh)).astype(np.int64).clip(0, h - 1)
    xs = (np.arange(nw) * (w / nw)).astype(np.int64).clip(0, w - 1)
    return img[ys][:, xs]


def _resize_bilinear(img, nh, nw):
    h, w = img.shape[:2]
    arr = img.astype(np.float32)
    ys = (np.arange(nh) + 0.5) * (h / nh) - 0.5
    xs = (np.arange(nw) + 0.5) * (w / nw) - 0.5
    y0 = np.clip(np.floor(ys).astype(np.int64), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(np.int64), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)[:, None]
    wx = np.clip(xs - x0, 0.0, 1.0)[None, :]
    if arr.ndim == 3:
        wy = wy[..., None]
        wx = wx[..., None]
    top = arr[y0][:, x0] * (1 - wx) + arr[y0][:, x1] * wx
    bot = arr[y1][:, x0] * (1 - wx) + arr[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out.astype(img.dtype) if img.dtype == np.float32 else \
        np.round(out).astype(img.dtype)


class Resize:
    """Parity: transforms.Resize; nearest + bilinear host kernels."""

    def __init__(self, size, interpolation="bilinear", keys=None, **kw):
        self.keys = keys
        self.size = size
        if interpolation not in ("nearest", "bilinear"):
            raise ValueError(
                f"unsupported interpolation {interpolation!r}: this host "
                "resize implements 'nearest' and 'bilinear'")
        self.interpolation = interpolation

    def _apply_image(self, img):
        img = np.asarray(img)
        nh, nw = _target_hw(img, self.size)
        if self.interpolation == "nearest":
            return _resize_nearest(img, nh, nw)
        return _resize_bilinear(img, nh, nw)

    def __call__(self, img):
        return _keyed(self.keys, self._apply_image, img)


class CenterCrop:
    def __init__(self, size, keys=None):
        self.keys = keys
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        return _keyed(self.keys, self._apply_image, img)

    def _apply_image(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        th, tw = self.size
        if h < th or w < tw:
            raise ValueError(
                f"CenterCrop size ({th},{tw}) larger than image ({h},{w})")
        i, j = (h - th) // 2, (w - tw) // 2
        return img[i:i + th, j:j + tw]


class RandomCrop:
    def __init__(self, size, keys=None, **kw):
        self.keys = keys
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        return _keyed(self.keys, self._apply_image, img)

    def _apply_image(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        th, tw = self.size
        if h < th or w < tw:
            raise ValueError(
                f"RandomCrop size ({th},{tw}) larger than image ({h},{w})")
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob
        self.keys = keys

    def __call__(self, img):
        return _keyed(self.keys, self._apply_image, img)

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class ToTensor:
    """HWC uint8 -> CHW float32 in [0,1] (floats pass through unscaled,
    matching the reference's uint8-only scaling). Delegates to
    functional.to_tensor; returns a raw numpy array for collate
    friendliness."""

    def __init__(self, data_format="CHW", **kw):
        self.data_format = data_format

    def __call__(self, img):
        from .functional import to_tensor
        return to_tensor(img, self.data_format).numpy()


class Transpose:
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order
        self.keys = keys

    def __call__(self, img):
        return _keyed(self.keys,
                      lambda im: np.asarray(im).transpose(self.order), img)


# ---------------------------------------------------------------------------
# full transform surface (reference: vision/transforms/transforms.py) over
# the functional kernels in .functional
# ---------------------------------------------------------------------------
from . import functional  # noqa: E402
from .functional import (adjust_brightness, adjust_contrast,  # noqa: E402
                         adjust_hue, adjust_saturation, affine,
                         center_crop, crop, erase, hflip, normalize, pad,
                         perspective, resize, rotate, to_grayscale,
                         to_tensor, vflip)

__all__ += ["BaseTransform", "BrightnessTransform", "ColorJitter",
            "ContrastTransform", "Grayscale", "HueTransform", "Pad",
            "RandomAffine", "RandomErasing", "RandomPerspective",
            "RandomResizedCrop", "RandomRotation", "RandomVerticalFlip",
            "SaturationTransform", "functional",
            "to_tensor", "normalize", "resize", "pad", "crop",
            "center_crop", "hflip", "vflip", "rotate", "affine",
            "perspective", "erase", "to_grayscale", "adjust_brightness",
            "adjust_contrast", "adjust_saturation", "adjust_hue"]


class BaseTransform:
    """Parity: transforms.BaseTransform — subclasses implement
    _apply_image (and optionally keys for paired targets)."""

    def __init__(self, keys=None):
        self.keys = keys

    def _apply_image(self, img):
        raise NotImplementedError

    def __call__(self, inputs):
        if self.keys is None:
            return self._apply_image(inputs)
        outs = []
        for key, inp in zip(self.keys, inputs):
            outs.append(self._apply_image(inp) if key == "image" else inp)
        return tuple(outs)


def _jitter_range(value, name, center=1.0, bound=None):
    """Reference _check_input (transforms.py:50): scalar v -> the range
    [max(0, center-v), center+v]; a (min, max) pair passes through.
    Returns None when the range collapses to the identity."""
    if np.isscalar(value):
        if value < 0:
            raise ValueError(f"{name} value should be non-negative")
        lo, hi = center - float(value), center + float(value)
        if bound is None:
            lo = max(0.0, lo)
    else:
        lo, hi = (float(v) for v in value)
        if lo > hi:
            raise ValueError(f"{name} range must have min <= max")
    if bound is not None and not (bound[0] <= lo <= hi <= bound[1]):
        raise ValueError(f"{name} values should be within {bound}")
    if (lo, hi) == (center, center):
        return None
    return lo, hi


class _JitterBase(BaseTransform):
    _name = ""
    _center = 1.0
    _bound = None

    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.rng = _jitter_range(value, self._name, self._center,
                                 self._bound)

    def _adjust(self, img, factor):
        raise NotImplementedError

    def _apply_image(self, img):
        if self.rng is None:
            return np.asarray(img)
        return self._adjust(img, np.random.uniform(*self.rng))


class BrightnessTransform(_JitterBase):
    _name = "brightness"

    def _adjust(self, img, f):
        return adjust_brightness(img, f)


class ContrastTransform(_JitterBase):
    _name = "contrast"

    def _adjust(self, img, f):
        return adjust_contrast(img, f)


class SaturationTransform(_JitterBase):
    _name = "saturation"

    def _adjust(self, img, f):
        return adjust_saturation(img, f)


class HueTransform(_JitterBase):
    _name = "hue"
    _center = 0.0
    _bound = (-0.5, 0.5)

    def _adjust(self, img, f):
        return adjust_hue(img, f)


class ColorJitter(BaseTransform):
    """Parity: transforms.ColorJitter — random order of the four jitters."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.ts = [BrightnessTransform(brightness),
                   ContrastTransform(contrast),
                   SaturationTransform(saturation), HueTransform(hue)]

    def _apply_image(self, img):
        for i in np.random.permutation(len(self.ts)):
            img = self.ts[i]._apply_image(img)
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant",
                 keys=None):
        super().__init__(keys)
        self.padding, self.fill = padding, fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return vflip(img) if np.random.rand() < self.prob \
            else np.asarray(img)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="bilinear", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if np.isscalar(degrees):
            if degrees < 0:
                raise ValueError("degrees must be non-negative")
            self.degrees = (-float(degrees), float(degrees))
        else:
            self.degrees = tuple(degrees)
        self.interpolation, self.expand = interpolation, expand
        self.center, self.fill = center, fill

    def _apply_image(self, img):
        angle = np.random.uniform(*self.degrees)
        return rotate(img, angle, self.interpolation, self.expand,
                      self.center, self.fill)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="bilinear", fill=0, center=None, keys=None):
        super().__init__(keys)
        self.degrees = (-float(degrees), float(degrees)) \
            if np.isscalar(degrees) else tuple(degrees)
        self.translate, self.scale_rng = translate, scale
        self.shear = shear
        self.interpolation, self.fill, self.center = \
            interpolation, fill, center

    def _apply_image(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        angle = np.random.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate is not None:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1], self.translate[1]) * h
        scale = np.random.uniform(*self.scale_rng) if self.scale_rng \
            else 1.0
        shear = (0.0, 0.0)
        if self.shear is not None:
            s = self.shear
            if np.isscalar(s):
                shear = (np.random.uniform(-s, s), 0.0)
            elif len(s) == 2:
                shear = (np.random.uniform(s[0], s[1]), 0.0)
            else:
                shear = (np.random.uniform(s[0], s[1]),
                         np.random.uniform(s[2], s[3]))
        return affine(img, angle, (tx, ty), scale, shear,
                      self.interpolation, self.fill, self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="bilinear", fill=0, keys=None):
        super().__init__(keys)
        self.prob, self.distortion_scale = prob, distortion_scale
        self.interpolation, self.fill = interpolation, fill

    def _apply_image(self, img):
        img = np.asarray(img)
        if np.random.rand() >= self.prob:
            return img
        h, w = img.shape[:2]
        d = self.distortion_scale
        dx, dy = int(d * w / 2), int(d * h / 2)
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        jitter = lambda lo, hi: int(np.random.randint(lo, hi + 1))
        end = [(jitter(0, dx), jitter(0, dy)),
               (w - 1 - jitter(0, dx), jitter(0, dy)),
               (w - 1 - jitter(0, dx), h - 1 - jitter(0, dy)),
               (jitter(0, dx), h - 1 - jitter(0, dy))]
        return perspective(img, start, end, self.interpolation, self.fill)


class RandomResizedCrop(BaseTransform):
    """Parity: transforms.RandomResizedCrop — random area/ratio crop then
    resize to `size`."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4, 4. / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale, self.ratio = scale, ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = np.random.randint(0, h - ch + 1)
                j = np.random.randint(0, w - cw + 1)
                patch = img[i:i + ch, j:j + cw]
                return resize(patch, self.size, self.interpolation)
        return resize(center_crop(img, min(h, w)), self.size,
                      self.interpolation)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob, self.scale, self.ratio = prob, scale, ratio
        self.value, self.inplace = value, inplace

    def _apply_image(self, img):
        img = np.asarray(img)
        if np.random.rand() >= self.prob:
            return img
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.random.uniform(*self.ratio)
            eh = int(round(np.sqrt(target / ar)))
            ew = int(round(np.sqrt(target * ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh + 1)
                j = np.random.randint(0, w - ew + 1)
                if isinstance(self.value, str):
                    if self.value != "random":
                        raise ValueError(
                            f"unsupported erasing value {self.value!r}")
                    v = np.random.standard_normal(
                        (eh, ew) + img.shape[2:]).astype(np.float32)
                else:
                    v = self.value
                return erase(img, i, j, eh, ew, v, self.inplace)
        return img
