"""paddle.vision.transforms parity — numpy/host-side image transforms
(the reference's transforms operate on PIL/numpy before the device;
SURVEY.md §2.8 vision row). Minimal functional core; Compose pipelines
plug into paddle_tpu.io.DataLoader workers.
"""
from __future__ import annotations

import numpy as np

__all__ = ["Compose", "Normalize", "Resize", "CenterCrop", "RandomCrop",
           "RandomHorizontalFlip", "ToTensor", "Transpose"]


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    def __init__(self, mean, std, data_format="CHW", **kw):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, x):
        x = np.asarray(x, np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        return (x - self.mean.reshape(shape)) / self.std.reshape(shape)


def _target_hw(img, size):
    h, w = img.shape[:2]
    if isinstance(size, int):
        if h < w:
            return size, int(w * size / h)
        return int(h * size / w), size
    return size


def _resize_nearest(img, nh, nw):
    h, w = img.shape[:2]
    ys = (np.arange(nh) * (h / nh)).astype(np.int64).clip(0, h - 1)
    xs = (np.arange(nw) * (w / nw)).astype(np.int64).clip(0, w - 1)
    return img[ys][:, xs]


def _resize_bilinear(img, nh, nw):
    h, w = img.shape[:2]
    arr = img.astype(np.float32)
    ys = (np.arange(nh) + 0.5) * (h / nh) - 0.5
    xs = (np.arange(nw) + 0.5) * (w / nw) - 0.5
    y0 = np.clip(np.floor(ys).astype(np.int64), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(np.int64), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)[:, None]
    wx = np.clip(xs - x0, 0.0, 1.0)[None, :]
    if arr.ndim == 3:
        wy = wy[..., None]
        wx = wx[..., None]
    top = arr[y0][:, x0] * (1 - wx) + arr[y0][:, x1] * wx
    bot = arr[y1][:, x0] * (1 - wx) + arr[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out.astype(img.dtype) if img.dtype == np.float32 else \
        np.round(out).astype(img.dtype)


class Resize:
    """Parity: transforms.Resize; nearest + bilinear host kernels."""

    def __init__(self, size, interpolation="bilinear", **kw):
        self.size = size
        if interpolation not in ("nearest", "bilinear"):
            raise ValueError(
                f"unsupported interpolation {interpolation!r}: this host "
                "resize implements 'nearest' and 'bilinear'")
        self.interpolation = interpolation

    def __call__(self, img):
        img = np.asarray(img)
        nh, nw = _target_hw(img, self.size)
        if self.interpolation == "nearest":
            return _resize_nearest(img, nh, nw)
        return _resize_bilinear(img, nh, nw)


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        th, tw = self.size
        if h < th or w < tw:
            raise ValueError(
                f"CenterCrop size ({th},{tw}) larger than image ({h},{w})")
        i, j = (h - th) // 2, (w - tw) // 2
        return img[i:i + th, j:j + tw]


class RandomCrop:
    def __init__(self, size, **kw):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        th, tw = self.size
        if h < th or w < tw:
            raise ValueError(
                f"RandomCrop size ({th},{tw}) larger than image ({h},{w})")
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class ToTensor:
    """HWC uint8 -> CHW float32 in [0,1] (floats pass through unscaled,
    matching the reference's uint8-only scaling)."""

    def __init__(self, data_format="CHW", **kw):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img)
        x = arr.astype(np.float32) / 255.0 if arr.dtype == np.uint8 \
            else arr.astype(np.float32)
        if x.ndim == 2:
            x = x[:, :, None]
        if self.data_format == "CHW":
            x = x.transpose(2, 0, 1)
        return x


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)
