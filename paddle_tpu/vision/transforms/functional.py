"""paddle.vision.transforms functional API.

Parity: python/paddle/vision/transforms/functional.py (+ functional_cv2 /
functional_pil / functional_tensor backends). Host-side numpy kernels on
HWC images (uint8 [0,255] or float [0,1]); geometric warps use
scipy.ndimage. These run in DataLoader workers — the device only ever
sees the collated batch (TPU-first split of work).
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["to_tensor", "normalize", "resize", "pad", "crop",
           "center_crop", "hflip", "vflip", "rotate", "affine",
           "perspective", "erase", "to_grayscale", "adjust_brightness",
           "adjust_contrast", "adjust_saturation", "adjust_hue"]

_GRAY = np.array([0.299, 0.587, 0.114], np.float32)


def _np(img) -> np.ndarray:
    from ...core.tensor import Tensor
    if isinstance(img, Tensor):
        return img.numpy()
    return np.asarray(img)


def _same_dtype(out: np.ndarray, ref: np.ndarray) -> np.ndarray:
    if np.issubdtype(ref.dtype, np.integer):
        return np.clip(np.round(out), 0, 255).astype(ref.dtype)
    return out.astype(ref.dtype)


def to_tensor(pic, data_format: str = "CHW"):
    """HWC image -> float32 Tensor; uint8 scaled to [0, 1]."""
    from ...core.tensor import Tensor
    arr = _np(pic)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    out = arr.astype(np.float32)
    if np.issubdtype(arr.dtype, np.integer):
        out = out / 255.0
    if data_format == "CHW":
        out = out.transpose(2, 0, 1)
    return Tensor(np.ascontiguousarray(out))


def normalize(img, mean, std, data_format: str = "CHW", to_rgb=False):
    """Reference: python/paddle/vision/transforms/functional.py normalize —
    to_rgb flips a BGR source to RGB before normalizing (cv2 backend)."""
    arr = _np(img).astype(np.float32)
    if to_rgb:
        arr = arr[::-1] if data_format == "CHW" else arr[..., ::-1]
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    shape = (-1, 1, 1) if data_format == "CHW" else (1, 1, -1)
    return (arr - mean.reshape(shape)) / std.reshape(shape)


def resize(img, size, interpolation: str = "bilinear"):
    from . import _resize_bilinear, _resize_nearest, _target_hw
    arr = _np(img)
    nh, nw = _target_hw(arr, size)
    if interpolation == "nearest":
        return _resize_nearest(arr, nh, nw)
    if interpolation == "bilinear":
        return _resize_bilinear(arr, nh, nw)
    raise ValueError(f"unsupported interpolation {interpolation!r}")


def pad(img, padding, fill=0, padding_mode: str = "constant"):
    arr = _np(img)
    if isinstance(padding, int):
        l = r = t = b = padding
    elif len(padding) == 2:
        l, t = padding
        r, b = padding
    else:
        l, t, r, b = padding
    width = [(t, b), (l, r)] + [(0, 0)] * (arr.ndim - 2)
    if padding_mode == "constant":
        return np.pad(arr, width, mode="constant", constant_values=fill)
    mode = {"edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}.get(padding_mode)
    if mode is None:
        raise ValueError(f"unsupported padding_mode {padding_mode!r}")
    return np.pad(arr, width, mode=mode)


def crop(img, top: int, left: int, height: int, width: int):
    arr = _np(img)
    return arr[top:top + height, left:left + width]


def center_crop(img, output_size):
    arr = _np(img)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    th, tw = output_size
    h, w = arr.shape[:2]
    return crop(arr, max(0, (h - th) // 2), max(0, (w - tw) // 2), th, tw)


def hflip(img):
    return _np(img)[:, ::-1].copy()


def vflip(img):
    return _np(img)[::-1].copy()


def _warp(arr: np.ndarray, matrix: np.ndarray, out_hw=None, fill=0,
          order=1) -> np.ndarray:
    """Inverse-map warp: out[y, x] = in[M @ (x, y, 1)] via scipy."""
    from scipy import ndimage
    h, w = (out_hw or arr.shape[:2])
    # scipy works in (row, col) = (y, x); build the (y,x) inverse matrix
    m = np.array([[matrix[1, 1], matrix[1, 0], matrix[1, 2]],
                  [matrix[0, 1], matrix[0, 0], matrix[0, 2]],
                  [0, 0, 1]], np.float64)
    src = arr.astype(np.float32)
    if src.ndim == 2:
        out = ndimage.affine_transform(src, m, output_shape=(h, w),
                                       order=order, cval=fill)
    else:
        out = np.stack([ndimage.affine_transform(
            src[:, :, c], m, output_shape=(h, w), order=order, cval=fill)
            for c in range(src.shape[2])], axis=2)
    return _same_dtype(out, arr)


def _affine_inverse_matrix(center, angle, translate, scale, shear):
    """Inverse (output->input) affine matrix in (x, y) coordinates,
    matching the torchvision/paddle parameterization (positive angle =
    counter-clockwise; image y points down, hence the sign flip)."""
    rot = -math.radians(angle)
    sx, sy = (math.radians(s) for s in shear)
    cx, cy = center
    tx, ty = translate
    # forward: T(center) R S Shear T(-center) + translate; invert directly
    a = math.cos(rot - sy) / math.cos(sy)
    b = -math.cos(rot - sy) * math.tan(sx) / math.cos(sy) - math.sin(rot)
    c = math.sin(rot - sy) / math.cos(sy)
    d = -math.sin(rot - sy) * math.tan(sx) / math.cos(sy) + math.cos(rot)
    fwd = np.array([[a * scale, b * scale, 0],
                    [c * scale, d * scale, 0],
                    [0, 0, 1]], np.float64)
    fwd[0, 2] = cx + tx - fwd[0, 0] * cx - fwd[0, 1] * cy
    fwd[1, 2] = cy + ty - fwd[1, 0] * cx - fwd[1, 1] * cy
    return np.linalg.inv(fwd)


def affine(img, angle, translate=(0, 0), scale=1.0, shear=(0.0, 0.0),
           interpolation: str = "bilinear", fill=0, center=None):
    arr = _np(img)
    h, w = arr.shape[:2]
    if np.isscalar(shear):
        shear = (float(shear), 0.0)
    center = center or ((w - 1) * 0.5, (h - 1) * 0.5)
    inv = _affine_inverse_matrix(center, angle, translate, scale, shear)
    order = 0 if interpolation == "nearest" else 1
    return _warp(arr, inv, fill=fill, order=order)


def rotate(img, angle, interpolation: str = "bilinear", expand=False,
           center=None, fill=0):
    arr = _np(img)
    h, w = arr.shape[:2]
    if expand:
        rad = math.radians(angle)
        nw = int(abs(w * math.cos(rad)) + abs(h * math.sin(rad)) + 0.5)
        nh = int(abs(w * math.sin(rad)) + abs(h * math.cos(rad)) + 0.5)
        # rotate about the input center, then re-center into the larger
        # canvas
        cx, cy = (w - 1) * 0.5, (h - 1) * 0.5
        inv = _affine_inverse_matrix((cx, cy), angle, (0, 0), 1.0,
                                     (0.0, 0.0))
        shift = np.array([[1, 0, cx - (nw - 1) * 0.5],
                          [0, 1, cy - (nh - 1) * 0.5],
                          [0, 0, 1]], np.float64)
        order = 0 if interpolation == "nearest" else 1
        return _warp(arr, inv @ shift, out_hw=(nh, nw), fill=fill,
                     order=order)
    return affine(img, angle, interpolation=interpolation, fill=fill,
                  center=center)


def _homography(src_pts, dst_pts) -> np.ndarray:
    """dst -> src homography from 4 point pairs (least squares)."""
    A, b = [], []
    for (xs, ys), (xd, yd) in zip(src_pts, dst_pts):
        A.append([xd, yd, 1, 0, 0, 0, -xs * xd, -xs * yd])
        b.append(xs)
        A.append([0, 0, 0, xd, yd, 1, -ys * xd, -ys * yd])
        b.append(ys)
    coef, *_ = np.linalg.lstsq(np.asarray(A, np.float64),
                               np.asarray(b, np.float64), rcond=None)
    return np.append(coef, 1.0).reshape(3, 3)


def perspective(img, startpoints, endpoints,
                interpolation: str = "bilinear", fill=0):
    """Warp so that startpoints map onto endpoints ((x, y) corners)."""
    arr = _np(img)
    H = _homography(startpoints, endpoints)   # output -> input
    h, w = arr.shape[:2]
    ys, xs = np.meshgrid(np.arange(h, dtype=np.float64),
                         np.arange(w, dtype=np.float64), indexing="ij")
    denom = H[2, 0] * xs + H[2, 1] * ys + H[2, 2]
    sx = (H[0, 0] * xs + H[0, 1] * ys + H[0, 2]) / denom
    sy = (H[1, 0] * xs + H[1, 1] * ys + H[1, 2]) / denom
    from scipy import ndimage
    order = 0 if interpolation == "nearest" else 1
    src = arr.astype(np.float32)
    # fp epsilon past the border must not fall to fill: sample with
    # clipped coords, fill only genuinely-outside points
    tol = 1e-6
    inside = ((sx >= -tol) & (sx <= w - 1 + tol)
              & (sy >= -tol) & (sy <= h - 1 + tol))
    coords = np.stack([np.clip(sy, 0, h - 1), np.clip(sx, 0, w - 1)])
    if src.ndim == 2:
        out = ndimage.map_coordinates(src, coords, order=order, cval=fill)
        out = np.where(inside, out, fill)
    else:
        out = np.stack([ndimage.map_coordinates(
            src[:, :, c], coords, order=order, cval=fill)
            for c in range(src.shape[2])], axis=2)
        out = np.where(inside[..., None], out, fill)
    return _same_dtype(out, arr)


def erase(img, i: int, j: int, h: int, w: int, v, inplace: bool = False):
    arr = _np(img)
    out = arr if inplace else arr.copy()
    out[i:i + h, j:j + w] = v
    return out


def to_grayscale(img, num_output_channels: int = 1):
    arr = _np(img)
    if arr.ndim == 2 or arr.shape[-1] == 1:
        g = arr.reshape(arr.shape[:2] + (1,)).astype(np.float32)
    else:
        g = (arr[..., :3].astype(np.float32) @ _GRAY)[..., None]
    g = np.repeat(g, num_output_channels, axis=-1)
    return _same_dtype(g, arr)


def adjust_brightness(img, brightness_factor: float):
    arr = _np(img)
    return _same_dtype(arr.astype(np.float32) * brightness_factor, arr)


def adjust_contrast(img, contrast_factor: float):
    arr = _np(img)
    f = arr.astype(np.float32)
    gray_mean = float(to_grayscale(f).mean())
    return _same_dtype(gray_mean + contrast_factor * (f - gray_mean), arr)


def adjust_saturation(img, saturation_factor: float):
    arr = _np(img)
    f = arr.astype(np.float32)
    g = to_grayscale(f).astype(np.float32)
    if g.shape[-1] != f.shape[-1]:
        g = np.repeat(g, f.shape[-1], axis=-1)
    return _same_dtype(g + saturation_factor * (f - g), arr)


def adjust_hue(img, hue_factor: float):
    """Shift hue by hue_factor in [-0.5, 0.5] turns (HSV round-trip)."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    arr = _np(img)
    f = arr.astype(np.float32)
    scale = 255.0 if np.issubdtype(arr.dtype, np.integer) else 1.0
    rgb = f[..., :3] / scale
    mx = rgb.max(-1)
    mn = rgb.min(-1)
    diff = mx - mn
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    hue = np.zeros_like(mx)
    nz = diff > 0
    rm = nz & (mx == r)
    gm = nz & (mx == g) & ~rm
    bm = nz & ~rm & ~gm
    hue[rm] = ((g - b)[rm] / diff[rm]) % 6
    hue[gm] = (b - r)[gm] / diff[gm] + 2
    hue[bm] = (r - g)[bm] / diff[bm] + 4
    hue = (hue / 6.0 + hue_factor) % 1.0
    sat = np.where(mx > 0, diff / np.maximum(mx, 1e-12), 0.0)
    # HSV -> RGB
    hp = hue * 6.0
    c = mx * sat
    x = c * (1 - np.abs(hp % 2 - 1))
    m = mx - c
    zeros = np.zeros_like(c)
    idx = np.floor(hp).astype(int) % 6
    r2 = np.select([idx == 0, idx == 1, idx == 2, idx == 3, idx == 4],
                   [c, x, zeros, zeros, x], c)
    g2 = np.select([idx == 0, idx == 1, idx == 2, idx == 3, idx == 4],
                   [x, c, c, x, zeros], zeros)
    b2 = np.select([idx == 0, idx == 1, idx == 2, idx == 3, idx == 4],
                   [zeros, zeros, x, c, c], x)
    out = np.stack([r2 + m, g2 + m, b2 + m], axis=-1) * scale
    if f.shape[-1] > 3:
        out = np.concatenate([out, f[..., 3:]], axis=-1)
    return _same_dtype(out, arr)
