"""paddle.vision.ops parity — detection ops, TPU-first.

Reference surface: python/paddle/vision/ops.py (yolo_box:262, box_coder:572,
deform_conv2d:742, psroi_pool:1384, roi_pool:1504, roi_align:1628, nms:1853,
matrix_nms:2190, prior_box:425, distribute_fpn_proposals:1151). The reference
backs these with hand-written CUDA kernels (paddle/fluid/operators/detection/);
here every op is a static-shape jnp/lax composition:

- nms: vectorized O(N^2) IoU matrix + `lax.fori_loop` greedy suppression
  (sequential dependence is irreducible; the IoU matrix is the FLOPs and it
  is one batched matmul-shaped pass on the VPU).
- matrix_nms: fully parallel decay-matrix formulation (no loop at all).
- roi_align / roi_pool / psroi_pool: gather-based bilinear / masked-window
  sampling, vectorized over (roi, channel, bin, sample) — XLA fuses the
  gathers; variable per-roi sample counts are handled by masking up to a
  static maximum taken from the concrete boxes (eager) so shapes stay static.
- deform_conv2d: bilinear-sampled im2col then one grouped matmul (MXU),
  instead of the reference's per-pixel CUDA kernel.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..autograd.tape import apply
from ..core.tensor import Tensor

__all__ = [
    "yolo_box", "yolo_loss", "prior_box", "box_coder", "deform_conv2d",
    "DeformConv2D", "distribute_fpn_proposals", "psroi_pool", "PSRoIPool",
    "roi_pool", "RoIPool", "roi_align", "RoIAlign", "nms", "matrix_nms",
    "generate_proposals", "ConvNormActivation",
]


def _val(x):
    return x.value if isinstance(x, Tensor) else jnp.asarray(x)


def _np(x):
    return np.asarray(x.value if isinstance(x, Tensor) else x)


# ---------------------------------------------------------------------------
# IoU / NMS family
# ---------------------------------------------------------------------------

def _pairwise_iou(a, b):
    """IoU matrix between (N,4) and (M,4) xyxy boxes."""
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(area_a[:, None] + area_b[None, :] - inter,
                               1e-10)


def _nms_keep_mask(boxes, iou_threshold):
    """Greedy index-order NMS keep mask; jittable, static shapes."""
    n = boxes.shape[0]
    iou = _pairwise_iou(boxes, boxes)
    idx = jnp.arange(n)

    def body(i, keep):
        over = (iou[i] > iou_threshold) & keep & (idx < i)
        return keep.at[i].set(~jnp.any(over))

    return jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Parity: vision/ops.py:1853 — returns int64 indices of kept boxes.

    Plain call keeps boxes greedily in index order; with scores the boxes
    are score-sorted first; with categories NMS runs per category and the
    surviving indices are returned score-sorted (optionally top_k).
    """
    b = _np(boxes).astype(np.float32)
    keep_of = lambda bb: np.asarray(
        _nms_keep_mask(jnp.asarray(bb), float(iou_threshold)))

    if scores is None:
        idxs = np.nonzero(keep_of(b))[0]
        return Tensor(jnp.asarray(np.asarray(idxs)), stop_gradient=True)

    s = _np(scores).astype(np.float32)
    if category_idxs is None:
        order = np.argsort(-s, kind="stable")
        kept = keep_of(b[order])
        out = order[np.nonzero(kept)[0]]
        return Tensor(jnp.asarray(np.asarray(out)), stop_gradient=True)

    assert categories is not None, (
        "categories (unique category ids) is required with category_idxs")
    if top_k is not None:
        assert top_k <= s.shape[0], (
            "top_k should be smaller equal than the number of boxes")
    cat = _np(category_idxs)
    mask = np.zeros(s.shape[0], bool)
    for cid in categories:
        sub = np.nonzero(cat == int(cid))[0]
        if sub.size == 0:
            continue
        if sub.size == 1:
            mask[sub] = True
            continue
        order = sub[np.argsort(-s[sub], kind="stable")]
        kept = keep_of(b[order])
        mask[order[np.nonzero(kept)[0]]] = True
    kept_idx = np.nonzero(mask)[0]
    kept_idx = kept_idx[np.argsort(-s[kept_idx], kind="stable")]
    if top_k is not None:
        kept_idx = kept_idx[:top_k]
    return Tensor(jnp.asarray(np.asarray(kept_idx)), stop_gradient=True)


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Parity: vision/ops.py:2190 (SOLOv2 Matrix-NMS) — unlike greedy NMS
    this is loop-free: scores decay by the max IoU with any higher-scored
    box of the same class, computed as one masked matrix reduction.

    bboxes: (N, M, 4); scores: (N, C, M). Returns (out, rois_num[, index]):
    out rows are [label, score, x1, y1, x2, y2].
    """
    bb = _np(bboxes).astype(np.float32)
    sc = _np(scores).astype(np.float32)
    n_batch, n_cls, m = sc.shape
    outs, idxs, nums = [], [], []
    for bi in range(n_batch):
        rows = []
        for c in range(n_cls):
            if c == background_label:
                continue
            s = sc[bi, c]
            sel = np.nonzero(s > score_threshold)[0]
            if sel.size == 0:
                continue
            order = sel[np.argsort(-s[sel], kind="stable")][:nms_top_k]
            boxes_c = bb[bi, order]
            s_c = s[order]
            iou = np.asarray(_pairwise_iou(jnp.asarray(boxes_c),
                                           jnp.asarray(boxes_c)))
            k = len(order)
            tri = np.triu(np.ones((k, k), bool), 1)  # j < i pairs (row j)
            # decay_ij considers IoU of box i with each higher-scored j
            ious = np.where(tri, iou, 0.0).T  # (i, j) j<i
            iou_max_j = np.max(np.where(tri, iou, 0.0), axis=0)  # per j
            if use_gaussian:
                # reference decay_score<T,true> (matrix_nms_kernel.cc:70):
                # exp((max_iou^2 - iou^2) * sigma)
                decay = np.exp((iou_max_j[None, :] ** 2 - ious ** 2)
                               * gaussian_sigma)
            else:
                decay = (1.0 - ious) / np.maximum(1.0 - iou_max_j[None, :],
                                                  1e-10)
            decay = np.where(tri.T, decay, 1.0).min(axis=1)
            dec_s = s_c * decay
            keep = dec_s >= post_threshold
            for i in np.nonzero(keep)[0]:
                rows.append((float(c), float(dec_s[i]), *boxes_c[i],
                             bi * m + order[i]))
        rows.sort(key=lambda r: -r[1])
        if keep_top_k > 0:
            rows = rows[:keep_top_k]
        nums.append(len(rows))
        for r in rows:
            outs.append(r[:6])
            idxs.append(r[6])
    out = np.asarray(outs, np.float32).reshape(-1, 6)
    res = [Tensor(jnp.asarray(out), stop_gradient=True)]
    if return_rois_num:
        res.append(Tensor(jnp.asarray(np.asarray(nums, np.int32)),
                          stop_gradient=True))
    if return_index:
        res.append(Tensor(jnp.asarray(np.asarray(idxs, np.int32)),
                          stop_gradient=True))
    return tuple(res) if len(res) > 1 else res[0]


# ---------------------------------------------------------------------------
# RoI pooling family
# ---------------------------------------------------------------------------

def _roi_batch_index(boxes_num, total):
    bn = _np(boxes_num).astype(np.int64)
    return np.repeat(np.arange(len(bn)), bn)[:total]


def _out_hw(output_size):
    if isinstance(output_size, (list, tuple)):
        return int(output_size[0]), int(output_size[1])
    return int(output_size), int(output_size)


def _bilinear_gather(feat, bidx, ys, xs):
    """Sample feat (N,C,H,W) at per-roi fractional rows ys (R,Y) and cols
    xs (R,X) → (R, C, Y, X). Out-of-range (< -1 or > size) samples are 0,
    matching the reference roi_align CUDA kernel's boundary rule."""
    H, W = feat.shape[2], feat.shape[3]
    valid = ((ys > -1.0) & (ys < H))[:, None, :, None] & \
            ((xs > -1.0) & (xs < W))[:, None, None, :]
    y = jnp.clip(ys, 0.0, H - 1)
    x = jnp.clip(xs, 0.0, W - 1)
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    ly = (y - y0)[:, None, :, None]
    lx = (x - x0)[:, None, None, :]
    b = bidx[:, None, None, None]
    cc = jnp.arange(feat.shape[1])[None, :, None, None]

    def g(yy, xx):
        return feat[b, cc, yy[:, None, :, None], xx[:, None, None, :]]

    val = (g(y0, x0) * (1 - ly) * (1 - lx) + g(y0, x1) * (1 - ly) * lx
           + g(y1, x0) * ly * (1 - lx) + g(y1, x1) * ly * lx)
    return jnp.where(valid, val, 0.0)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """Parity: vision/ops.py:1628. Average of bilinear samples per bin.

    sampling_ratio=-1 uses per-roi adaptive ceil(roi_size/out) counts; the
    static-shape trick is to sample up to the max count over the (concrete)
    boxes and mask the average — exact reference numerics, static shapes.
    """
    ph, pw = _out_hw(output_size)
    bx = _np(boxes).astype(np.float32)
    bidx = jnp.asarray(_roi_batch_index(boxes_num, bx.shape[0]))
    off = 0.5 if aligned else 0.0
    roi_w = np.maximum(bx[:, 2] - bx[:, 0], 0) * spatial_scale
    roi_h = np.maximum(bx[:, 3] - bx[:, 1], 0) * spatial_scale
    if sampling_ratio > 0:
        sh = sw = int(sampling_ratio)
        nh = np.full(len(bx), sh, np.int32)
        nw = np.full(len(bx), sw, np.int32)
    else:
        nh = np.maximum(np.ceil(roi_h / ph).astype(np.int32), 1)
        nw = np.maximum(np.ceil(roi_w / pw).astype(np.int32), 1)
        sh, sw = int(nh.max(initial=1)), int(nw.max(initial=1))

    def f(feat, b):
        x1 = b[:, 0] * spatial_scale - off
        y1 = b[:, 1] * spatial_scale - off
        w = jnp.maximum(b[:, 2] * spatial_scale - off - x1,
                        1e-10 if aligned else 1.0)
        h = jnp.maximum(b[:, 3] * spatial_scale - off - y1,
                        1e-10 if aligned else 1.0)
        bin_h = h / ph
        bin_w = w / pw
        nhj = jnp.asarray(nh)[:, None, None]
        nwj = jnp.asarray(nw)[:, None, None]
        # sample grid (R, ph, sh) / (R, pw, sw), masked beyond per-roi count
        iy = jnp.arange(sh)[None, None, :]
        ix = jnp.arange(sw)[None, None, :]
        py = jnp.arange(ph)[None, :, None]
        px = jnp.arange(pw)[None, :, None]
        ys = y1[:, None, None] + (py + (iy + 0.5) / nhj) * bin_h[:, None, None]
        xs = x1[:, None, None] + (px + (ix + 0.5) / nwj) * bin_w[:, None, None]
        my = (iy < nhj)
        mx = (ix < nwj)
        vals = _bilinear_gather(feat, bidx, ys.reshape(len(bx), -1),
                                xs.reshape(len(bx), -1))
        vals = vals.reshape(len(bx), feat.shape[1], ph, sh, pw, sw)
        m = (my[:, None, :, :, None, None]
             & mx[:, None, None, None, :, :]).astype(vals.dtype)
        cnt = (jnp.asarray(nh) * jnp.asarray(nw)).astype(
            vals.dtype)[:, None, None, None]
        return (vals * m).sum((3, 5)) / cnt

    return apply(f, x, boxes, _op_name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Parity: vision/ops.py:1504 — quantized-bin max pooling. Variable bin
    extents are handled with a masked max over a static max window."""
    ph, pw = _out_hw(output_size)
    bx = _np(boxes).astype(np.float32)
    bidx = jnp.asarray(_roi_batch_index(boxes_num, bx.shape[0]))
    xs_np = np.round(bx * spatial_scale).astype(np.int64)
    rh = np.maximum(xs_np[:, 3] - xs_np[:, 1] + 1, 1)
    rw = np.maximum(xs_np[:, 2] - xs_np[:, 0] + 1, 1)
    # bin extent = ceil((i+1)h/ph) - floor(i*h/ph) <= h/ph + 2
    wh = int(np.max(np.ceil(rh / ph), initial=1)) + 2
    ww = int(np.max(np.ceil(rw / pw), initial=1)) + 2

    def f(feat, b):
        H, W = feat.shape[2], feat.shape[3]
        q = jnp.round(b * spatial_scale).astype(jnp.int32)
        x1, y1 = q[:, 0], q[:, 1]
        h = jnp.maximum(q[:, 3] - y1 + 1, 1)
        w = jnp.maximum(q[:, 2] - x1 + 1, 1)
        py = jnp.arange(ph)[None, :]
        px = jnp.arange(pw)[None, :]
        ys0 = y1[:, None] + jnp.floor(py * h[:, None] / ph).astype(jnp.int32)
        ye = y1[:, None] + jnp.ceil((py + 1) * h[:, None] / ph).astype(
            jnp.int32)
        xs0 = x1[:, None] + jnp.floor(px * w[:, None] / pw).astype(jnp.int32)
        xe = x1[:, None] + jnp.ceil((px + 1) * w[:, None] / pw).astype(
            jnp.int32)
        # reference clamps bin bounds into the image (roi_pool_kernel.cc:
        # 124-132); out-of-image bins become empty → 0
        ys0 = jnp.clip(ys0, 0, H)
        ye = jnp.clip(ye, 0, H)
        xs0 = jnp.clip(xs0, 0, W)
        xe = jnp.clip(xe, 0, W)
        dy = jnp.arange(wh)[None, None, :]
        dx = jnp.arange(ww)[None, None, :]
        yy = jnp.clip(ys0[:, :, None] + dy, 0, H - 1)  # (R, ph, wh)
        xx = jnp.clip(xs0[:, :, None] + dx, 0, W - 1)  # (R, pw, ww)
        myv = (ys0[:, :, None] + dy) < ye[:, :, None]
        mxv = (xs0[:, :, None] + dx) < xe[:, :, None]
        # full (R, C, ph, wh, pw, ww) gather, masked max over the window
        g = feat[bidx[:, None, None, None, None, None],
                 jnp.arange(feat.shape[1])[None, :, None, None, None, None],
                 yy[:, None, :, :, None, None],
                 xx[:, None, None, None, :, :]]
        m = myv[:, None, :, :, None, None] & mxv[:, None, None, None, :, :]
        neg = jnp.asarray(-jnp.inf, g.dtype)
        out = jnp.where(m, g, neg).max((3, 5))
        # empty bins (shouldn't happen since h,w >= 1) → 0
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return apply(f, x, boxes, _op_name="roi_pool")


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Parity: vision/ops.py:1384 (R-FCN position-sensitive average pool).
    Channel layout: C = out_c * ph * pw; bin (i,j) of output channel c reads
    input channel c*ph*pw + i*pw + j."""
    ph, pw = _out_hw(output_size)
    bx = _np(boxes).astype(np.float32)
    total = bx.shape[0]
    bidx = jnp.asarray(_roi_batch_index(boxes_num, total))
    C = (x.shape[1] if hasattr(x, "shape") else _val(x).shape[1])
    assert C % (ph * pw) == 0, (
        "psroi_pool: input channels must be divisible by pooled h*w")
    out_c = C // (ph * pw)
    # static max window from concrete boxes, mirroring f()'s rounded
    # start/end math exactly: bin extent = ceil(start+(i+1)*bin) -
    # floor(start+i*bin) <= bin + 2
    rh = np.maximum(np.round(bx[:, 3] + 1.0) * spatial_scale
                    - np.round(bx[:, 1]) * spatial_scale, 0.1)
    rw = np.maximum(np.round(bx[:, 2] + 1.0) * spatial_scale
                    - np.round(bx[:, 0]) * spatial_scale, 0.1)
    wh = int(np.max(np.ceil(rh / ph), initial=1)) + 2
    ww = int(np.max(np.ceil(rw / pw), initial=1)) + 2

    def f(feat, b):
        H, W = feat.shape[2], feat.shape[3]
        x1 = jnp.round(b[:, 0]) * spatial_scale
        y1 = jnp.round(b[:, 1]) * spatial_scale
        x2 = jnp.round(b[:, 2] + 1.0) * spatial_scale
        y2 = jnp.round(b[:, 3] + 1.0) * spatial_scale
        h = jnp.maximum(y2 - y1, 0.1)
        w = jnp.maximum(x2 - x1, 0.1)
        bin_h = h / ph
        bin_w = w / pw
        py = jnp.arange(ph)[None, :]
        px = jnp.arange(pw)[None, :]
        ys0 = jnp.floor(y1[:, None] + py * bin_h[:, None]).astype(jnp.int32)
        ye = jnp.ceil(y1[:, None] + (py + 1) * bin_h[:, None]).astype(
            jnp.int32)
        xs0 = jnp.floor(x1[:, None] + px * bin_w[:, None]).astype(jnp.int32)
        xe = jnp.ceil(x1[:, None] + (px + 1) * bin_w[:, None]).astype(
            jnp.int32)
        ys0 = jnp.clip(ys0, 0, H)
        ye = jnp.clip(ye, 0, H)
        xs0 = jnp.clip(xs0, 0, W)
        xe = jnp.clip(xe, 0, W)
        dy = jnp.arange(wh)[None, None, :]
        dx = jnp.arange(ww)[None, None, :]
        yy = jnp.clip(ys0[:, :, None] + dy, 0, H - 1)
        xx = jnp.clip(xs0[:, :, None] + dx, 0, W - 1)
        myv = (ys0[:, :, None] + dy) < ye[:, :, None]  # (R, ph, wh)
        mxv = (xs0[:, :, None] + dx) < xe[:, :, None]  # (R, pw, ww)
        # feat reshaped (N, out_c, ph, pw, H, W); select c-bin channel
        fr = feat.reshape(feat.shape[0], out_c, ph, pw, H, W)
        g = fr[bidx[:, None, None, None, None, None],
               jnp.arange(out_c)[None, :, None, None, None, None],
               jnp.arange(ph)[None, None, :, None, None, None],
               jnp.arange(pw)[None, None, None, :, None, None],
               yy[:, None, :, None, :, None],
               xx[:, None, None, :, None, :]]
        m = (myv[:, None, :, None, :, None] & mxv[:, None, None, :, None, :])
        cnt = jnp.maximum(m.sum((4, 5)), 1).astype(g.dtype)
        out = (jnp.where(m, g, 0.0).sum((4, 5)) / cnt)
        is_empty = (ye <= ys0)[:, None, :, None] | (xe <= xs0)[:, None, None]
        return jnp.where(is_empty, 0.0, out)

    return apply(f, x, boxes, _op_name="psroi_pool")


# ---------------------------------------------------------------------------
# deformable conv
# ---------------------------------------------------------------------------

def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Parity: vision/ops.py:742 (DCNv1 when mask is None, DCNv2 with mask).

    offset: (N, 2*dg*kh*kw, Hout, Wout), per kernel tap (dy, dx) pairs;
    mask: (N, dg*kh*kw, Hout, Wout). Implementation: bilinear-sample an
    im2col tensor (N, Cin*kh*kw, Hout*Wout) then one grouped matmul — the
    sampling is gathers (VPU), the contraction hits the MXU.
    """
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = (padding, padding)
    if isinstance(dilation, int):
        dilation = (dilation, dilation)
    sh, sw = stride
    padh, padw = padding
    dh, dw = dilation
    dg = deformable_groups

    def f(xv, off, wv, *rest):
        mk = rest[0] if mask is not None else None
        b = rest[-1] if bias is not None else None
        N, Cin, H, W = xv.shape
        O, _, kh, kw = wv.shape
        Ho = (H + 2 * padh - (dh * (kh - 1) + 1)) // sh + 1
        Wo = (W + 2 * padw - (dw * (kw - 1) + 1)) // sw + 1
        K = kh * kw
        off = off.reshape(N, dg, K, 2, Ho, Wo)
        base_y = (jnp.arange(Ho) * sh - padh)[None, :, None]
        base_x = (jnp.arange(Wo) * sw - padw)[None, None, :]
        ky = (jnp.arange(kh) * dh)[:, None].repeat(kw, 1).reshape(K)
        kx = (jnp.arange(kw) * dw)[None, :].repeat(kh, 0).reshape(K)
        # sample positions (N, dg, K, Ho, Wo)
        ys = base_y[None, None] + ky[None, None, :, None, None] \
            + off[:, :, :, 0]
        xs = base_x[None, None] + kx[None, None, :, None, None] \
            + off[:, :, :, 1]
        valid = (ys > -1.0) & (ys < H) & (xs > -1.0) & (xs < W)
        y = jnp.clip(ys, 0.0, H - 1)
        xq = jnp.clip(xs, 0.0, W - 1)
        y0 = jnp.floor(y).astype(jnp.int32)
        x0 = jnp.floor(xq).astype(jnp.int32)
        y1 = jnp.minimum(y0 + 1, H - 1)
        x1 = jnp.minimum(x0 + 1, W - 1)
        ly = y - y0
        lx = xq - x0
        # gather per dg-group of channels: (N, dg, C/dg, K, Ho, Wo)
        xg = xv.reshape(N, dg, Cin // dg, H, W)
        bb = jnp.arange(N)[:, None, None, None, None, None]
        gg = jnp.arange(dg)[None, :, None, None, None, None]
        cc = jnp.arange(Cin // dg)[None, None, :, None, None, None]

        def g(yy, xx):
            return xg[bb, gg, cc, yy[:, :, None], xx[:, :, None]]

        v = (g(y0, x0) * ((1 - ly) * (1 - lx))[:, :, None]
             + g(y0, x1) * ((1 - ly) * lx)[:, :, None]
             + g(y1, x0) * (ly * (1 - lx))[:, :, None]
             + g(y1, x1) * (ly * lx)[:, :, None])
        v = v * valid[:, :, None].astype(v.dtype)
        if mk is not None:
            v = v * mk.reshape(N, dg, 1, K, Ho, Wo).astype(v.dtype)
        # (N, Cin, K, Ho, Wo) → grouped contraction with weight
        v = v.reshape(N, Cin, K, Ho, Wo)
        cg = Cin // groups
        og = O // groups
        vg = v.reshape(N, groups, cg, K, Ho * Wo)
        wg = wv.reshape(groups, og, cg, K)
        out = jnp.einsum("ngckp,gock->ngop", vg, wg,
                         preferred_element_type=vg.dtype)
        out = out.reshape(N, O, Ho, Wo)
        if b is not None:
            out = out + b.reshape(1, -1, 1, 1)
        return out

    args = [x, offset, weight]
    if mask is not None:
        args.append(mask)
    if bias is not None:
        args.append(bias)
    return apply(f, *args, _op_name="deform_conv2d")


from ..nn.layer_base import Layer as _Layer  # noqa: E402
from ..nn import initializer as _I  # noqa: E402


class DeformConv2D(_Layer):
    """Parity: vision/ops.py:951 DeformConv2D layer."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        kh, kw = (tuple(kernel_size) if isinstance(kernel_size, (list, tuple))
                  else (kernel_size, kernel_size))
        self._attrs = (stride, padding, dilation, deformable_groups, groups)
        fan_in = (in_channels // groups) * kh * kw
        bound = 1.0 / float(np.sqrt(fan_in))
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, kh, kw], attr=weight_attr,
            default_initializer=_I.Uniform(-bound, bound))
        self.bias = self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True,
            default_initializer=_I.Uniform(-bound, bound))

    def forward(self, x, offset, mask=None):
        s, p, d, dg, g = self._attrs
        return deform_conv2d(x, offset, self.weight, self.bias, stride=s,
                             padding=p, dilation=d, deformable_groups=dg,
                             groups=g, mask=mask)


# ---------------------------------------------------------------------------
# box decoding / anchors
# ---------------------------------------------------------------------------

def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """Parity: vision/ops.py:262 — decode YOLOv3 head. Pure elementwise
    (sigmoid/exp/scale), one fused XLA kernel."""
    an = np.asarray(anchors, np.float32).reshape(-1, 2)
    na = an.shape[0]

    def f(xv, imgs):
        N, C, H, W = xv.shape
        if iou_aware:
            ioup = jax.nn.sigmoid(xv[:, :na].reshape(N, na, 1, H, W))
            xv = xv[:, na:]
        p = xv.reshape(N, na, 5 + class_num, H, W)
        gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
        gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
        a = scale_x_y
        b = -0.5 * (scale_x_y - 1.0)
        cx = (jax.nn.sigmoid(p[:, :, 0]) * a + b + gx) / W
        cy = (jax.nn.sigmoid(p[:, :, 1]) * a + b + gy) / H
        aw = jnp.asarray(an[:, 0])[None, :, None, None]
        ah = jnp.asarray(an[:, 1])[None, :, None, None]
        in_w = downsample_ratio * W
        in_h = downsample_ratio * H
        bw = jnp.exp(p[:, :, 2]) * aw / in_w
        bh = jnp.exp(p[:, :, 3]) * ah / in_h
        conf = jax.nn.sigmoid(p[:, :, 4])
        if iou_aware:
            conf = conf ** (1 - iou_aware_factor) \
                * ioup[:, :, 0] ** iou_aware_factor
        on = (conf >= conf_thresh).astype(xv.dtype)
        conf = conf * on
        imh = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        imw = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (cx - bw * 0.5) * imw
        y1 = (cy - bh * 0.5) * imh
        x2 = (cx + bw * 0.5) * imw
        y2 = (cy + bh * 0.5) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0)
            y1 = jnp.clip(y1, 0)
            x2 = jnp.minimum(x2, imw - 1)
            y2 = jnp.minimum(y2, imh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1).reshape(N, -1, 4)
        boxes = boxes * (conf.reshape(N, -1, 1) > 0).astype(boxes.dtype)
        scores = (jax.nn.sigmoid(p[:, :, 5:]) * conf[:, :, None])
        scores = scores.transpose(0, 1, 3, 4, 2).reshape(
            N, -1, class_num)
        return boxes, scores

    return apply(f, x, img_size, _op_name="yolo_box")


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """Parity: vision/ops.py:51 — YOLOv3 per-scale training loss.

    Responsible anchors are chosen by best full-anchor-set IoU at the gt
    cell; objectness targets are down-weighted where predictions overlap
    any gt above ignore_thresh. Vectorized over (N, B) gt slots.
    """
    an = np.asarray(anchors, np.float32).reshape(-1, 2)
    amask = np.asarray(anchor_mask, np.int64)
    return _yolo_loss_impl(x, gt_box, gt_label, an, amask, class_num,
                           ignore_thresh, downsample_ratio, gt_score,
                           use_label_smooth, scale_x_y)


def _yolo_loss_impl(x, gt_box, gt_label, an, amask, class_num,
                    ignore_thresh, downsample_ratio, gt_score,
                    use_label_smooth, scale_x_y):
    na = len(amask)

    def bce(logit, target):
        return jnp.maximum(logit, 0) - logit * target \
            + jnp.log1p(jnp.exp(-jnp.abs(logit)))

    def f(xv, gtb, gtl, *rest):
        gts = rest[0] if rest else None
        N, C, H, W = xv.shape
        p = xv.reshape(N, na, 5 + class_num, H, W)
        in_w = downsample_ratio * W
        in_h = downsample_ratio * H
        B = gtb.shape[1]
        gx, gy = gtb[:, :, 0], gtb[:, :, 1]
        gw, gh = gtb[:, :, 2], gtb[:, :, 3]
        valid = (gw > 1e-8) & (gh > 1e-8)
        gi = jnp.clip((gx * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gy * H).astype(jnp.int32), 0, H - 1)
        aw_all = jnp.asarray(an[:, 0]) / in_w
        ah_all = jnp.asarray(an[:, 1]) / in_h
        inter = jnp.minimum(gw[:, :, None], aw_all) \
            * jnp.minimum(gh[:, :, None], ah_all)
        union = gw[:, :, None] * gh[:, :, None] + aw_all * ah_all - inter
        best = jnp.argmax(inter / jnp.maximum(union, 1e-10), -1)
        slot = jnp.full(best.shape, -1, jnp.int32)
        for li, a_id in enumerate(amask):
            slot = jnp.where(best == int(a_id), li, slot)
        resp = valid & (slot >= 0)
        slot_c = jnp.clip(slot, 0, na - 1)
        a = scale_x_y
        bsh = -0.5 * (scale_x_y - 1.0)
        # gather raw logits at responsible cells: (N, B)
        nb = jnp.arange(N)[:, None]
        lx = p[nb, slot_c, 0, gj, gi]
        ly = p[nb, slot_c, 1, gj, gi]
        pw = p[nb, slot_c, 2, gj, gi]
        ph = p[nb, slot_c, 3, gj, gi]
        tx = gx * W - gi
        ty = gy * H - gj
        aw_m = jnp.asarray(an[amask][:, 0])
        ah_m = jnp.asarray(an[amask][:, 1])
        tw = jnp.log(jnp.maximum(gw * in_w, 1e-9)
                     / jnp.maximum(aw_m[slot_c], 1e-9))
        th = jnp.log(jnp.maximum(gh * in_h, 1e-9)
                     / jnp.maximum(ah_m[slot_c], 1e-9))
        # reference CalcBoxLoss (yolo_loss_kernel.cc:109): sigmoid-CE on
        # x/y logits, L1 on w/h, scaled by (2 - w*h) * score
        score = resp.astype(xv.dtype)
        if gts is not None:
            score = score * gts
        w = score * (2.0 - gw * gh)
        loss_xy = ((bce(lx, tx) + bce(ly, ty)) * w).sum(-1)
        loss_wh = ((jnp.abs(pw - tw) + jnp.abs(ph - th)) * w).sum(-1)
        # objectness: target 1 at responsible cells; ignore where best
        # pred-gt IoU > ignore_thresh
        pobj = p[:, :, 4]
        gxs = jnp.arange(W, dtype=xv.dtype)[None, None, None, :]
        gys = jnp.arange(H, dtype=xv.dtype)[None, None, :, None]
        bx = (jax.nn.sigmoid(p[:, :, 0]) * a + bsh + gxs) / W
        by = (jax.nn.sigmoid(p[:, :, 1]) * a + bsh + gys) / H
        bw = jnp.exp(jnp.clip(p[:, :, 2], -10, 10)) \
            * (jnp.asarray(an[amask][:, 0]) / in_w)[None, :, None, None]
        bhh = jnp.exp(jnp.clip(p[:, :, 3], -10, 10)) \
            * (jnp.asarray(an[amask][:, 1]) / in_h)[None, :, None, None]
        # IoU of each pred box with each gt (N, A, H, W, B)
        px1 = bx - bw / 2
        px2 = bx + bw / 2
        py1 = by - bhh / 2
        py2 = by + bhh / 2
        qx1 = (gx - gw / 2)[:, None, None, None]
        qx2 = (gx + gw / 2)[:, None, None, None]
        qy1 = (gy - gh / 2)[:, None, None, None]
        qy2 = (gy + gh / 2)[:, None, None, None]
        iw = jnp.clip(jnp.minimum(px2[..., None], qx2)
                      - jnp.maximum(px1[..., None], qx1), 0)
        ih = jnp.clip(jnp.minimum(py2[..., None], qy2)
                      - jnp.maximum(py1[..., None], qy1), 0)
        it = iw * ih
        un = (bw * bhh)[..., None] + (gw * gh)[:, None, None, None] - it
        iou = jnp.where(valid[:, None, None, None], it
                        / jnp.maximum(un, 1e-10), 0.0)
        ignore = jnp.max(iou, -1) > ignore_thresh
        tobj = jnp.zeros_like(pobj)
        tobj = tobj.at[nb, slot_c, gj, gi].max(resp.astype(xv.dtype))
        objw = jnp.where((tobj == 0) & ignore, 0.0, 1.0)
        if gts is not None:
            sobj = jnp.zeros_like(pobj).at[nb, slot_c, gj, gi].max(
                jnp.where(resp, gts, 0.0))
            tgt_obj = sobj
        else:
            tgt_obj = tobj
        loss_obj = (bce(pobj, tgt_obj) * objw).sum((1, 2, 3))
        # classification at responsible cells. Reference CalcLabelLoss
        # (yolo_loss_kernel.cc:117): smoothing pos=1-sw, neg=sw with
        # sw=min(1/C, 1/40) (:215-217); weighted by score only (no box
        # scale).
        pc = p[nb, slot_c, :, gj, gi][:, :, 5:]
        sw = min(1.0 / class_num, 1.0 / 40) if use_label_smooth else 0.0
        onehot = jax.nn.one_hot(gtl.astype(jnp.int32), class_num,
                                dtype=xv.dtype)
        tcls = onehot * (1.0 - sw) + (1 - onehot) * sw
        loss_cls = (bce(pc, tcls).sum(-1) * score).sum(-1)
        return loss_xy + loss_wh + loss_obj + loss_cls

    args = [x, gt_box, gt_label]
    if gt_score is not None:
        args.append(gt_score)
    return apply(f, *args, _op_name="yolo_loss")


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """Parity: vision/ops.py:425 (SSD anchors). Deterministic host-side
    generation (no gradients flow through anchors)."""
    feat = _np(input)
    img = _np(image)
    fh, fw = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    step_h = steps[1] if steps[1] > 0 else ih / fh
    step_w = steps[0] if steps[0] > 0 else iw / fw
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    boxes = []
    for ms_i, ms in enumerate(min_sizes):
        per = []
        ms = float(ms)
        if min_max_aspect_ratios_order:
            per.append((ms, ms))
            if max_sizes:
                bs = float(np.sqrt(ms * float(max_sizes[ms_i])))
                per.append((bs, bs))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                per.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        else:
            for ar in ars:
                per.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            if max_sizes:
                bs = float(np.sqrt(ms * float(max_sizes[ms_i])))
                per.append((bs, bs))
        boxes.append(per)
    flat = [wh for per in boxes for wh in per]
    npr = len(flat)
    cy = (np.arange(fh) + offset) * step_h
    cx = (np.arange(fw) + offset) * step_w
    out = np.zeros((fh, fw, npr, 4), np.float32)
    for k, (bw, bh) in enumerate(flat):
        out[:, :, k, 0] = (cx[None, :] - bw / 2.) / iw
        out[:, :, k, 1] = (cy[:, None] - bh / 2.) / ih
        out[:, :, k, 2] = (cx[None, :] + bw / 2.) / iw
        out[:, :, k, 3] = (cy[:, None] + bh / 2.) / ih
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    return (Tensor(jnp.asarray(out), stop_gradient=True),
            Tensor(jnp.asarray(var), stop_gradient=True))


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              name=None):
    """Parity: vision/ops.py:572 — encode/decode boxes against priors."""
    norm = 0.0 if box_normalized else 1.0

    def f(pb, tb, *rest):
        pv = rest[0] if rest else None
        pw = pb[:, 2] - pb[:, 0] + norm
        phh = pb[:, 3] - pb[:, 1] + norm
        pcx = pb[:, 0] + pw * 0.5
        pcy = pb[:, 1] + phh * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tcx = tb[:, 0] + tw * 0.5
            tcy = tb[:, 1] + th * 0.5
            # output (T, P, 4): each target vs each prior
            ox = (tcx[:, None] - pcx[None, :]) / pw[None, :]
            oy = (tcy[:, None] - pcy[None, :]) / phh[None, :]
            ow = jnp.log(jnp.maximum(tw[:, None] / pw[None, :], 1e-10))
            oh = jnp.log(jnp.maximum(th[:, None] / phh[None, :], 1e-10))
            out = jnp.stack([ox, oy, ow, oh], -1)
            if pv is not None:
                out = out / pv[None, :, :]
            return out
        # decode_center_size: target (T, P, 4) or broadcast by axis
        t = tb
        if t.ndim == 2:
            t = t[:, None, :]
        if axis == 0:
            pcxb, pcyb = pcx[None, :], pcy[None, :]
            pwb, phb = pw[None, :], phh[None, :]
            pvb = pv[None, :, :] if pv is not None else None
        else:
            pcxb, pcyb = pcx[:, None], pcy[:, None]
            pwb, phb = pw[:, None], phh[:, None]
            pvb = pv[:, None, :] if pv is not None else None
        d = t * pvb if pvb is not None else t
        dcx = d[..., 0] * pwb + pcxb
        dcy = d[..., 1] * phb + pcyb
        dw = jnp.exp(jnp.clip(d[..., 2], -20, 20)) * pwb
        dhh = jnp.exp(jnp.clip(d[..., 3], -20, 20)) * phb
        return jnp.stack([dcx - dw * 0.5, dcy - dhh * 0.5,
                          dcx + dw * 0.5 - norm, dcy + dhh * 0.5 - norm],
                         -1)

    args = [prior_box, target_box]
    if prior_box_var is not None and not isinstance(prior_box_var,
                                                    (list, tuple)):
        args.append(prior_box_var)
        return apply(f, *args, _op_name="box_coder")
    if isinstance(prior_box_var, (list, tuple)):
        pvv = jnp.asarray(np.asarray(prior_box_var, np.float32))
        pvv = jnp.broadcast_to(pvv, (_val(prior_box).shape[0], 4))
        args.append(Tensor(pvv, stop_gradient=True))
        return apply(f, *args, _op_name="box_coder")
    return apply(f, *args, _op_name="box_coder")


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """Parity: vision/ops.py:1151 — assign RoIs to FPN levels by scale.
    Host-side (output shapes are data-dependent by design)."""
    rois = _np(fpn_rois).astype(np.float32)
    off = 1.0 if pixel_offset else 0.0
    w = np.maximum(rois[:, 2] - rois[:, 0] + off, 0)
    h = np.maximum(rois[:, 3] - rois[:, 1] + off, 0)
    scale = np.sqrt(w * h)
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    n_lvl = max_level - min_level + 1
    multi_rois, restore, nums = [], np.zeros(len(rois), np.int64), []
    order = []
    splits = None
    if rois_num is not None:
        rn = _np(rois_num).astype(np.int64)
        splits = np.split(np.arange(len(rois)), np.cumsum(rn)[:-1])
    for li in range(n_lvl):
        sel = np.nonzero(lvl == min_level + li)[0]
        order.append(sel)
        multi_rois.append(Tensor(jnp.asarray(rois[sel]),
                                 stop_gradient=True))
        if splits is not None:
            nums.append(Tensor(jnp.asarray(np.asarray(
                [int(np.sum(lvl[s] == min_level + li)) for s in splits],
                np.int32)), stop_gradient=True))
    concat_order = np.concatenate(order) if order else np.empty(0, np.int64)
    restore[concat_order] = np.arange(len(rois))
    restore_t = Tensor(jnp.asarray(restore.reshape(-1, 1)),
                       stop_gradient=True)
    if rois_num is not None:
        return multi_rois, restore_t, nums
    return multi_rois, restore_t


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False, name=None):
    """Parity: vision/ops.py:2023 (RPN proposal generation). Composition of
    decode + clip + filter + greedy NMS, per batch image, host-driven."""
    sc = _np(scores).astype(np.float32)        # (N, A, H, W)
    bd = _np(bbox_deltas).astype(np.float32)   # (N, 4A, H, W)
    ims = _np(img_size).astype(np.float32)     # (N, 2) (h, w)
    anc = _np(anchors).astype(np.float32).reshape(-1, 4)
    var = _np(variances).astype(np.float32).reshape(-1, 4)
    N, A, H, W = sc.shape
    off = 1.0 if pixel_offset else 0.0
    # reference clamp (generate_proposals_kernel.cc:83)
    min_size = max(min_size, 1.0)
    rois_out, num_out, scores_out = [], [], []
    for i in range(N):
        s = sc[i].transpose(1, 2, 0).reshape(-1)          # H,W,A
        d = bd[i].reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        a = anc  # anchors come already as (H*W*A, 4)
        v = var if var.shape[0] == a.shape[0] else np.tile(
            var, (a.shape[0] // var.shape[0], 1))
        order = np.argsort(-s, kind="stable")[:pre_nms_top_n]
        s_k = s[order]
        d_k = d[order]
        a_k = a[order]
        v_k = v[order]
        aw = a_k[:, 2] - a_k[:, 0] + off
        ah = a_k[:, 3] - a_k[:, 1] + off
        acx = a_k[:, 0] + aw * 0.5
        acy = a_k[:, 1] + ah * 0.5
        cx = v_k[:, 0] * d_k[:, 0] * aw + acx
        cy = v_k[:, 1] * d_k[:, 1] * ah + acy
        wd = np.exp(np.clip(v_k[:, 2] * d_k[:, 2], -20, 20)) * aw
        hd = np.exp(np.clip(v_k[:, 3] * d_k[:, 3], -20, 20)) * ah
        props = np.stack([cx - wd * 0.5, cy - hd * 0.5,
                          cx + wd * 0.5 - off, cy + hd * 0.5 - off], -1)
        ih, iw = ims[i, 0], ims[i, 1]
        props[:, 0] = np.clip(props[:, 0], 0, iw - off)
        props[:, 1] = np.clip(props[:, 1], 0, ih - off)
        props[:, 2] = np.clip(props[:, 2], 0, iw - off)
        props[:, 3] = np.clip(props[:, 3], 0, ih - off)
        pw = props[:, 2] - props[:, 0] + off
        phh = props[:, 3] - props[:, 1] + off
        keep = np.nonzero((pw >= min_size) & (phh >= min_size))[0]
        props, s_k = props[keep], s_k[keep]
        if len(props):
            km = np.asarray(_nms_keep_mask(jnp.asarray(props),
                                           float(nms_thresh)))
            ki = np.nonzero(km)[0][:post_nms_top_n]
            props, s_k = props[ki], s_k[ki]
        rois_out.append(props)
        scores_out.append(s_k.reshape(-1, 1))
        num_out.append(len(props))
    rois = Tensor(jnp.asarray(np.concatenate(rois_out, 0)),
                  stop_gradient=True)
    rscores = Tensor(jnp.asarray(np.concatenate(scores_out, 0)),
                     stop_gradient=True)
    if return_rois_num:
        return rois, rscores, Tensor(
            jnp.asarray(np.asarray(num_out, np.int32)), stop_gradient=True)
    return rois, rscores


# ---------------------------------------------------------------------------
# layers
# ---------------------------------------------------------------------------

class _RoILayerBase(_Layer):
    _fn = None

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return type(self)._fn(x, boxes, boxes_num, self.output_size,
                              self.spatial_scale)


class RoIAlign(_RoILayerBase):
    """Parity: vision/ops.py:1748."""
    _fn = staticmethod(roi_align)


class RoIPool(_RoILayerBase):
    """Parity: vision/ops.py:1581."""
    _fn = staticmethod(roi_pool)


class PSRoIPool(_RoILayerBase):
    """Parity: vision/ops.py:1459."""
    _fn = staticmethod(psroi_pool)


_DEFAULT = object()  # sentinel: "use the default layer class"


def ConvNormActivation(in_channels, out_channels, kernel_size=3, stride=1,
                       padding=None, groups=1, norm_layer=_DEFAULT,
                       activation_layer=_DEFAULT, dilation=1, bias=None):
    """Parity: vision/ops.py:1796 — Conv2D + Norm + Activation block used
    across the model zoo. Returns an nn.Sequential. Passing
    norm_layer=None / activation_layer=None disables that stage (and a
    missing norm implies a biased conv), matching the reference defaults
    of BatchNorm2D / ReLU."""
    from .. import nn
    if norm_layer is _DEFAULT:
        norm_layer = nn.BatchNorm2D
    if activation_layer is _DEFAULT:
        activation_layer = nn.ReLU
    if padding is None:
        padding = (kernel_size - 1) // 2 * dilation
    if bias is None:
        bias = norm_layer is None
    layers = [nn.Conv2D(in_channels, out_channels, kernel_size, stride,
                        padding, dilation=dilation, groups=groups,
                        bias_attr=None if bias else False)]
    if norm_layer is not None:
        layers.append(norm_layer(out_channels))
    if activation_layer is not None:
        layers.append(activation_layer())
    return nn.Sequential(*layers)
