"""MobileNetV3 Small/Large. Parity: python/paddle/vision/models/
mobilenetv3.py (SE-augmented inverted residuals, hardswish stem/head).
"""
from __future__ import annotations

import paddle_tpu.nn as nn

from .mobilenetv2 import _make_divisible

__all__ = ["MobileNetV3Small", "MobileNetV3Large", "mobilenet_v3_small",
           "mobilenet_v3_large"]


class SqueezeExcitation(nn.Layer):
    def __init__(self, c, squeeze_c):
        super().__init__()
        self.avgpool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(c, squeeze_c, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(squeeze_c, c, 1)
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.avgpool(x)
        s = self.relu(self.fc1(s))
        s = self.hsig(self.fc2(s))
        return x * s


class InvertedResidualV3(nn.Layer):
    def __init__(self, inp, exp, out, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and inp == out
        Act = nn.Hardswish if act == "HS" else nn.ReLU
        layers = []
        if exp != inp:
            layers += [nn.Conv2D(inp, exp, 1, bias_attr=False),
                       nn.BatchNorm2D(exp), Act()]
        layers += [nn.Conv2D(exp, exp, k, stride=stride,
                             padding=(k - 1) // 2, groups=exp,
                             bias_attr=False),
                   nn.BatchNorm2D(exp), Act()]
        if use_se:
            layers.append(SqueezeExcitation(exp, _make_divisible(exp // 4)))
        layers += [nn.Conv2D(exp, out, 1, bias_attr=False),
                   nn.BatchNorm2D(out)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        if self.use_res:
            out = x + out
        return out


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_exp, last_channel, scale=1.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_c = _make_divisible(16 * scale)
        layers = [nn.Sequential(
            nn.Conv2D(3, in_c, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(in_c), nn.Hardswish())]
        for k, exp, out, se, act, s in cfg:
            exp_c = _make_divisible(exp * scale)
            out_c = _make_divisible(out * scale)
            layers.append(InvertedResidualV3(in_c, exp_c, out_c, k, s, se,
                                             act))
            in_c = out_c
        exp_c = _make_divisible(last_exp * scale)
        layers.append(nn.Sequential(
            nn.Conv2D(in_c, exp_c, 1, bias_attr=False),
            nn.BatchNorm2D(exp_c), nn.Hardswish()))
        self.features = nn.Sequential(*layers)
        self.last_conv_c = exp_c
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(exp_c, last_channel), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_channel, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


class MobileNetV3Small(_MobileNetV3):
    # k, exp, out, SE, act, stride
    _cfg = [
        (3, 16, 16, True, "RE", 2), (3, 72, 24, False, "RE", 2),
        (3, 88, 24, False, "RE", 1), (5, 96, 40, True, "HS", 2),
        (5, 240, 40, True, "HS", 1), (5, 240, 40, True, "HS", 1),
        (5, 120, 48, True, "HS", 1), (5, 144, 48, True, "HS", 1),
        (5, 288, 96, True, "HS", 2), (5, 576, 96, True, "HS", 1),
        (5, 576, 96, True, "HS", 1)]

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(self._cfg, 576, 1024, scale, num_classes,
                         with_pool)


class MobileNetV3Large(_MobileNetV3):
    _cfg = [
        (3, 16, 16, False, "RE", 1), (3, 64, 24, False, "RE", 2),
        (3, 72, 24, False, "RE", 1), (5, 72, 40, True, "RE", 2),
        (5, 120, 40, True, "RE", 1), (5, 120, 40, True, "RE", 1),
        (3, 240, 80, False, "HS", 2), (3, 200, 80, False, "HS", 1),
        (3, 184, 80, False, "HS", 1), (3, 184, 80, False, "HS", 1),
        (3, 480, 112, True, "HS", 1), (3, 672, 112, True, "HS", 1),
        (5, 672, 160, True, "HS", 2), (5, 960, 160, True, "HS", 1),
        (5, 960, 160, True, "HS", 1)]

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(self._cfg, 960, 1280, scale, num_classes,
                         with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    assert not pretrained, "pretrained weights unavailable (no egress)"
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    assert not pretrained, "pretrained weights unavailable (no egress)"
    return MobileNetV3Large(scale=scale, **kwargs)
