"""SqueezeNet. Parity: python/paddle/vision/models/squeezenet.py (Fire
modules; versions 1.0 and 1.1 differ in stem stride/pool placement).
"""
from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


class Fire(nn.Layer):
    def __init__(self, in_c, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Conv2D(in_c, squeeze, 1)
        self.expand1 = nn.Conv2D(squeeze, e1, 1)
        self.expand3 = nn.Conv2D(squeeze, e3, 3, padding=1)
        self.relu = nn.ReLU()

    def forward(self, x):
        x = self.relu(self.squeeze(x))
        return paddle.concat(
            [self.relu(self.expand1(x)), self.relu(self.expand3(x))], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        assert version in ("1.0", "1.1"), (
            f"supported versions are 1.0 and 1.1, but got {version}")
        self.version = version
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2),
                Fire(96, 16, 64, 64), Fire(128, 16, 64, 64),
                Fire(128, 32, 128, 128), nn.MaxPool2D(3, 2),
                Fire(256, 32, 128, 128), Fire(256, 48, 192, 192),
                Fire(384, 48, 192, 192), Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, 2), Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2, padding=1), nn.ReLU(),
                nn.MaxPool2D(3, 2),
                Fire(64, 16, 64, 64), Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, 2),
                Fire(128, 32, 128, 128), Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, 2),
                Fire(256, 48, 192, 192), Fire(384, 48, 192, 192),
                Fire(384, 64, 256, 256), Fire(512, 64, 256, 256))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.5),
                nn.Conv2D(512, num_classes, 1),
                nn.ReLU())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
        return x


def _squeezenet(version, pretrained, **kwargs):
    assert not pretrained, "pretrained weights unavailable (no egress)"
    return SqueezeNet(version, **kwargs)


def squeezenet1_0(pretrained=False, **kwargs):
    return _squeezenet("1.0", pretrained, **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return _squeezenet("1.1", pretrained, **kwargs)
