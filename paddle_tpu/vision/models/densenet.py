"""DenseNet. Parity: python/paddle/vision/models/densenet.py
(dense blocks with bottleneck layers; 121/161/169/201/264 configs).
"""
from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

_cfgs = {
    121: (64, 32, (6, 12, 24, 16)),
    161: (96, 48, (6, 12, 36, 24)),
    169: (64, 32, (6, 12, 32, 32)),
    201: (64, 32, (6, 12, 48, 32)),
    264: (64, 32, (6, 12, 64, 48)),
}


class DenseLayer(nn.Layer):
    def __init__(self, in_c, growth_rate, bn_size, dropout):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(in_c)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(in_c, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.conv1(self.relu(self.norm1(x)))
        out = self.conv2(self.relu(self.norm2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return paddle.concat([x, out], axis=1)


class Transition(nn.Layer):
    def __init__(self, in_c, out_c):
        super().__init__()
        self.norm = nn.BatchNorm2D(in_c)
        self.relu = nn.ReLU()
        self.conv = nn.Conv2D(in_c, out_c, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, 2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.norm(x))))


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        assert layers in _cfgs, (
            f"supported layers are {sorted(_cfgs)} but got {layers}")
        num_init, growth, block_cfg = _cfgs[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, num_init, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(num_init), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1))
        blocks = []
        c = num_init
        for bi, n in enumerate(block_cfg):
            for _ in range(n):
                blocks.append(DenseLayer(c, growth, bn_size, dropout))
                c += growth
            if bi != len(block_cfg) - 1:
                blocks.append(Transition(c, c // 2))
                c = c // 2
        self.blocks = nn.Sequential(*blocks)
        self.norm_final = nn.BatchNorm2D(c)
        self.relu = nn.ReLU()
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(c, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.blocks(x)
        x = self.relu(self.norm_final(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


def _densenet(layers, pretrained, **kwargs):
    assert not pretrained, "pretrained weights unavailable (no egress)"
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)
