"""MobileNetV1. Parity: python/paddle/vision/models/mobilenetv1.py
(13 depthwise-separable blocks, width multiplier `scale`).
"""
from __future__ import annotations

import paddle_tpu.nn as nn

__all__ = ["MobileNetV1", "mobilenet_v1"]


class DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, out1, out2, num_groups, stride, scale):
        super().__init__()
        self.dw = nn.Sequential(
            nn.Conv2D(in_c, int(out1 * scale), 3, stride=stride, padding=1,
                      groups=int(num_groups * scale), bias_attr=False),
            nn.BatchNorm2D(int(out1 * scale)),
            nn.ReLU())
        self.pw = nn.Sequential(
            nn.Conv2D(int(out1 * scale), int(out2 * scale), 1,
                      bias_attr=False),
            nn.BatchNorm2D(int(out2 * scale)),
            nn.ReLU())

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, int(32 * scale), 3, stride=2, padding=1,
                      bias_attr=False),
            nn.BatchNorm2D(int(32 * scale)),
            nn.ReLU())
        cfg = [  # in, out1, out2, groups, stride
            (32, 32, 64, 32, 1), (64, 64, 128, 64, 2),
            (128, 128, 128, 128, 1), (128, 128, 256, 128, 2),
            (256, 256, 256, 256, 1), (256, 256, 512, 256, 2),
            (512, 512, 512, 512, 1), (512, 512, 512, 512, 1),
            (512, 512, 512, 512, 1), (512, 512, 512, 512, 1),
            (512, 512, 512, 512, 1), (512, 512, 1024, 512, 2),
            (1024, 1024, 1024, 1024, 1)]
        blocks = [DepthwiseSeparable(int(i * scale), o1, o2, g, s, scale)
                  for i, o1, o2, g, s in cfg]
        self.blocks = nn.Sequential(*blocks)
        if with_pool:
            self.pool2d_avg = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(int(1024 * scale), num_classes)

    def forward(self, x):
        x = self.conv1(x)
        x = self.blocks(x)
        if self.with_pool:
            x = self.pool2d_avg(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    assert not pretrained, "pretrained weights unavailable (no egress)"
    return MobileNetV1(scale=scale, **kwargs)
