from .resnet import (ResNet, resnet18, resnet34, resnet50, resnet101,
                     resnet152, wide_resnet50_2, resnext50_32x4d)

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
           "resnet152", "wide_resnet50_2", "resnext50_32x4d"]
