"""Model zoo. Parity: python/paddle/vision/models/__init__.py — same
13 families / 51 exported symbols."""
from .resnet import (ResNet, resnet18, resnet34, resnet50, resnet101,
                     resnet152, wide_resnet50_2, wide_resnet101_2,
                     resnext50_32x4d, resnext50_64x4d, resnext101_32x4d,
                     resnext101_64x4d, resnext152_32x4d, resnext152_64x4d)
from .lenet import LeNet
from .alexnet import AlexNet, alexnet
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19
from .squeezenet import SqueezeNet, squeezenet1_0, squeezenet1_1
from .mobilenetv1 import MobileNetV1, mobilenet_v1
from .mobilenetv2 import MobileNetV2, mobilenet_v2
from .mobilenetv3 import (MobileNetV3Small, MobileNetV3Large,
                          mobilenet_v3_small, mobilenet_v3_large)
from .shufflenetv2 import (ShuffleNetV2, shufflenet_v2_x0_25,
                           shufflenet_v2_x0_33, shufflenet_v2_x0_5,
                           shufflenet_v2_x1_0, shufflenet_v2_x1_5,
                           shufflenet_v2_x2_0, shufflenet_v2_swish)
from .densenet import (DenseNet, densenet121, densenet161, densenet169,
                       densenet201, densenet264)
from .googlenet import GoogLeNet, googlenet
from .inceptionv3 import InceptionV3, inception_v3
from .ppyoloe import CSPResNet, PPYOLOE, ppyoloe_s, ppyoloe_m, ppyoloe_l

__all__ = [
    "ResNet", "resnet18", "resnet34", "resnet50", "resnet101", "resnet152",
    "resnext50_32x4d", "resnext50_64x4d", "resnext101_32x4d",
    "resnext101_64x4d", "resnext152_32x4d", "resnext152_64x4d",
    "wide_resnet50_2", "wide_resnet101_2",
    "VGG", "vgg11", "vgg13", "vgg16", "vgg19",
    "MobileNetV1", "mobilenet_v1", "MobileNetV2", "mobilenet_v2",
    "MobileNetV3Small", "MobileNetV3Large", "mobilenet_v3_small",
    "mobilenet_v3_large",
    "LeNet",
    "DenseNet", "densenet121", "densenet161", "densenet169", "densenet201",
    "densenet264",
    "AlexNet", "alexnet",
    "InceptionV3", "inception_v3",
    "SqueezeNet", "squeezenet1_0", "squeezenet1_1",
    "GoogLeNet", "googlenet",
    "ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
    "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
    "shufflenet_v2_x2_0", "shufflenet_v2_swish",
    "CSPResNet", "PPYOLOE", "ppyoloe_s", "ppyoloe_m", "ppyoloe_l"]
