"""InceptionV3. Parity: python/paddle/vision/models/inceptionv3.py
(stem + InceptionA/B/C/D/E stacks, 299x299 input).
"""
from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn

__all__ = ["InceptionV3", "inception_v3"]


def _bn_conv(in_c, out_c, k, stride=1, padding=0):
    return nn.Sequential(
        nn.Conv2D(in_c, out_c, k, stride=stride, padding=padding,
                  bias_attr=False),
        nn.BatchNorm2D(out_c), nn.ReLU())


class InceptionA(nn.Layer):
    def __init__(self, in_c, pool_features):
        super().__init__()
        self.b1 = _bn_conv(in_c, 64, 1)
        self.b5 = nn.Sequential(_bn_conv(in_c, 48, 1),
                                _bn_conv(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_bn_conv(in_c, 64, 1),
                                _bn_conv(64, 96, 3, padding=1),
                                _bn_conv(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _bn_conv(in_c, pool_features, 1))

    def forward(self, x):
        return paddle.concat(
            [self.b1(x), self.b5(x), self.b3(x), self.bp(x)], axis=1)


class InceptionB(nn.Layer):
    """Grid reduction 35->17."""

    def __init__(self, in_c):
        super().__init__()
        self.b3 = _bn_conv(in_c, 384, 3, stride=2)
        self.b3d = nn.Sequential(_bn_conv(in_c, 64, 1),
                                 _bn_conv(64, 96, 3, padding=1),
                                 _bn_conv(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return paddle.concat([self.b3(x), self.b3d(x), self.pool(x)],
                             axis=1)


class InceptionC(nn.Layer):
    def __init__(self, in_c, c7):
        super().__init__()
        self.b1 = _bn_conv(in_c, 192, 1)
        self.b7 = nn.Sequential(
            _bn_conv(in_c, c7, 1),
            _bn_conv(c7, c7, (1, 7), padding=(0, 3)),
            _bn_conv(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = nn.Sequential(
            _bn_conv(in_c, c7, 1),
            _bn_conv(c7, c7, (7, 1), padding=(3, 0)),
            _bn_conv(c7, c7, (1, 7), padding=(0, 3)),
            _bn_conv(c7, c7, (7, 1), padding=(3, 0)),
            _bn_conv(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _bn_conv(in_c, 192, 1))

    def forward(self, x):
        return paddle.concat(
            [self.b1(x), self.b7(x), self.b7d(x), self.bp(x)], axis=1)


class InceptionD(nn.Layer):
    """Grid reduction 17->8."""

    def __init__(self, in_c):
        super().__init__()
        self.b3 = nn.Sequential(_bn_conv(in_c, 192, 1),
                                _bn_conv(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            _bn_conv(in_c, 192, 1),
            _bn_conv(192, 192, (1, 7), padding=(0, 3)),
            _bn_conv(192, 192, (7, 1), padding=(3, 0)),
            _bn_conv(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return paddle.concat([self.b3(x), self.b7(x), self.pool(x)],
                             axis=1)


class InceptionE(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b1 = _bn_conv(in_c, 320, 1)
        self.b3_stem = _bn_conv(in_c, 384, 1)
        self.b3_a = _bn_conv(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _bn_conv(384, 384, (3, 1), padding=(1, 0))
        self.b3d_stem = nn.Sequential(_bn_conv(in_c, 448, 1),
                                      _bn_conv(448, 384, 3, padding=1))
        self.b3d_a = _bn_conv(384, 384, (1, 3), padding=(0, 1))
        self.b3d_b = _bn_conv(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _bn_conv(in_c, 192, 1))

    def forward(self, x):
        s = self.b3_stem(x)
        b3 = paddle.concat([self.b3_a(s), self.b3_b(s)], axis=1)
        d = self.b3d_stem(x)
        b3d = paddle.concat([self.b3d_a(d), self.b3d_b(d)], axis=1)
        return paddle.concat([self.b1(x), b3, b3d, self.bp(x)], axis=1)


class InceptionV3(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _bn_conv(3, 32, 3, stride=2),
            _bn_conv(32, 32, 3),
            _bn_conv(32, 64, 3, padding=1),
            nn.MaxPool2D(3, stride=2),
            _bn_conv(64, 80, 1),
            _bn_conv(80, 192, 3),
            nn.MaxPool2D(3, stride=2))
        self.blocks = nn.Sequential(
            InceptionA(192, 32), InceptionA(256, 64), InceptionA(288, 64),
            InceptionB(288),
            InceptionC(768, 128), InceptionC(768, 160),
            InceptionC(768, 160), InceptionC(768, 192),
            InceptionD(768),
            InceptionE(1280), InceptionE(2048))
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.blocks(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.dropout(x.flatten(1))
            x = self.fc(x)
        return x


def inception_v3(pretrained=False, **kwargs):
    assert not pretrained, "pretrained weights unavailable (no egress)"
    return InceptionV3(**kwargs)
