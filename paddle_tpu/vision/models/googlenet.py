"""GoogLeNet (Inception v1). Parity: python/paddle/vision/models/
googlenet.py — returns (out, aux1, aux2) like the reference.
"""
from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn

__all__ = ["GoogLeNet", "googlenet"]


class Inception(nn.Layer):
    def __init__(self, in_c, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = nn.Sequential(nn.Conv2D(in_c, c1, 1), nn.ReLU())
        self.b3 = nn.Sequential(
            nn.Conv2D(in_c, c3r, 1), nn.ReLU(),
            nn.Conv2D(c3r, c3, 3, padding=1), nn.ReLU())
        self.b5 = nn.Sequential(
            nn.Conv2D(in_c, c5r, 1), nn.ReLU(),
            nn.Conv2D(c5r, c5, 5, padding=2), nn.ReLU())
        self.bp = nn.Sequential(
            nn.MaxPool2D(3, stride=1, padding=1),
            nn.Conv2D(in_c, proj, 1), nn.ReLU())

    def forward(self, x):
        return paddle.concat(
            [self.b1(x), self.b3(x), self.b5(x), self.bp(x)], axis=1)


class _AuxHead(nn.Layer):
    def __init__(self, in_c, num_classes):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(4)
        self.conv = nn.Conv2D(in_c, 128, 1)
        self.relu = nn.ReLU()
        self.fc1 = nn.Linear(128 * 4 * 4, 1024)
        self.drop = nn.Dropout(0.7)
        self.fc2 = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.relu(self.conv(self.pool(x)))
        x = self.relu(self.fc1(x.flatten(1)))
        return self.fc2(self.drop(x))


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, 64, 7, stride=2, padding=3), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1),
            nn.Conv2D(64, 64, 1), nn.ReLU(),
            nn.Conv2D(64, 192, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1))
        self.ince3a = Inception(192, 64, 96, 128, 16, 32, 32)
        self.ince3b = Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, stride=2, padding=1)
        self.ince4a = Inception(480, 192, 96, 208, 16, 48, 64)
        self.ince4b = Inception(512, 160, 112, 224, 24, 64, 64)
        self.ince4c = Inception(512, 128, 128, 256, 24, 64, 64)
        self.ince4d = Inception(512, 112, 144, 288, 32, 64, 64)
        self.ince4e = Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, stride=2, padding=1)
        self.ince5a = Inception(832, 256, 160, 320, 32, 128, 128)
        self.ince5b = Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.drop = nn.Dropout(0.4)
            self.fc = nn.Linear(1024, num_classes)
            self.aux1 = _AuxHead(512, num_classes)
            self.aux2 = _AuxHead(528, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.ince3b(self.ince3a(x))
        x = self.pool3(x)
        x = self.ince4a(x)
        aux1 = self.aux1(x) if self.num_classes > 0 else None
        x = self.ince4c(self.ince4b(x))
        x = self.ince4d(x)
        aux2 = self.aux2(x) if self.num_classes > 0 else None
        x = self.ince4e(x)
        x = self.pool4(x)
        x = self.ince5b(self.ince5a(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.drop(x.flatten(1))
            x = self.fc(x)
            return x, aux1, aux2
        return x


def googlenet(pretrained=False, **kwargs):
    assert not pretrained, "pretrained weights unavailable (no egress)"
    return GoogLeNet(**kwargs)
