"""PP-YOLOE-style anchor-free detector — BASELINE.json config 5 (serving).

The reference core repo ships the detection *ops* (vision/ops.py: yolo_box,
nms, matrix_nms, ...; fused inference ops §2.4) while the PP-YOLOE model
itself lives in the PaddleDetection suite. For the serving north star
(PP-YOLOE on the predictor path) this module provides the model: CSPResNet
backbone, CSP-PAN neck, and the ET-head's anchor-free decode (per-level
cls + DFL regression, distribution→ltrb expectation, grid anchor points),
ending in multiclass NMS from paddle_tpu.vision.ops.

Inference-first design: `forward` is pure tensor compute (AOT-exportable
through paddle_tpu.inference / jit.save); `postprocess` applies score
threshold + NMS on host. Backbone/neck/head are trainable Layers (grads
flow; detection-suite losses like TAL/VFL live outside core, as in the
reference split).
"""
from __future__ import annotations

import numpy as np

import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import tensor as T
from paddle_tpu.nn.layer_base import Layer
from ..ops import nms

__all__ = ["CSPResNet", "PPYOLOE", "ppyoloe_s", "ppyoloe_m", "ppyoloe_l"]


class ConvBNAct(Layer):
    def __init__(self, cin, cout, k=3, stride=1, groups=1, act="swish"):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, k, stride=stride,
                              padding=(k - 1) // 2, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(cout)
        self.act = act

    def forward(self, x):
        x = self.bn(self.conv(x))
        return F.swish(x) if self.act == "swish" else F.relu(x)


class RepBasicBlock(Layer):
    """CSPResNet basic block: 3x3 + 1x1 branch sum (RepVGG-style pair,
    kept unfused — XLA folds the parallel convs), optional shortcut."""

    def __init__(self, ch, shortcut=True):
        super().__init__()
        self.conv1 = ConvBNAct(ch, ch, 3)
        self.conv2 = nn.Conv2D(ch, ch, 3, padding=1, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(ch)
        self.conv2_1x1 = nn.Conv2D(ch, ch, 1, bias_attr=False)
        self.bn2_1x1 = nn.BatchNorm2D(ch)
        self.shortcut = shortcut

    def forward(self, x):
        y = self.conv1(x)
        y = F.swish(self.bn2(self.conv2(y)) + self.bn2_1x1(self.conv2_1x1(y)))
        return x + y if self.shortcut else y


class EffectiveSE(Layer):
    """Effective squeeze-excitation (one fc), as in CSPResNet stages."""

    def __init__(self, ch):
        super().__init__()
        self.fc = nn.Conv2D(ch, ch, 1)

    def forward(self, x):
        s = T.mean(x, axis=[2, 3], keepdim=True)
        return x * F.sigmoid(self.fc(s))


class CSPResStage(Layer):
    def __init__(self, cin, cout, n, stride=2, use_attn=True):
        super().__init__()
        if cout % 2:
            raise ValueError(
                f"CSPResStage needs an even channel count, got {cout}; "
                "pick a width_mult that keeps (64,128,256,512,1024)*mult "
                "even")
        mid = cout // 2
        self.conv_down = ConvBNAct(cin, cout, 3, stride=stride) \
            if stride > 1 or cin != cout else None
        self.conv1 = ConvBNAct(cout, mid, 1)
        self.conv2 = ConvBNAct(cout, mid, 1)
        self.blocks = nn.Sequential(
            *[RepBasicBlock(mid) for _ in range(n)])
        self.attn = EffectiveSE(cout) if use_attn else None
        self.conv3 = ConvBNAct(cout, cout, 1)

    def forward(self, x):
        if self.conv_down is not None:
            x = self.conv_down(x)
        y = T.concat([self.conv1(x), self.blocks(self.conv2(x))], axis=1)
        if self.attn is not None:
            y = self.attn(y)
        return self.conv3(y)


class CSPResNet(Layer):
    """Backbone; returns C3, C4, C5 feature maps (strides 8/16/32)."""

    def __init__(self, width_mult=1.0, depth_mult=1.0):
        super().__init__()
        ch = [round(c * width_mult) for c in (64, 128, 256, 512, 1024)]
        n = [max(1, round(d * depth_mult)) for d in (3, 6, 6, 3)]
        c0 = ch[0]
        self.stem = nn.Sequential(
            ConvBNAct(3, c0 // 2, 3, stride=2),
            ConvBNAct(c0 // 2, c0 // 2, 3),
            ConvBNAct(c0 // 2, c0, 3))
        self.stage1 = CSPResStage(ch[0], ch[1], n[0])
        self.stage2 = CSPResStage(ch[1], ch[2], n[1])
        self.stage3 = CSPResStage(ch[2], ch[3], n[2])
        self.stage4 = CSPResStage(ch[3], ch[4], n[3])
        self.out_channels = ch[2:]

    def forward(self, x):
        x = self.stage1(self.stem(x))
        c3 = self.stage2(x)
        c4 = self.stage3(c3)
        c5 = self.stage4(c4)
        return c3, c4, c5


class CSPPAN(Layer):
    """PAN neck: top-down then bottom-up fusion with CSP stages."""

    def __init__(self, in_channels, depth=1):
        super().__init__()
        c3, c4, c5 = in_channels
        self.reduce5 = ConvBNAct(c5, c4, 1)
        self.td4 = CSPResStage(c4 * 2, c4, depth, stride=1, use_attn=False)
        self.reduce4 = ConvBNAct(c4, c3, 1)
        self.td3 = CSPResStage(c3 * 2, c3, depth, stride=1, use_attn=False)
        self.down3 = ConvBNAct(c3, c3, 3, stride=2)
        # bu4 fuses down3(p3) [c3] with p4r [c3] (p4 reduced to c3)
        self.bu4 = CSPResStage(c3 * 2, c4, depth, stride=1, use_attn=False)
        self.down4 = ConvBNAct(c4, c4, 3, stride=2)
        self.bu5 = CSPResStage(c4 * 2, c5, depth, stride=1, use_attn=False)
        self.out_channels = [c3, c4, c5]

    @staticmethod
    def _upx2(x):
        return F.interpolate(x, scale_factor=2, mode="nearest")

    def forward(self, feats):
        c3, c4, c5 = feats
        p5 = self.reduce5(c5)
        p4 = self.td4(T.concat([self._upx2(p5), c4], axis=1))
        p4r = self.reduce4(p4)
        p3 = self.td3(T.concat([self._upx2(p4r), c3], axis=1))
        n4 = self.bu4(T.concat([self.down3(p3), p4r], axis=1))
        n5 = self.bu5(T.concat([self.down4(n4), p5], axis=1))
        return p3, n4, n5


class PPYOLOEHead(Layer):
    """Anchor-free decoupled head with DFL regression.

    Per level: ESE-gated stem, then cls conv -> [B, nc, H, W] and reg conv
    -> [B, 4*(reg_max+1), H, W]; decode turns the reg distribution into
    ltrb distances via softmax expectation (the DFL integral), scaled by
    the level stride around grid anchor points.
    """

    def __init__(self, in_channels, num_classes=80, reg_max=16,
                 strides=(8, 16, 32)):
        super().__init__()
        self.num_classes = num_classes
        self.reg_max = reg_max
        self.strides = strides
        self.stems = nn.LayerList()
        self.cls_convs = nn.LayerList()
        self.reg_convs = nn.LayerList()
        self.cls_preds = nn.LayerList()
        self.reg_preds = nn.LayerList()
        for ch in in_channels:
            self.stems.append(EffectiveSE(ch))
            self.cls_convs.append(ConvBNAct(ch, ch, 3))
            self.reg_convs.append(ConvBNAct(ch, ch, 3))
            self.cls_preds.append(nn.Conv2D(ch, num_classes, 1))
            self.reg_preds.append(nn.Conv2D(ch, 4 * (reg_max + 1), 1))

    def forward(self, feats):
        """Returns (scores [B, A, nc], boxes [B, A, 4] xyxy in input px)."""
        all_scores, all_boxes = [], []
        for i, x in enumerate(feats):
            s = self.stems[i](x)
            cls = self.cls_preds[i](self.cls_convs[i](s) + s)
            reg = self.reg_preds[i](self.reg_convs[i](s))
            B, _, H, W = cls.shape
            nc, rm = self.num_classes, self.reg_max
            scores = T.reshape(T.transpose(cls, [0, 2, 3, 1]),
                               [B, H * W, nc])
            dist = T.reshape(T.transpose(reg, [0, 2, 3, 1]),
                             [B, H * W, 4, rm + 1])
            # DFL expectation: softmax over bins x bin index
            prob = F.softmax(dist, axis=-1)
            bins = T.reshape(T.arange(0, rm + 1, dtype="float32"),
                             [1, 1, 1, rm + 1])
            ltrb = T.sum(prob * bins, axis=-1)       # [B, HW, 4]
            stride = float(self.strides[i])
            # anchor centers in input pixels
            xs = (T.arange(0, W, dtype="float32") + 0.5) * stride
            ys = (T.arange(0, H, dtype="float32") + 0.5) * stride
            cx = T.reshape(T.tile(T.reshape(xs, [1, W]), [H, 1]),
                           [1, H * W])
            cy = T.reshape(T.tile(T.reshape(ys, [H, 1]), [1, W]),
                           [1, H * W])
            lt = T.slice(ltrb, [2], [0], [2]) * stride
            rb = T.slice(ltrb, [2], [2], [4]) * stride
            x1 = cx - T.squeeze(T.slice(lt, [2], [0], [1]), axis=2)
            y1 = cy - T.squeeze(T.slice(lt, [2], [1], [2]), axis=2)
            x2 = cx + T.squeeze(T.slice(rb, [2], [0], [1]), axis=2)
            y2 = cy + T.squeeze(T.slice(rb, [2], [1], [2]), axis=2)
            boxes = T.stack([x1, y1, x2, y2], axis=2)
            all_scores.append(F.sigmoid(scores))
            all_boxes.append(boxes)
        return (T.concat(all_scores, axis=1), T.concat(all_boxes, axis=1))


class PPYOLOE(Layer):
    """Backbone + neck + head; forward -> (scores, boxes), both dense."""

    def __init__(self, num_classes=80, width_mult=1.0, depth_mult=1.0):
        super().__init__()
        self.backbone = CSPResNet(width_mult, depth_mult)
        self.neck = CSPPAN(self.backbone.out_channels,
                           depth=max(1, round(depth_mult)))
        self.head = PPYOLOEHead(self.neck.out_channels, num_classes)
        self.num_classes = num_classes

    def forward(self, images):
        return self.head(self.neck(self.backbone(images)))

    def postprocess(self, scores, boxes, score_threshold=0.25,
                    iou_threshold=0.6, max_dets=100):
        """Host-side multiclass NMS over the dense predictions.

        scores: [B, A, nc]; boxes: [B, A, 4]. Returns a list (per image)
        of dicts with 'boxes' [k, 4], 'scores' [k], 'labels' [k] numpy.
        """
        s = scores.numpy() if hasattr(scores, "numpy") else np.asarray(scores)
        b = boxes.numpy() if hasattr(boxes, "numpy") else np.asarray(boxes)
        out = []
        for bi in range(s.shape[0]):
            cls = s[bi].argmax(-1)
            conf = s[bi].max(-1)
            keep0 = conf >= score_threshold
            if not keep0.any():
                out.append({"boxes": np.zeros((0, 4), np.float32),
                            "scores": np.zeros((0,), np.float32),
                            "labels": np.zeros((0,), np.int64)})
                continue
            kb, ks, kc = b[bi][keep0], conf[keep0], cls[keep0]
            kept = nms(kb, iou_threshold, scores=ks, category_idxs=kc,
                       categories=list(range(self.num_classes)),
                       top_k=min(max_dets, kb.shape[0]))
            kept = kept.numpy() if hasattr(kept, "numpy") else kept
            out.append({"boxes": kb[kept], "scores": ks[kept],
                        "labels": kc[kept].astype(np.int64)})
        return out


def ppyoloe_s(num_classes=80):
    return PPYOLOE(num_classes, width_mult=0.50, depth_mult=0.33)


def ppyoloe_m(num_classes=80):
    return PPYOLOE(num_classes, width_mult=0.75, depth_mult=0.67)


def ppyoloe_l(num_classes=80):
    return PPYOLOE(num_classes, width_mult=1.0, depth_mult=1.0)
