"""ShuffleNetV2. Parity: python/paddle/vision/models/shufflenetv2.py
(channel-shuffle units; width variants x0_25..x2_0 and a swish variant).
Uses nn.ChannelShuffle (one reshape-transpose, XLA-fused).
"""
from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0",
           "shufflenet_v2_x1_5", "shufflenet_v2_x2_0",
           "shufflenet_v2_swish"]


def _conv_bn_act(in_c, out_c, k, stride, groups=1, act="relu"):
    layers = [nn.Conv2D(in_c, out_c, k, stride=stride,
                        padding=(k - 1) // 2, groups=groups,
                        bias_attr=False),
              nn.BatchNorm2D(out_c)]
    if act == "relu":
        layers.append(nn.ReLU())
    elif act == "swish":
        layers.append(nn.Swish())
    return nn.Sequential(*layers)


class InvertedResidualUnit(nn.Layer):
    """stride-1 unit: split channels, transform one half, shuffle."""

    def __init__(self, c, act):
        super().__init__()
        half = c // 2
        self.branch = nn.Sequential(
            _conv_bn_act(half, half, 1, 1, act=act),
            _conv_bn_act(half, half, 3, 1, groups=half, act="none"),
            _conv_bn_act(half, half, 1, 1, act=act))
        self.shuffle = nn.ChannelShuffle(2)

    def forward(self, x):
        c = x.shape[1] // 2
        x1 = x[:, :c]
        x2 = x[:, c:]
        out = paddle.concat([x1, self.branch(x2)], axis=1)
        return self.shuffle(out)


class InvertedResidualDS(nn.Layer):
    """stride-2 (downsample) unit: both branches transformed."""

    def __init__(self, in_c, out_c, act):
        super().__init__()
        half = out_c // 2
        self.branch1 = nn.Sequential(
            _conv_bn_act(in_c, in_c, 3, 2, groups=in_c, act="none"),
            _conv_bn_act(in_c, half, 1, 1, act=act))
        self.branch2 = nn.Sequential(
            _conv_bn_act(in_c, half, 1, 1, act=act),
            _conv_bn_act(half, half, 3, 2, groups=half, act="none"),
            _conv_bn_act(half, half, 1, 1, act=act))
        self.shuffle = nn.ChannelShuffle(2)

    def forward(self, x):
        out = paddle.concat([self.branch1(x), self.branch2(x)], axis=1)
        return self.shuffle(out)


class ShuffleNetV2(nn.Layer):
    _stage_repeats = (4, 8, 4)

    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        supported = (0.25, 0.33, 0.5, 1.0, 1.5, 2.0)
        if scale not in supported:
            raise NotImplementedError(
                f"scale {scale} is not supported; choose one of "
                f"{supported}")
        channels = {
            0.25: (24, 24, 48, 96, 512), 0.33: (24, 32, 64, 128, 512),
            0.5: (24, 48, 96, 192, 1024), 1.0: (24, 116, 232, 464, 1024),
            1.5: (24, 176, 352, 704, 1024), 2.0: (24, 244, 488, 976, 2048),
        }[scale]
        self.conv1 = _conv_bn_act(3, channels[0], 3, 2, act=act)
        self.max_pool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        in_c = channels[0]
        for si, reps in enumerate(self._stage_repeats):
            out_c = channels[si + 1]
            stages.append(InvertedResidualDS(in_c, out_c, act))
            for _ in range(reps - 1):
                stages.append(InvertedResidualUnit(out_c, act))
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.conv_last = _conv_bn_act(in_c, channels[-1], 1, 1, act=act)
        if with_pool:
            self.pool2d_avg = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(channels[-1], num_classes)

    def forward(self, x):
        x = self.conv1(x)
        x = self.max_pool(x)
        x = self.stages(x)
        x = self.conv_last(x)
        if self.with_pool:
            x = self.pool2d_avg(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def _shufflenet(scale, act, pretrained, **kwargs):
    assert not pretrained, "pretrained weights unavailable (no egress)"
    return ShuffleNetV2(scale=scale, act=act, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet(0.25, "relu", pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet(0.33, "relu", pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet(0.5, "relu", pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet(1.0, "relu", pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet(1.5, "relu", pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet(2.0, "relu", pretrained, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _shufflenet(1.0, "swish", pretrained, **kwargs)
