"""Speculative decoding — draft/verify program pairs for the engine.

The serving engine (inference/engine.py) emits at most one token per
model forward: each decode tick runs ``tick_tokens`` sequential
micro-steps, and tpucost's decode anchor shows every micro-step is
KV-bandwidth bound (7 cache passes + a full weight stream per token).
Speculative decoding turns those one-token forwards into multi-token
forwards (ROADMAP item 2, per the MPK per-tick overhead analysis,
PAPERS.md 2512.22219): a cheap DRAFT proposes k candidate tokens per
slot, and ONE batched VERIFY program scores all k+1 positions for every
slot in a single target-model forward — weights stream once and the
cache makes its passes once per up-to-(k+1) emitted tokens instead of
once per token.

Two proposers, one verify program:

- :class:`NGramProposer` — host-side self-drafting ("prompt lookup"
  decoding): match the longest recent n-gram suffix of a slot's context
  (prompt + emitted tokens) against earlier occurrences and propose the
  tokens that followed the most recent match. No extra model, no extra
  programs, free on repetitive text (code, quoted context, template
  continuations — and greedy loops, which tiny LMs love).

- :class:`DraftModelProposer` — a small draft model running its OWN
  registered decode program (``gpt_draft_decode``) over a second
  slot-based KV cache: one jitted dispatch catches the draft cache up
  on the tokens accepted last tick (always exactly the 2-token block
  [prev, tok] — see the sync invariant below) and scans k greedy draft
  steps, returning [N, k] proposals for every slot at once.

- the VERIFY program (``gpt_verify_k``, built by
  :func:`make_verify_program`) feeds every slot's [tok, d1..dk] block
  through the target model at per-row position vectors — k-drift,
  acceptance-pattern drift, prompt drift and page placement all ride as
  int32/bool arguments, so nothing ever retraces (the PR 2/9
  discipline). The greedy accept-longest-prefix AND the correction
  token come out of the same forward: the emitted block is simply the
  target's own argmax at every position (an accepted draft token equals
  the target token by definition), so greedy speculative output is
  BITWISE token-identical to plain decode no matter what the drafter
  proposed — acceptance only decides how MANY tokens each tick may
  consume (n_accepted + 1).

Why rejected tokens need no KV rollback program: verify writes the
block's KV at positions [pos, pos+k] — contiguous from the row's true
length. After accepting n, the row's new true length is pos+n+1; the
garbage KV the rejected tokens left at (pos+n+1, pos+k] sits strictly
ABOVE every future query position until the token actually at that
index overwrites it (causal masking — the same dead-row argument the
engine's admission reset and paged live-mask rely on). In paged mode
the write is live-mask gated and lands only in the slot's PRIVATE
pages: shared prefix pages cover complete PROMPT pages, and every
speculative write position is >= prompt_len (asserted bitwise in
tests/test_paged_engine.py churn).

Draft-cache sync invariant (draft-model mode): before each tick the
draft cache holds true KV through position pos-1 and has never seen
``tok`` (the engine's current token at position pos). The draft
dispatch feeds [prev, tok] at positions [pos-1, pos] — re-writing
pos-1 with the true token it already holds (idempotent: k/v rows are
deterministic functions of the true prefix) covers the one case where
full acceptance left position pos-1 unwritten — then drafts k tokens
autoregressively. After verify accepts n of them, positions pos..pos+n
hold true draft KV (accepted tokens ARE the true tokens), so the
invariant holds again at pos' = pos+n+1 with no rollback either.

Greedy only: acceptance-by-token-equality is exact for argmax; the
engine rejects ``do_sample`` + speculative loudly rather than serve a
subtly different sampling distribution.

Env knobs (engine-resolved): PADDLE_TPU_SERVE_SPEC ("ngram" or unset),
PADDLE_TPU_SERVE_SPEC_K (draft length k, default 4),
PADDLE_TPU_SERVE_SPEC_NGRAM (max n-gram match length, default 3).
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..jit.functional import functional_call, raw_state

__all__ = ["SpeculativeConfig", "resolve_speculative", "NGramProposer",
           "DraftModelProposer", "make_verify_program"]


@dataclass(frozen=True)
class SpeculativeConfig:
    """Resolved speculative-decoding configuration for one engine."""
    kind: str                    # "ngram" | "draft"
    k: int                       # draft tokens proposed per tick
    ngram_max: int = 3           # longest suffix n-gram to match
    ngram_min: int = 1           # shortest n-gram worth matching
    draft_model: Optional[object] = None   # kind == "draft" only


def resolve_speculative(speculative, spec_k=None, spec_ngram=None,
                        draft_model=None) -> Optional[SpeculativeConfig]:
    """Normalize the engine's ``speculative=`` knob (None reads
    PADDLE_TPU_SERVE_SPEC, False forces off, True means "ngram") into a
    SpeculativeConfig or None."""
    if speculative is None:
        speculative = os.environ.get("PADDLE_TPU_SERVE_SPEC", "").strip()
    if speculative in (False, None, "", "0", "off", "none"):
        return None
    if speculative is True:
        speculative = "ngram"
    kind = str(speculative).lower()
    if kind not in ("ngram", "draft"):
        raise ValueError(f"unknown speculative mode {speculative!r} "
                         "(valid: 'ngram', 'draft', None)")
    if kind == "draft" and draft_model is None:
        raise ValueError("speculative='draft' needs draft_model= (a "
                         "small cache-threaded causal LM)")
    from ..framework.env import int_env
    k = int(spec_k if spec_k is not None
            else int_env("PADDLE_TPU_SERVE_SPEC_K", 4))
    if k < 1:
        raise ValueError("spec_k must be >= 1")
    ngram_max = int(spec_ngram if spec_ngram is not None
                    else int_env("PADDLE_TPU_SERVE_SPEC_NGRAM", 3))
    if ngram_max < 1:
        raise ValueError("spec_ngram must be >= 1")
    return SpeculativeConfig(kind, k, ngram_max, 1,
                             draft_model if kind == "draft" else None)


# ---------------------------------------------------------------------------
# n-gram self-drafting (host-side — no model, no programs)
# ---------------------------------------------------------------------------

class NGramProposer:
    """Propose the continuation of the most recent earlier occurrence
    of the context's longest matching suffix n-gram ("prompt lookup"
    decoding). Pure numpy over each slot's token history; wrong
    proposals cost only rejected verify positions, never correctness.
    """

    kind = "ngram"

    def __init__(self, k: int, ngram_max: int = 3, ngram_min: int = 1):
        if ngram_min < 1 or ngram_max < ngram_min:
            raise ValueError("need 1 <= ngram_min <= ngram_max")
        self.k = int(k)
        self.ngram_max = int(ngram_max)
        self.ngram_min = int(ngram_min)

    def propose(self, context: np.ndarray):
        """(props[k] int32, draft_len) for one slot's full token
        context. Longest suffix n-gram wins; among equal-length matches
        the most recent one with a FULL k-token continuation wins, else
        the EARLIEST hit (whose continuation to the context end is the
        longest available). Both preferences matter on exactly the text
        this drafter exists for: in periodic context the most recent
        match sits near the context end and its continuation truncates
        after one period, and inside a still-growing repeated run the
        latest match's continuation is a single token while the
        earliest covers the whole run so far."""
        ctx = np.asarray(context).reshape(-1)
        L = ctx.shape[0]
        props = np.zeros(self.k, np.int32)
        for g in range(min(self.ngram_max, L - 1), self.ngram_min - 1,
                       -1):
            pat = ctx[L - g:]
            # candidate matches end strictly before the suffix itself
            # and must leave >= 1 continuation token
            hay = ctx[:L - 1]
            if hay.shape[0] < g:
                continue
            win = np.lib.stride_tricks.sliding_window_view(hay, g)
            hits = np.nonzero((win == pat).all(axis=1))[0]
            if hits.shape[0] == 0:
                continue
            # continuation of hit h starts at h + g; full drafts need
            # h + g + k <= L — absent one, the earliest hit maximizes
            # the truncated continuation
            full = hits[hits + g + self.k <= L]
            j = int(full[-1] if full.shape[0] else hits[0]) + g
            cont = ctx[j:j + self.k]
            props[:cont.shape[0]] = cont.astype(np.int32)
            return props, int(cont.shape[0])
        return props, 0


# ---------------------------------------------------------------------------
# draft-model proposer (its own registered decode program + KV cache)
# ---------------------------------------------------------------------------

class DraftModelProposer:
    """A small draft model with its own slot-based KV cache and two
    jitted programs: a bucketed admission prefill (mirrors the engine's
    slot admit, full-row reset included) and ONE batched draft-decode
    program (``gpt_draft_decode``) that catches every slot up on the
    [prev, tok] sync block and scans k greedy draft steps — proposals
    for all N slots in a single dispatch, positions as int32 vectors so
    nothing ever retraces."""

    kind = "draft"

    def __init__(self, model, slots: int, max_len: int, k: int,
                 cache_dtype: str = "float32"):
        self.model = model
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.k = int(k)
        self.cache_dtype = cache_dtype
        was_training = model.training
        model.eval()
        self._params, self._buffers = raw_state(model)
        if was_training:
            model.train()
        self._caches = model.new_cache(self.slots, self.max_len,
                                       cache_dtype)
        self._admit_progs = {}
        self._decode_prog = None
        self._trace_count = 0      # ticks inside traced bodies only

    # -- programs --------------------------------------------------------
    def _get_admit_prog(self, bucket: int):
        prog = self._admit_progs.get(bucket)
        if prog is not None:
            return prog
        model, proposer = self.model, self

        def admit(params, buffers, ids, caches, slot):
            proposer._trace_count += 1    # fires at trace time only
            # fresh zeroed row built in-program: inserting the full row
            # range resets a retired slot's stale draft KV, exactly
            # like the engine's own admission
            temp = model.new_cache(1, proposer.max_len,
                                   proposer.cache_dtype)
            (_, temp), _ = functional_call(
                model, params, buffers, ids, temp, jnp.int32(0),
                training=False)

            def insert(slot_leaf, temp_leaf):
                ax = next(i for i, (a, c) in enumerate(
                    zip(slot_leaf.shape, temp_leaf.shape)) if a != c)
                start = [0] * slot_leaf.ndim
                start[ax] = slot
                return lax.dynamic_update_slice(
                    slot_leaf, temp_leaf.astype(slot_leaf.dtype),
                    tuple(start))

            return jax.tree_util.tree_map(insert, caches, temp)

        prog = jax.jit(admit, donate_argnums=(3,))
        self._admit_progs[bucket] = prog
        return prog

    def _get_decode_prog(self):
        """ONE batched draft program: sync block [prev, tok] at
        positions [pos-1, pos] (see the module-docstring invariant),
        then k greedy single-token draft steps — [N, k] proposals per
        dispatch. The draft's own numerics never affect emitted tokens
        (those are always the TARGET's argmax); draft drift only costs
        acceptance."""
        if self._decode_prog is not None:
            return self._decode_prog
        model, proposer = self.model, self
        K = self.k

        def draft_decode(params, buffers, caches, prev, tok, pos):
            proposer._trace_count += 1    # fires at trace time only
            ids = jnp.stack([prev, tok], axis=1)          # [N, 2]
            (logits, caches), _ = functional_call(
                model, params, buffers, ids, caches, pos - 1,
                training=False)
            d = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)

            def body(carry, _):
                d, caches, p = carry
                (lg, caches), _ = functional_call(
                    model, params, buffers, d[:, None], caches, p,
                    training=False)
                nd = jnp.argmax(lg[:, -1, :],
                                axis=-1).astype(jnp.int32)
                return (nd, caches, p + 1), nd

            if K > 1:
                (_, caches, _), rest = lax.scan(
                    body, (d, caches, pos + 1), None, length=K - 1)
                props = jnp.concatenate([d[:, None], rest.T], axis=1)
            else:
                props = d[:, None]
            return props, caches                          # [N, K]

        self._decode_prog = jax.jit(draft_decode, donate_argnums=(2,))
        return self._decode_prog

    def _decode_example_args(self) -> tuple:
        N = self.slots
        return (self._params, self._buffers, self._caches,
                np.zeros(N, np.int32), np.zeros(N, np.int32),
                np.ones(N, np.int32))

    def _admit_example_args(self, bucket: int) -> tuple:
        return (self._params, self._buffers,
                np.zeros((1, bucket), np.int64), self._caches,
                np.int32(0))

    # -- host API --------------------------------------------------------
    def admit(self, slot: int, prompt: np.ndarray, bucket: int) -> None:
        """Prefill one slot's draft cache row with the full prompt
        (right-padded to ``bucket`` — padding garbage lands above the
        prompt and is overwritten before any query can attend it)."""
        P = prompt.shape[0]
        ids = np.zeros((1, bucket), np.int64)
        ids[0, :P] = prompt
        self._caches = self._get_admit_prog(bucket)(
            self._params, self._buffers, ids, self._caches,
            np.int32(slot))

    def propose(self, prev: np.ndarray, tok: np.ndarray,
                pos: np.ndarray) -> np.ndarray:
        """[N, k] int32 proposals for every slot in one dispatch."""
        props, self._caches = self._get_decode_prog()(
            self._params, self._buffers, self._caches, prev, tok, pos)
        return np.asarray(props)

    def warmup(self, buckets, store=None, static_key: str = "") -> list:
        """AOT compile-or-load the draft programs through the
        persistent executable store (engine.warmup() forwards here)."""
        from ..compilation import log as _clog
        from ..compilation.store import AotProgram, aot_compile
        static = static_key + "|draft:" + repr(
            (type(self.model).__name__, self.k, self.max_len,
             self.cache_dtype))
        recs = []
        if not isinstance(self._decode_prog, AotProgram):
            rec: dict = {"site": "engine_draft_decode"}
            self._decode_prog = aot_compile(
                "engine_draft_decode", self._get_decode_prog(),
                self._decode_example_args(), store=store,
                log_record=rec, static_key=static)
            recs.append(_clog.record(rec))
        for bucket in buckets:
            bucket = int(bucket)
            if isinstance(self._admit_progs.get(bucket), AotProgram):
                continue
            rec = {"site": f"engine_draft_admit_b{bucket}"}
            self._admit_progs[bucket] = aot_compile(
                f"engine_draft_admit_b{bucket}",
                self._get_admit_prog(bucket),
                self._admit_example_args(bucket), store=store,
                log_record=rec, static_key=static)
            recs.append(_clog.record(rec))
        return recs


# ---------------------------------------------------------------------------
# the batched verify-k program (the target-model half of the pair)
# ---------------------------------------------------------------------------

def make_verify_program(model, spec_k: int, paged: bool,
                        trace_hook=None):
    """Build the ONE jitted batched verify program for an engine.

    Slot mode:
        verify(params, buffers, caches, tok, pos, live, props, dlen)
    Paged mode (block tables + live write gate attached per call):
        verify(params, buffers, caches, bt, tok, pos, live, props, dlen)

    Returns ``(toks [N, k+1] i32, n_acc [N] i32, caches)``:
    ``toks[i, j]`` is the TARGET's greedy token for position
    pos[i]+j+1 (context = the true prefix + tok + d1..dj, which is the
    true context exactly for j <= n_acc[i]); the host consumes
    ``n_acc[i] + 1`` of them — the accepted prefix plus the
    correction/bonus token, all computed in-program from one forward.
    Proposal values, draft lengths, positions and the live mask are all
    ARGUMENTS: k-pattern drift never retraces.
    """
    from .engine import _attach_page_meta, _strip_page_meta
    K = int(spec_k)

    def _verify_body(params, buffers, caches, bt, tok, pos, live,
                     props, dlen):
        if trace_hook is not None:
            trace_hook()                  # fires at trace time only
        ids = jnp.concatenate([tok[:, None], props], axis=1)  # [N,K+1]
        if paged:
            cm = _attach_page_meta(caches, bt=bt, live=live)
        else:
            cm = caches
        (logits, cm), _ = functional_call(
            model, params, buffers, ids, cm, pos, training=False)
        if paged:
            caches = _strip_page_meta(cm)
        else:
            caches = cm
        tgt = jnp.argmax(logits, axis=-1).astype(jnp.int32)   # [N,K+1]
        j = jnp.arange(K, dtype=jnp.int32)[None, :]
        match = ((props == tgt[:, :K])
                 & (j < dlen[:, None])).astype(jnp.int32)
        # leading-match count: cumprod zeroes everything after the
        # first mismatch, the row sum is the accepted prefix length
        n_acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
        return tgt, n_acc.astype(jnp.int32), caches

    if paged:
        def verify(params, buffers, caches, bt, tok, pos, live, props,
                   dlen):
            return _verify_body(params, buffers, caches, bt, tok, pos,
                                live, props, dlen)
    else:
        def verify(params, buffers, caches, tok, pos, live, props,
                   dlen):
            return _verify_body(params, buffers, caches, None, tok,
                                pos, live, props, dlen)

    return jax.jit(verify, donate_argnums=(2,))
