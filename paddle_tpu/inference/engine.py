"""Continuous-batching serving engine with a slot-based KV cache.

Serving north star (ROADMAP: "heavy traffic from millions of users, as
fast as the hardware allows"): `models/generation.py::generate()` decodes
ONE stream per compiled program, so chip utilization collapses to
batch=1 the moment traffic is concurrent. This engine multiplexes many
requests through a CONSTANT set of compiled programs:

- a fixed pool of N decode slots backed by one pre-allocated slot-based
  KV cache (`model.new_cache(N, max_len, dtype)` — per-layer
  [B=N, max_len, kv_heads, head_dim] arrays, bf16/f32 or the int8
  quantized dict form), donated through every step so XLA updates it in
  place in HBM;
- ONE jitted batched decode program per engine: each tick runs
  `tick_tokens` micro-steps for ALL slots (dead slots ride along under
  an active mask — fixed shapes, no recompiles, one host sync per tick
  for the emitted [N, tick_tokens] block);
- a small set of bucketed prefill programs: a queued request's prompt is
  right-padded to the nearest bucket, prefilled into a FRESH zeroed
  cache inside the program, and the whole slot row range is overwritten
  at admission (so a retired slot's stale rows — including int8
  quantization scales — can never leak into the next request);
- admission and retirement happen at tick boundaries only: queued
  requests enter free slots, finished ones (per-request EOS / token
  budget) resolve their futures. No head-of-line blocking: a long
  request never stalls short ones sharing the batch.

Why right-padded bucketed prefill is exact: causal attention means the
garbage rows a padded prompt writes at [P, bucket) are never attended
by positions < P, and decode overwrites position p before the mask can
reach it — so greedy outputs are token-identical to sequential
`generate()` per request (asserted in tests/test_engine.py).

Fusion-preserving, recompile-free regime per "Operator Fusion in XLA"
and MPK (PAPERS.md): the decode step stays one fixed-shape compiled
program; concurrency is multiplexed through it, never traced into it.

Paged mode (``paged=True`` / PADDLE_TPU_SERVE_PAGED — ISSUE 9): the
worst-case [N, max_len] slot rows above waste cache on the 99% of
requests that are short — one long ``max_len`` caps concurrency for
everyone, and tpucost's decode anchor shows the tick is KV-bandwidth
bound, so every wasted byte is wasted HBM traffic too. Paged mode
carves the cache into fixed ``page_size``-token PAGES shared by all
slots (per-layer pools [num_pages, page_size, kv_heads, hd]); each slot
holds a BLOCK TABLE of physical page indices:

- the ONE batched decode program GATHERS each slot's pages by table
  index into the contiguous view attention already understands (reads
  stay gather-based — the scatter-free decode anchor holds) and writes
  stay one-hot masked into the slot's current page, gated on the live
  mask so a dead slot can never touch a page reallocated to another
  request;
- admission appends the VARIABLE-LENGTH prefill output page-by-page
  (bucketed by suffix length, write-masked to the real rows) instead of
  rebuilding a worst-case row — a request holds exactly
  ceil((P + max_new + tick) / page_size) pages, so at equal cache bytes
  the pool admits strictly more short requests than slot rows can;
- a host-side page allocator (free list + refcounts, inference/paging)
  lets concurrent requests SHARE the read-only pages of a common prompt
  prefix: the prefix trie matches complete prompt pages at admission,
  matched pages are increffed instead of recomputed (prefill work drops
  to the un-matched suffix — for a fully-cached prompt, to ONE token),
  and the only page a fully-matched prompt would write into is
  copy-on-written first. Shared pages are read-only for life: complete
  prompt pages end strictly below every decode write position.

Why paged greedy output is token-identical to the slot engine: the
gathered view has the same length the slot row had, the causal mask
passes the same positions, and masked garbage (stale pages, bucket
padding) contributes exact zeros through softmax(-1e30) — asserted in
tests/test_paged_engine.py, including int8 pools and shared-prefix
admissions.

Speculative mode (``speculative=`` / PADDLE_TPU_SERVE_SPEC — ISSUE 13,
ROADMAP item 2): the decode tick above still pays one model forward
per emitted token. With speculative decoding on, the tick loop swaps
the plain tick for a DRAFT -> VERIFY pair (inference/speculative.py):
a proposer drafts up to k candidate tokens per slot (host-side n-gram
self-drafting, or a small draft model's own registered decode
program), and ONE jitted batched verify program scores all k+1
positions for every slot in a single target forward — per-slot
proposal vectors, draft lengths, positions and live masks ride as
int32/bool arguments, so k-drift / acceptance-pattern drift / prompt
drift never recompile. The emitted block is the TARGET's own argmax at
every position, so greedy speculative output is bitwise
token-identical to plain decode (f32 and int8, slot and paged caches —
tier-1 asserted); acceptance only decides how many tokens each tick
consumes. Rejected positions need no KV rollback: their garbage KV
sits above the row's true length behind the causal mask (and, paged,
behind the live write gate in the slot's PRIVATE pages) until the true
token overwrites it. Greedy only — ``do_sample`` rejects loudly.

Env knobs: PADDLE_TPU_SERVE_SLOTS (default 8),
PADDLE_TPU_SERVE_PREFILL_BUCKETS (comma list, default powers of two),
PADDLE_TPU_SERVE_TICK_TOKENS (default 8),
PADDLE_TPU_SERVE_MAX_QUEUE (default 32),
PADDLE_TPU_SERVE_PAGED (default 0), PADDLE_TPU_KV_PAGE (page size,
default 16), PADDLE_TPU_SERVE_NUM_PAGES (default slots *
ceil(max_len/page) — the slot engine's exact byte budget),
PADDLE_TPU_SERVE_SPEC ("ngram" to self-draft, default off),
PADDLE_TPU_SERVE_SPEC_K (draft tokens per tick, default 4),
PADDLE_TPU_SERVE_SPEC_NGRAM (max suffix n-gram, default 3).
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
import uuid
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .. import obs as _obs
from ..obs import efficiency as _eff
from ..distributed import resilience as _resil
from ..jit.functional import functional_call, raw_state
from ..models.generation import _select_token
from .paging import pages_needed as _pages_needed

__all__ = ["ContinuousBatchingEngine", "EngineOverloaded",
           "CacheExhausted", "RequestCancelled", "GenerationPredictor",
           "create_engine_predictor"]


class EngineOverloaded(RuntimeError):
    """Raised by submit() when the request queue is at capacity — the
    serving layer maps this to the 503 `overloaded` record (same
    load-shedding contract as the PR-1 predictor path). ``reason`` is
    the truthful shed record the serving layer forwards (a subclass
    narrows it)."""

    reason = "overloaded"

    def __init__(self, queue_depth: int, max_queue: int):
        super().__init__(
            f"engine queue saturated ({queue_depth}/{max_queue})")
        self.queue_depth = queue_depth
        self.max_queue = max_queue


class CacheExhausted(EngineOverloaded):
    """Queue saturated while the KV page pool — not slot count or
    request rate — is the binding constraint (paged engines only). The
    serving layer maps this to 503 `cache_exhausted` so operators can
    tell "add cache pages / shrink page footprints" from plain
    overload; retries clear when a request retires and frees pages."""

    reason = "cache_exhausted"

    def __init__(self, queue_depth: int, max_queue: int,
                 free_pages: int, num_pages: int):
        super().__init__(queue_depth, max_queue)
        self.free_pages = free_pages
        self.num_pages = num_pages


class RequestCancelled(RuntimeError):
    """The request was cancelled (``engine.cancel`` — client
    disconnect, a hedged duplicate losing its race, an operator
    ``POST /cancel``). Raised out of the request's future; the partial
    result — tokens generated before the cancel landed — rides the
    future's ``_ptpu_gen_info`` (``tokens_generated`` +
    ``partial_tokens``) so no work is silently discarded. Cancellation
    applies at the next tick boundary: the slot retires, its KV pages
    free — leak-free, counter-asserted in tests."""

    def __init__(self, request_id: str, tokens_generated: int):
        super().__init__(
            f"request {request_id or '<anonymous>'} cancelled after "
            f"{tokens_generated} generated token(s)")
        self.request_id = request_id
        self.tokens_generated = tokens_generated


def _attach_page_meta(caches, **meta):
    """Return the cache pytree with block-table / write-gate metadata
    merged into every paged dict (same traced arrays referenced
    everywhere — XLA sees one value). Scan-stacked pools (leaves with a
    leading layer axis — ``pages`` is 5-D) get the metadata broadcast
    with that same leading L, so ScannedStack's layer scan slices ONE
    host block table into identical per-layer [B, PM] views (the block
    table's "layer axis", ISSUE 20 / the PR 9 follow-up) and each scan
    step sees an ordinary per-layer paged dict."""
    if isinstance(caches, dict):
        if "pages" not in caches:
            return caches
        if caches["pages"].ndim == 5:     # scan-stacked [L, NP, PS, ...]
            L = caches["pages"].shape[0]
            meta = {k: jnp.broadcast_to(jnp.asarray(v),
                                        (L,) + tuple(jnp.shape(v)))
                    for k, v in meta.items()}
        return {**caches, **meta}
    if isinstance(caches, (list, tuple)):
        return type(caches)(_attach_page_meta(c, **meta)
                            for c in caches)
    return caches


def _strip_page_meta(caches):
    """Inverse of _attach_page_meta: reduce paged dicts back to their
    pool leaves so the engine-held pytree (and the donated program
    output) is pools only."""
    if isinstance(caches, dict):
        return {k: v for k, v in caches.items()
                if k in ("pages", "scale")}
    if isinstance(caches, (list, tuple)):
        return type(caches)(_strip_page_meta(c) for c in caches)
    return caches


# shared env-knob parser (framework/env.py), aliased to keep call sites
from ..framework.env import int_env as _env_int


def _default_buckets(max_len: int) -> tuple:
    """Powers of two up to AND INCLUDING max_len (a long prompt with a
    small token budget legitimately prefills near the full cache)."""
    out, b = [], 8
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(max_len)
    return tuple(sorted(set(out)))


@dataclass
class _Request:
    prompt: np.ndarray           # [P] int64
    max_new_tokens: int
    eos_token_id: Optional[int]
    seed: int
    future: Future = field(default_factory=Future)
    rid: str = ""                # request id (obs span correlation)
    t_submit: float = 0.0        # perf_counter at submit (obs only)
    drafted: int = 0             # speculative: tokens proposed for me
    accepted: int = 0            # speculative: proposals accepted
    progress_cb: Optional[object] = None   # per-token progress hook
    cancelled: bool = False      # cancel() flagged; retired at the
    #                              next tick boundary


class _Slot:
    """Host-side mirror of one decode slot's in-program state."""

    __slots__ = ("req", "pos", "tok", "alive", "remaining", "emitted",
                 "key", "t_dec0", "pages")

    def __init__(self):
        self.req: Optional[_Request] = None
        self.pos = 0
        self.tok = 0
        self.alive = False
        self.remaining = 0
        self.emitted: List[int] = []
        self.key = np.zeros(2, np.uint32)
        self.t_dec0 = 0.0        # decode-phase start (obs only)
        self.pages: List[int] = []   # paged mode: owned page refs

    @property
    def free(self) -> bool:
        return self.req is None


class ContinuousBatchingEngine:
    """Serve arbitrary concurrent mixed-length generate requests through
    a constant set of compiled programs (see module docstring).

    `model` must expose the cache-threaded forward contract of
    models/generation.py (GPTForCausalLM, LlamaForCausalLM do). Greedy
    outputs are token-identical to sequential `generate()`; sampling is
    reproducible per request (slot-position-keyed PRNG) but draws a
    different stream than the sequential scan.
    """

    def __init__(self, model, slots: Optional[int] = None,
                 max_len: Optional[int] = None,
                 cache_dtype: str = "bfloat16",
                 prefill_buckets: Optional[tuple] = None,
                 tick_tokens: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0,
                 paged: Optional[bool] = None,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 prefix_cache: bool = True,
                 speculative=None, spec_k: Optional[int] = None,
                 spec_ngram: Optional[int] = None, draft_model=None,
                 tp: Optional[int] = None, mesh=None,
                 comm_precision: Optional[str] = None):
        self.model = model
        self.slots = int(slots if slots is not None
                         else _env_int("PADDLE_TPU_SERVE_SLOTS", 8))
        if self.slots < 2:
            raise ValueError("engine needs >= 2 slots (batch-axis "
                             "detection and batching both require it)")
        model_max = getattr(getattr(model, "cfg", None), "max_seq_len",
                            None)
        self.max_len = int(max_len if max_len is not None
                           else (model_max or 1024))
        if model_max is not None and self.max_len > model_max:
            raise ValueError(
                f"max_len {self.max_len} exceeds the model's "
                f"max_seq_len {model_max}")
        if prefill_buckets is None:
            spec = os.environ.get("PADDLE_TPU_SERVE_PREFILL_BUCKETS", "")
            prefill_buckets = (tuple(int(x) for x in spec.split(",") if
                                     x.strip())
                               if spec else _default_buckets(self.max_len))
        self.prefill_buckets = tuple(sorted(
            b for b in prefill_buckets if b <= self.max_len))
        if not self.prefill_buckets:
            raise ValueError("no prefill bucket fits max_len")
        self.tick_tokens = int(
            tick_tokens if tick_tokens is not None
            else _env_int("PADDLE_TPU_SERVE_TICK_TOKENS", 8))
        if self.tick_tokens < 1:
            raise ValueError("tick_tokens must be >= 1")
        self.max_queue = int(
            max_queue if max_queue is not None
            else _env_int("PADDLE_TPU_SERVE_MAX_QUEUE", 32))
        self.cache_dtype = cache_dtype
        self._sampling = (bool(do_sample), float(temperature),
                          int(top_k), float(top_p))

        # tensor-parallel slice (inference/tp.py, ISSUE 20): tp > 1
        # makes THIS engine an N-chip replica — params/KV head-sharded
        # per the Megatron layout, programs pjit-partitioned over the
        # slice mesh, block tables and all host-side control replicated.
        # tp= / mesh= / PADDLE_TPU_SERVE_TP; comm_precision routes the
        # per-block all-reduce through the PR 17 quantized wire bodies.
        from .tp import TPContext, resolve_tp, validate_tp_model
        if mesh is not None and tp is None:
            tp = int(mesh.shape.get("mp", 1))
        self.tp = resolve_tp(tp)
        if self.tp > 1 or mesh is not None:
            validate_tp_model(model, self.tp)
            self._tp = TPContext(self.tp, comm_precision=comm_precision,
                                 mesh=mesh)
        else:
            self._tp = None
        # fused-kernel knobs × TP (ISSUE 20 satellite): knobs that are
        # env-enabled but forced off under this engine's sharded mesh —
        # the loud fallback fires HERE (once, at construction), and
        # stats() carries the list so operators see the downgrade
        self.fused_knobs_disabled_tp: List[str] = []
        if self._tp is not None:
            from ..framework.env import bool_env as _bool_env
            from ..nn.functional.flash_attention import (
                _fused_cache_write_on, _mega_decode_on)
            with self._tp.activate():
                if _bool_env("PADDLE_TPU_FUSED_CACHE_WRITE", False) \
                        and not _fused_cache_write_on():
                    self.fused_knobs_disabled_tp.append(
                        "PADDLE_TPU_FUSED_CACHE_WRITE")
                if _bool_env("PADDLE_TPU_MEGA_DECODE", False) \
                        and not _mega_decode_on():
                    self.fused_knobs_disabled_tp.append(
                        "PADDLE_TPU_MEGA_DECODE")

        # speculative decoding (inference/speculative.py, ISSUE 13)
        from .speculative import (DraftModelProposer, NGramProposer,
                                  resolve_speculative)
        self._spec = resolve_speculative(speculative, spec_k,
                                         spec_ngram, draft_model)
        if self._spec is not None and do_sample:
            raise ValueError(
                "speculative decoding is greedy-only (acceptance is "
                "exact token equality against the target argmax); "
                "do_sample engines must run plain decode")
        # worst-case tokens a slot can overshoot its budget by in one
        # tick: tick_tokens plain, k+1 per verify dispatch — and the
        # verify block WRITES cache positions up to pos + k, so the
        # same bound sizes the cache-length check and page footprints
        self._overshoot = (max(self.tick_tokens, self._spec.k + 1)
                           if self._spec is not None
                           else self.tick_tokens)

        # paged KV cache config (module docstring, ISSUE 9)
        self.paged = bool(_env_int("PADDLE_TPU_SERVE_PAGED", 0)
                          if paged is None else paged)
        self.page_size = int(page_size if page_size is not None
                             else _env_int("PADDLE_TPU_KV_PAGE", 16))
        if self.page_size < 1:
            raise ValueError("page_size must be >= 1")
        # block-table width: enough logical pages to cover one
        # max_len-token request — the per-REQUEST cap is unchanged,
        # paging relaxes only the per-POOL sum
        self.pages_per_slot = _pages_needed(self.max_len,
                                            self.page_size)
        if num_pages is None:
            num_pages = _env_int("PADDLE_TPU_SERVE_NUM_PAGES", 0) or \
                self.slots * self.pages_per_slot
        self.num_pages = int(num_pages)
        self.prefix_cache = bool(prefix_cache)
        self._allocator = None
        self._trie = None
        self._pool_blocked = False    # last admission failed on pages
        self.prefix_hits = 0          # admissions with >= 1 trie page
        self.prefix_misses = 0
        self.prefix_tokens_saved = 0  # prompt tokens NOT re-prefilled
        self.prefill_tokens = 0       # suffix tokens actually prefilled

        was_training = model.training
        model.eval()
        self._params, self._buffers = raw_state(model)
        if was_training:
            model.train()
        if self.paged:
            if self.num_pages < self.pages_per_slot:
                raise ValueError(
                    f"num_pages {self.num_pages} cannot hold even one "
                    f"max_len request ({self.pages_per_slot} pages)")
            from .paging import PageAllocator, PrefixTrie
            self._allocator = PageAllocator(self.num_pages)
            self._trie = PrefixTrie(self._allocator)
            self._caches = model.new_paged_cache(
                self.num_pages, self.page_size, cache_dtype)
            self._block_tables = np.zeros(
                (self.slots, self.pages_per_slot), np.int32)
        else:
            self._caches = model.new_cache(self.slots, self.max_len,
                                           cache_dtype)
            self._block_tables = None
        if self._tp is not None:
            # land state in the Megatron layout BEFORE any program
            # traces: params/buffers by their sharding_axes annotations,
            # KV leaves head-sharded — pjit then propagates these input
            # shardings through every engine program (block tables stay
            # host numpy, replicated by jit's default for uncommitted
            # arguments, so paging.py never changes)
            self._params, self._buffers = self._tp.shard_state(
                model, self._params, self._buffers)
            self._caches = self._tp.shard_caches(self._caches)
        self._slots = [_Slot() for _ in range(self.slots)]
        self._queue: List[_Request] = []
        self._cv = _obs.make_condition("engine.cv")
        self._stop_flag = False
        self._broken: Optional[BaseException] = None

        # compiled-program accounting: the counters tick inside the
        # TRACED bodies, so they move only when XLA actually (re)traces
        # — tests assert they stay constant after warmup no matter how
        # many distinct (prompt-len, max-new-tokens) pairs are served
        self._trace_count = 0
        self._admit_progs = {}        # bucket -> jitted admit program
        self._decode_prog = None
        self._copy_prog = None        # paged: COW page-copy program
        self._verify_prog = None      # speculative: batched verify-k
        self._warmed = False          # warmup() completed
        # serializes warmup(): two threads tracing the same program
        # concurrently leak tracers into each other's jaxprs (found by
        # tools/race_hunt.py warmup_concurrent) — one compiles, the
        # rest wait and see AotPrograms already installed
        self._warmup_lock = _obs.make_lock("engine.warmup")
        self.ticks = 0
        self.admitted = 0
        self.completed = 0
        self.cancelled = 0            # requests cancelled (queued or
        #                               slot-retired mid-decode)
        # last tick's model efficiency (obs.efficiency): modeled HBM
        # bytes over measured tick wall time as a fraction of the
        # efficiency chip's bandwidth; 0.0 until a tick ran (or with
        # obs off — stats() stays shape-uniform either way)
        self.last_tick_model_eff = 0.0

        # speculative proposer + counters (always present so stats()
        # reads uniformly; the proposer exists only when configured)
        self._proposer = None
        self.spec_ticks = 0           # verify dispatches
        self.tokens_drafted = 0
        self.tokens_accepted = 0      # drafted tokens that matched
        self.tokens_rejected = 0
        self.spec_tokens_emitted = 0  # tokens consumed off verify ticks
        self.spec_slot_ticks = 0      # live (slot, verify-tick) pairs
        if self._spec is not None:
            if self._spec.kind == "draft":
                self._proposer = DraftModelProposer(
                    self._spec.draft_model, self.slots, self.max_len,
                    self._spec.k, cache_dtype="float32")
            else:
                self._proposer = NGramProposer(
                    self._spec.k, self._spec.ngram_max,
                    self._spec.ngram_min)

        # observability (paddle_tpu.obs): per-request phase spans into
        # the flight recorder + registry series on /metrics. The flag
        # is snapshotted ONCE so the disabled hot path is a single
        # attribute test per site — no spans, no histogram touches, no
        # allocations per tick (counter-asserted in tests/test_obs.py;
        # tools/bench_obs_overhead.py pins the enabled cost <= 2%).
        # modeled per-chip all-reduce bytes per tick / per verify
        # dispatch (inference/tp.py formula; 0 single-chip) — reported
        # on the tp_allreduce span, in stats(), and tabulated by
        # tools/bench_tp_decode.py
        cfg = getattr(model, "cfg", None)
        if self._tp is not None and cfg is not None:
            self.tp_tick_comm_bytes = self._tp.modeled_tick_comm_bytes(
                cfg.num_layers, cfg.hidden_size, self.slots,
                self.tick_tokens)
            self.tp_verify_comm_bytes = (
                self._tp.modeled_tick_comm_bytes(
                    cfg.num_layers, cfg.hidden_size,
                    self.slots * (self._spec.k + 1), 1)
                if self._spec is not None else 0)
        else:
            self.tp_tick_comm_bytes = 0
            self.tp_verify_comm_bytes = 0

        self._obs = _obs.enabled()
        if self._obs:
            reg = _obs.metrics.registry
            self._g_mesh_devices = reg.gauge(
                "ptpu_engine_mesh_devices",
                "devices in this engine's mesh slice (1 = single-chip; "
                "the tier sum over replicas is total serving chips)")
            self._g_mesh_devices.set(self.tp)
            self._m_ticks = reg.counter(
                "ptpu_engine_ticks_total", "batched decode ticks")
            self._m_admits = reg.counter(
                "ptpu_engine_admits_total", "requests admitted to slots")
            self._m_retires = reg.counter(
                "ptpu_engine_retires_total", "requests retired")
            self._m_cancels = reg.counter(
                "ptpu_engine_cancels_total",
                "requests cancelled (queued or mid-decode; slot and "
                "pages reclaimed)")
            self._m_occupancy = reg.histogram(
                "ptpu_engine_batch_occupancy",
                "live slots per decode tick",
                buckets=(0, 1, 2, 4, 8, 16, 32, 64, 128))
            self._m_queue_wait = reg.histogram(
                "ptpu_engine_queue_wait_ms",
                "submit -> admission start")
            self._m_prefill = reg.histogram(
                "ptpu_engine_prefill_ms",
                "admission program incl. first-token sync")
            self._m_decode = reg.histogram(
                "ptpu_engine_decode_ms", "first token -> retirement")
            self._m_ttft = reg.histogram(
                "ptpu_engine_ttft_ms", "submit -> first token")
            self._m_e2e = reg.histogram(
                "ptpu_engine_e2e_ms", "submit -> retirement")
            if self.paged:
                self._g_pages_free = reg.gauge(
                    "ptpu_engine_pages_free", "KV pool pages free")
                self._g_pages_used = reg.gauge(
                    "ptpu_engine_pages_used", "KV pool pages in use")
                self._g_pages_free.set(self._allocator.free_pages)
                self._g_pages_used.set(self._allocator.used_pages)
                self._m_prefix_hits = reg.counter(
                    "ptpu_engine_prefix_hits_total",
                    "admissions reusing >=1 cached prefix page")
                self._m_prefix_misses = reg.counter(
                    "ptpu_engine_prefix_misses_total",
                    "admissions with no cached prefix page")
            if self._spec is not None:
                self._m_spec_ticks = reg.counter(
                    "ptpu_engine_spec_ticks_total",
                    "draft->verify tick dispatches")
                self._m_spec_drafted = reg.counter(
                    "ptpu_engine_spec_drafted_total",
                    "draft tokens proposed to verify")
                self._m_spec_accepted = reg.counter(
                    "ptpu_engine_spec_accepted_total",
                    "draft tokens accepted by the target")
                self._m_spec_rejected = reg.counter(
                    "ptpu_engine_spec_rejected_total",
                    "draft tokens rejected by the target")
                self._m_spec_per_tick = reg.histogram(
                    "ptpu_engine_spec_accepted_per_tick",
                    "tokens emitted per slot per verify tick "
                    "(accepted prefix + correction)",
                    buckets=tuple(range(0, self._spec.k + 2)))
            # live model efficiency (obs.efficiency — ISSUE 14): the
            # decode tick is bandwidth-bound (tpucost's anchor), so
            # each tick exports modeled HBM bytes over its measured
            # wall time as a fraction of the efficiency chip's
            # bandwidth. The modeled-bytes constants are the SAME
            # analytic bounds the tpucost anchors price (one formula,
            # no drift); they are computed once here so the per-tick
            # cost is one multiply + one gauge set.
            # PER-CHIP geometry: a tp-sharded engine streams 1/tp of
            # the (sharded) params and KV bytes per chip each tick —
            # same convention as the tpucost gpt_decode_tp anchor
            # (replicated norm scales/biases are noise at this scale)
            geom = {"tick_tokens": self.tick_tokens,
                    "param_bytes": _eff.tree_nbytes(
                        (self._params, self._buffers)) // self.tp,
                    "kv_cache_bytes":
                        _eff.tree_nbytes(self._caches) // self.tp}
            if self.paged:
                geom["kv_view_bytes"] = self._kv_view_nbytes() // self.tp
            self._tick_model_bytes = _eff.modeled_tick_bytes(
                "decode_paged" if self.paged else "decode", geom)
            self._verify_model_bytes = (
                _eff.modeled_tick_bytes("verify", geom)
                if self._spec is not None else 0)
            self._eff_chip = _eff.chip_spec()
            self._g_tick_eff = reg.gauge(
                _eff.TICK_EFF_GAUGE,
                "decode tick modeled-bytes/s over measured wall time, "
                "as a fraction of the efficiency chip's HBM bandwidth")

        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="cb-engine")
        self._thread.start()

    # -- public API ------------------------------------------------------
    def submit(self, input_ids, max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None,
               seed: int = 0, request_id: Optional[str] = None,
               progress_cb=None) -> Future:
        """Queue one request; returns a Future resolving to an int64
        [prompt_len + max_new_tokens] array, eos-padded after finish —
        the same shape/padding contract as one row of generate().
        ``request_id`` correlates this request's obs spans (the serving
        layer forwards the X-PTPU-Request-Id header here; absent, one
        is minted when tracing is on) and is the handle ``cancel``
        takes. ``progress_cb(new_tokens)`` — when given — is invoked
        from the engine thread with each newly emitted token block
        (the first token at admission, then per tick): the streaming
        side-channel the serving layer's incremental ``/generate`` and
        the router's token journal ride. It must be fast and must not
        raise; a raising callback is dropped, never the engine loop."""
        _resil.maybe_inject("serve_backend")   # dead-backend fault site
        prompt = np.asarray(input_ids).astype(np.int64).reshape(-1)
        P = prompt.shape[0]
        if P < 1:
            raise ValueError("empty prompt")
        if P > self.prefill_buckets[-1]:
            raise ValueError(
                f"prompt length {P} exceeds the largest prefill bucket "
                f"{self.prefill_buckets[-1]}")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        # worst-case decode overshoot is one tick past the budget (a
        # row is only retired at a tick boundary; a speculative tick
        # also WRITES cache rows up to k past the current position)
        worst = P + max_new_tokens + self._overshoot
        if worst > self.max_len:
            raise ValueError(
                f"prompt ({P}) + max_new_tokens ({max_new_tokens}) + "
                f"tick overshoot ({self._overshoot}) exceeds the "
                f"engine cache length {self.max_len}")
        # Paged engines need no extra static rejection here: worst <=
        # max_len (above) bounds a request at pages_per_slot pages, and
        # the constructor guarantees num_pages >= pages_per_slot — so
        # any request passing the view-length check CAN fit once enough
        # pages free up; transient shortage queues, and sheds as
        # cache_exhausted below when the queue is also full.
        req = _Request(prompt, int(max_new_tokens),
                       None if eos_token_id is None else int(eos_token_id),
                       int(seed))
        req.progress_cb = progress_cb
        if self._obs:
            req.rid = (str(request_id) if request_id
                       else uuid.uuid4().hex[:16])
            req.t_submit = time.perf_counter()
        elif request_id:
            req.rid = str(request_id)
        with self._cv:
            if self._broken is not None:
                raise RuntimeError("engine is broken") from self._broken
            if self._stop_flag:
                # after stop() no thread will ever drain the queue — a
                # silently-enqueued request would hang its caller forever
                raise RuntimeError("engine stopped")
            if len(self._queue) >= self.max_queue:
                if self.paged and self._pool_is_binding_locked():
                    # the queue backed up because admission is waiting
                    # on PAGES (a slot was free but the pool could not
                    # cover the head request) — shed with the truthful
                    # reason so operators size the pool, not the fleet
                    raise CacheExhausted(
                        len(self._queue), self.max_queue,
                        self._allocator.free_pages, self.num_pages)
                raise EngineOverloaded(len(self._queue), self.max_queue)
            self._queue.append(req)
            self._cv.notify()
        return req.future

    def cancel(self, request_id: Optional[str]) -> bool:
        """Cancel the in-flight request carrying ``request_id`` (the id
        given to submit). Returns True when a request was found. A
        QUEUED request resolves immediately (its future raises
        :class:`RequestCancelled`, zero tokens); an ADMITTED one is
        flagged and retired by the engine thread at the next tick
        boundary — the slot frees, its KV pages decref (leak-free),
        and the future raises :class:`RequestCancelled` with the
        partial result attached (``_ptpu_gen_info``: tokens_generated
        + partial_tokens). Idempotent: a second cancel of the same id
        returns False once the first resolved it."""
        if not request_id:
            return False
        rid = str(request_id)
        victim = None
        with self._cv:
            for i, req in enumerate(self._queue):
                if req.rid == rid:
                    victim = self._queue.pop(i)
                    break
            if victim is None:
                for s in self._slots:
                    if (s.req is not None and s.req.rid == rid
                            and not s.req.cancelled):
                        s.req.cancelled = True
                        self._cv.notify()
                        return True
                return False
            self.cancelled += 1
        # queued request: resolve outside the lock (future callbacks
        # must never run under the engine lock)
        victim.future._ptpu_gen_info = {"tokens_generated": 0,
                                        "partial_tokens": []}
        if self._obs:
            self._m_cancels.inc()
        if not victim.future.done():
            victim.future.set_exception(RequestCancelled(rid, 0))
        return True

    def _notify_progress(self, req: _Request, toks) -> None:
        """Deliver newly emitted tokens to the request's progress
        callback (streaming side-channel). Runs on the engine thread:
        a raising callback is dropped so it can never take the loop —
        and with it every other slot — down."""
        cb = req.progress_cb
        if cb is None:
            return
        try:
            cb([int(t) for t in toks])
        except Exception:   # noqa: BLE001 — a broken stream is the
            req.progress_cb = None   # caller's problem, not the loop's

    def _pool_is_binding_locked(self) -> bool:
        """Is the page pool (not slots / request rate) what is blocking
        the queue? True once an actual admission attempt failed on
        pages, or — to close the window before the engine thread gets
        to try — when a slot is free but the head request's worst-case
        pages exceed everything the pool could produce (free pages plus
        every trie-only page eviction could reclaim). Callers hold
        self._cv."""
        if self._pool_blocked:
            return True
        if not self._queue or not any(s.free for s in self._slots):
            return False
        head = self._queue[0]
        need = _pages_needed(head.prompt.shape[0] + head.max_new_tokens
                             + self._overshoot, self.page_size)
        return need > (self._allocator.free_pages
                       + self._trie.reclaimable())

    def generate(self, input_ids, max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None, seed: int = 0,
                 timeout: Optional[float] = None) -> np.ndarray:
        """Blocking convenience wrapper over submit()."""
        return self.submit(input_ids, max_new_tokens, eos_token_id,
                           seed).result(timeout)

    def _kv_view_nbytes(self) -> int:
        """Bytes of the gathered [N, pages_per_slot * page_size] KV
        view one PAGED micro-step materializes (all layers, k + v) —
        the geometry input the paged analytic HBM bound prices
        alongside the pool itself (compilation/sites.py exports the
        same number on the gpt_decode_paged registry geometry)."""
        total = 0
        if isinstance(self._caches, tuple):
            # scan-stacked (k_stack, v_stack): leaves carry a leading
            # layer axis, pages live on axis 1 — every layer gathers
            # its own view
            for half in self._caches:
                for leaf in half.values():
                    L, NP = leaf.shape[0], leaf.shape[1]
                    per_page = _eff.tree_nbytes(leaf) // (L * NP)
                    total += (per_page * self.pages_per_slot
                              * self.slots * L)
            return total
        for kc, vc in self._caches:
            for half in (kc, vc):
                for leaf in half.values():
                    per_page = _eff.tree_nbytes(leaf) // leaf.shape[0]
                    total += per_page * self.pages_per_slot * self.slots
        return total

    def stats(self) -> dict:
        with self._cv:
            active = sum(1 for s in self._slots if not s.free)
            queued = len(self._queue)
            cancelled = self.cancelled
        out = {"slots": self.slots, "active": active,
               "free": self.slots - active, "queued": queued,
               "max_queue": self.max_queue, "ticks": self.ticks,
               "admitted": self.admitted, "completed": self.completed,
               "cancelled": cancelled,
               "compiled_programs": self.compiled_program_count,
               "tick_tokens": self.tick_tokens,
               "prefill_buckets": list(self.prefill_buckets),
               "max_len": self.max_len,
               "cache_dtype": self.cache_dtype,
               "paged": self.paged,
               "speculative": (self._spec.kind if self._spec else None),
               # tensor-parallel slice geometry (ISSUE 20): tp == 1 is
               # the single-chip engine; fused_knobs_disabled_tp lists
               # env-enabled Pallas knobs forced off under the sharded
               # mesh (the loud fallback's machine-readable half)
               "tp": self.tp,
               "mesh_devices": self.tp,
               "fused_knobs_disabled_tp":
                   list(self.fused_knobs_disabled_tp),
               # obs.efficiency: last tick's modeled-bytes/s as a
               # fraction of the efficiency chip's HBM bandwidth
               # (0.0 before the first tick or with obs disabled)
               "tick_model_eff": round(self.last_tick_model_eff, 6)}
        if self._tp is not None:
            out["mesh"] = self._tp.describe()
            out["tp_comm_precision"] = (self._tp.comm_precision
                                        or "fp32")
            out["tp_tick_comm_bytes"] = self.tp_tick_comm_bytes
        if self._spec is not None:
            drafted = self.tokens_drafted
            out.update({
                "spec_k": self._spec.k,
                "spec_ticks": self.spec_ticks,
                "tokens_drafted": drafted,
                "tokens_accepted": self.tokens_accepted,
                "tokens_rejected": self.tokens_rejected,
                "acceptance_rate": round(
                    self.tokens_accepted / drafted, 4) if drafted
                else 0.0,
                # tokens emitted per SLOT per verify forward — the
                # multi-token-tick number (1.0 = no better than the
                # plain one-token-per-forward regime)
                "accepted_tokens_per_tick": round(
                    self.spec_tokens_emitted / self.spec_slot_ticks, 4)
                if self.spec_slot_ticks else 0.0,
            })
        if self.paged:
            free_p = self._allocator.free_pages
            used_p = self._allocator.used_pages
            lookups = self.prefix_hits + self.prefix_misses
            out.update({
                "page_size": self.page_size,
                "pages_total": self.num_pages,
                "pages_free": free_p,
                "pages_used": used_p,
                "pages_cached_prefix": self._trie.pages_cached,
                "page_utilization": round(used_p / self.num_pages, 4),
                "prefix_hits": self.prefix_hits,
                "prefix_misses": self.prefix_misses,
                "prefix_hit_rate": round(self.prefix_hits / lookups, 4)
                if lookups else 0.0,
                "prefix_tokens_saved": self.prefix_tokens_saved,
                "prefill_tokens": self.prefill_tokens,
            })
            # cross-process prefix identity for the router's affinity
            # scoring (ISSUE 16): chained crc32 per cached trie node,
            # bounded. The walk races the engine loop's inserts by
            # design — a torn read only costs one poll's freshness,
            # never correctness (hashes are compared, not dereferenced)
            try:
                out["prefix_fingerprints"] = self._trie.fingerprints()
            except RuntimeError:
                out["prefix_fingerprints"] = []
        return out

    @property
    def compiled_program_count(self) -> int:
        """How many times XLA traced an engine program — constant after
        warmup is the no-recompile serving guarantee. Includes the
        draft proposer's programs (a re-tracing draft would pay the
        same per-request compile tax as a re-tracing target)."""
        return self._trace_count + (
            self._proposer._trace_count
            if getattr(self._proposer, "kind", None) == "draft" else 0)

    @property
    def warm(self) -> bool:
        """True once the batched decode program is actually COMPILED —
        either warmup() finished (compiled or loaded from the
        executable store) or the first lazy tick completed. The raw jit
        wrapper existing is not enough: readiness claimed mid-compile
        would stall the first routed request, the exact lie the
        serving layer's warming->ready /healthz transition exists to
        prevent."""
        return self._warmed or self.ticks > 0

    def _tp_scope(self):
        """The trace/dispatch scope for this engine's programs: under
        tp > 1 it thread-locally activates the slice mesh (so
        mp_layers' constraints and the comm-precision routing take
        effect at trace time) — a no-op context single-chip. Wraps
        every site that may TRACE an engine program (warmup and the
        lazy first call of each dispatch path)."""
        return (self._tp.activate() if self._tp is not None
                else contextlib.nullcontext())

    # -- AOT warmup ------------------------------------------------------
    def _static_key(self) -> str:
        """Trace-time constants of this engine's programs that never
        appear in an argument aval — part of the executable-store key
        (two engines over the same weights but different sampling
        config must not collide)."""
        paged = ((self.page_size, self.num_pages, self.pages_per_slot)
                 if self.paged else None)
        spec = ((self._spec.kind, self._spec.k)
                if self._spec is not None else None)
        # kernel-fusion knobs are trace-time constants too: a cached
        # executable traced with the unfused chain must not be reused
        # when the fused kernels are toggled on (ISSUE 19)
        from ..nn.functional.flash_attention import (_fused_cache_write_on,
                                                     _mega_decode_on)
        # evaluated under the engine's mesh scope: a tp engine's knobs
        # read as OFF (the loud TP fallback), so its cache key matches
        # what its traces actually contain — a single-chip fused
        # executable can never be loaded for the sharded programs
        with self._tp_scope():
            fusion = (_fused_cache_write_on(), _mega_decode_on())
        tp_key = ((self.tp, self._tp.comm_precision or "fp32")
                  if self._tp is not None else None)
        return repr((type(self.model).__name__, self._sampling,
                     self.tick_tokens, self.max_len, self.cache_dtype,
                     paged, spec, fusion, tp_key))

    def _decode_example_args(self) -> tuple:
        N = self.slots
        if self.paged:
            return (self._params, self._buffers, self._caches,
                    np.zeros((N, self.pages_per_slot), np.int32),
                    np.zeros(N, np.int32), np.zeros(N, np.int32),
                    np.ones(N, bool), np.full(N, -1, np.int32),
                    np.zeros((N, 2), np.uint32))
        return (self._params, self._buffers, self._caches,
                np.zeros(N, np.int32), np.zeros(N, np.int32),
                np.ones(N, bool), np.full(N, -1, np.int32),
                np.zeros((N, 2), np.uint32))

    def _admit_example_args(self, bucket: int) -> tuple:
        if self.paged:
            return (self._params, self._buffers,
                    np.zeros((1, bucket), np.int64), np.int32(0),
                    np.int32(0), np.int32(bucket),
                    np.zeros(2, np.uint32), self._caches,
                    np.zeros((1, self.pages_per_slot), np.int32))
        return (self._params, self._buffers,
                np.zeros((1, bucket), np.int64), np.int32(0),
                np.zeros(2, np.uint32), self._caches, np.int32(0))

    def _copy_example_args(self) -> tuple:
        return (self._caches, np.int32(0), np.int32(0))

    def _verify_example_args(self) -> tuple:
        N, K = self.slots, self._spec.k
        head = (self._params, self._buffers, self._caches)
        if self.paged:
            head += (np.zeros((N, self.pages_per_slot), np.int32),)
        return head + (np.zeros(N, np.int32), np.zeros(N, np.int32),
                       np.ones(N, bool), np.zeros((N, K), np.int32),
                       np.zeros(N, np.int32))

    def warmup(self, buckets: Optional[tuple] = None, store=None) -> list:
        """Compile-or-load THIS engine's programs ahead of traffic: the
        batched decode tick plus one admission program per prefill
        bucket, through the persistent executable store
        (paddle_tpu.compilation) — a store-warm fresh process reaches
        its first token without XLA compiling anything. Also primes the
        tiny eager helper ops the admission path runs per request
        (PRNGKey construction). Returns the compile-log records."""
        from ..compilation import log as _clog
        from ..compilation import prime_helper_ops
        from ..compilation.store import AotProgram, aot_compile
        prime_helper_ops()
        static = self._static_key()
        with self._warmup_lock:
            return self._warmup_locked(buckets, store, static,
                                       AotProgram, aot_compile, _clog)

    def _warmup_locked(self, buckets, store, static, AotProgram,
                       aot_compile, _clog) -> list:
        recs = []
        # every TARGET program traces inside the engine's mesh scope
        # (sharded constraints + comm-precision routing are trace-time);
        # the draft proposer warms OUTSIDE it below — the draft stays a
        # single-device replicated model on purpose (its k-token
        # proposals are checked by the sharded verify, never trusted)
        with self._tp_scope():
            if not isinstance(self._decode_prog, AotProgram):
                rec: dict = {"site": "engine_decode"}
                self._decode_prog = aot_compile(
                    "engine_decode", self._get_decode_prog(),
                    self._decode_example_args(), store=store,
                    log_record=rec, static_key=static)
                recs.append(_clog.record(rec))
            for bucket in (buckets if buckets is not None
                           else self.prefill_buckets):
                bucket = self._bucket_for(int(bucket))
                if isinstance(self._admit_progs.get(bucket), AotProgram):
                    continue
                rec = {"site": f"engine_admit_b{bucket}"}
                self._admit_progs[bucket] = aot_compile(
                    f"engine_admit_b{bucket}",
                    self._get_admit_prog(bucket),
                    self._admit_example_args(bucket), store=store,
                    log_record=rec, static_key=static)
                recs.append(_clog.record(rec))
            if self.paged and not isinstance(self._copy_prog,
                                             AotProgram):
                rec = {"site": "engine_copy_page"}
                self._copy_prog = aot_compile(
                    "engine_copy_page", self._get_copy_page_prog(),
                    self._copy_example_args(), store=store,
                    log_record=rec, static_key=static)
                recs.append(_clog.record(rec))
            if self._spec is not None and not isinstance(
                    self._verify_prog, AotProgram):
                rec = {"site": "engine_verify"}
                self._verify_prog = aot_compile(
                    "engine_verify", self._get_verify_prog(),
                    self._verify_example_args(), store=store,
                    log_record=rec, static_key=static)
                recs.append(_clog.record(rec))
        if self._spec is not None and self._spec.kind == "draft":
            recs.extend(self._proposer.warmup(
                self.prefill_buckets, store=store, static_key=static))
        self._warmed = True
        return recs

    def stop(self):
        with self._cv:
            self._stop_flag = True
            self._cv.notify()
        self._thread.join(timeout=30)
        self._fail_all(RuntimeError("engine stopped"))

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.stop()
        return False

    # -- compiled programs ----------------------------------------------
    def _bucket_for(self, P: int) -> int:
        for b in self.prefill_buckets:
            if P <= b:
                return b
        raise ValueError(f"prompt length {P} exceeds largest bucket")

    def _get_admit_prog(self, bucket: int):
        prog = self._admit_progs.get(bucket)
        if prog is not None:
            return prog
        if self.paged:
            return self._get_paged_admit_prog(bucket)
        model, engine = self.model, self
        do_sample, temperature, top_k, top_p = self._sampling

        def admit(params, buffers, ids, last_idx, key, caches, slot):
            engine._trace_count += 1      # fires at trace time only
            # fresh zeroed cache built INSIDE the program: inserting its
            # full row range below is what resets a retired slot's stale
            # rows (incl. int8 scales) before re-admission
            temp = model.new_cache(1, engine.max_len, engine.cache_dtype)
            (logits, temp), _ = functional_call(
                model, params, buffers, ids, temp, jnp.int32(0),
                training=False)
            last = lax.dynamic_index_in_dim(logits, last_idx, axis=1,
                                            keepdims=False)   # [1, V]
            tok0 = _select_token(last, key, do_sample, temperature,
                                 top_k, top_p)

            def insert(slot_leaf, temp_leaf):
                # batch axis = the one where the N-slot leaf and the
                # batch-1 temp leaf disagree (works for unrolled
                # [B, L, ...] and scanned [layers, B, L, ...] layouts)
                ax = next(i for i, (a, c) in enumerate(
                    zip(slot_leaf.shape, temp_leaf.shape)) if a != c)
                start = [0] * slot_leaf.ndim
                start[ax] = slot
                return lax.dynamic_update_slice(
                    slot_leaf, temp_leaf.astype(slot_leaf.dtype),
                    tuple(start))

            caches = jax.tree_util.tree_map(insert, caches, temp)
            return tok0[0].astype(jnp.int32), caches

        prog = jax.jit(admit, donate_argnums=(5,))
        self._admit_progs[bucket] = prog
        return prog

    def _get_paged_admit_prog(self, bucket: int):
        """ONE jitted program per suffix bucket: prefill the request's
        un-cached suffix (tokens [M, M+wlen), right-padded to `bucket`)
        straight INTO its block-table pages. The suffix attends over
        the slot's gathered pages — shared prefix pages included, which
        is exactly why matched prefixes never re-prefill — and the
        write mask (wlen) keeps bucket padding out of the pool. M,
        wlen, last_idx and the table are traced values: prompt-length
        drift, prefix-hit depth and page placement never retrace."""
        model, engine = self.model, self
        do_sample, temperature, top_k, top_p = self._sampling

        def admit(params, buffers, ids, last_idx, m_pos, wlen, key,
                  caches, bt_row):
            engine._trace_count += 1      # fires at trace time only
            cm = _attach_page_meta(caches, bt=bt_row, wlen=wlen)
            (logits, cm), _ = functional_call(
                model, params, buffers, ids, cm, m_pos, training=False)
            caches = _strip_page_meta(cm)
            last = lax.dynamic_index_in_dim(logits, last_idx, axis=1,
                                            keepdims=False)   # [1, V]
            tok0 = _select_token(last, key, do_sample, temperature,
                                 top_k, top_p)
            return tok0[0].astype(jnp.int32), caches

        prog = jax.jit(admit, donate_argnums=(7,))
        self._admit_progs[bucket] = prog
        return prog

    def _get_copy_page_prog(self):
        """Copy-on-write: duplicate one physical page (every layer's
        k/v pool leaves, int8 scales included) into a freshly allocated
        page — the only write path that may target content shared with
        other requests, and it writes to the COPY. Gather + one-hot
        select, scatter-free like everything else."""
        if self._copy_prog is not None:
            return self._copy_prog
        engine = self
        # trace-time constant: scan-stacked pools put the page axis at
        # 1 (behind the layer axis), unrolled pools at 0
        stacked = isinstance(self._caches, tuple)

        def copy_page(caches, src, dst):
            engine._trace_count += 1      # fires at trace time only

            def cp(leaf):
                ax = 1 if stacked else 0
                row = jnp.take(leaf, src[None], axis=ax)  # page row
                hit = jnp.arange(leaf.shape[ax]) == dst
                shape = [1] * leaf.ndim
                shape[ax] = -1
                return jnp.where(hit.reshape(shape), row, leaf)

            return jax.tree_util.tree_map(cp, caches)

        self._copy_prog = jax.jit(copy_page, donate_argnums=(0,))
        return self._copy_prog

    def _get_decode_prog(self):
        if self._decode_prog is not None:
            return self._decode_prog
        if self.paged:
            return self._get_paged_decode_prog()
        model, engine = self.model, self
        do_sample, temperature, top_k, top_p = self._sampling
        T = self.tick_tokens

        def decode_tick(params, buffers, caches, tok, pos, live,
                        eos_ids, keys):
            engine._trace_count += 1      # fires at trace time only

            def body(carry, _):
                tok, caches, pos, live = carry
                (logits, caches), _ = functional_call(
                    model, params, buffers, tok[:, None], caches, pos,
                    training=False)
                last = logits[:, -1, :]
                if do_sample:
                    subs = jax.vmap(jax.random.fold_in)(keys, pos)
                    nxt = jax.vmap(
                        lambda lg, k: _select_token(
                            lg[None], k, True, temperature, top_k,
                            top_p)[0])(last, subs)
                else:
                    nxt = jnp.argmax(last, axis=-1)
                nxt = jnp.where(live, nxt.astype(jnp.int32),
                                jnp.int32(0))
                new_live = live & (nxt != eos_ids)
                pos = pos + live.astype(jnp.int32)
                tok = jnp.where(live, nxt, tok)
                return (tok, caches, pos, new_live), nxt

            (tok, caches, pos, live), toks = lax.scan(
                body, (tok, caches, pos, live), None, length=T)
            return toks.T, caches    # toks: [N, T]

        self._decode_prog = jax.jit(decode_tick, donate_argnums=(2,))
        return self._decode_prog

    def _get_paged_decode_prog(self):
        """The paged batched decode tick: identical token semantics to
        the slot-cache tick (same scan, same masks, same sampling) —
        the only difference is that each micro-step's cached_attention
        GATHERS the slot's pages through the block table and one-hot
        writes into the slot's current page, write-gated on the live
        mask (a dead slot's table may point at pages since reallocated
        to another request). Block tables ride as a [N, pages_per_slot]
        int32 argument, so page placement drift never retraces."""
        model, engine = self.model, self
        do_sample, temperature, top_k, top_p = self._sampling
        T = self.tick_tokens

        def decode_tick(params, buffers, caches, bt, tok, pos, live,
                        eos_ids, keys):
            engine._trace_count += 1      # fires at trace time only

            def body(carry, _):
                tok, caches, pos, live = carry
                cm = _attach_page_meta(caches, bt=bt, live=live)
                (logits, cm), _ = functional_call(
                    model, params, buffers, tok[:, None], cm, pos,
                    training=False)
                caches = _strip_page_meta(cm)
                last = logits[:, -1, :]
                if do_sample:
                    subs = jax.vmap(jax.random.fold_in)(keys, pos)
                    nxt = jax.vmap(
                        lambda lg, k: _select_token(
                            lg[None], k, True, temperature, top_k,
                            top_p)[0])(last, subs)
                else:
                    nxt = jnp.argmax(last, axis=-1)
                nxt = jnp.where(live, nxt.astype(jnp.int32),
                                jnp.int32(0))
                new_live = live & (nxt != eos_ids)
                pos = pos + live.astype(jnp.int32)
                tok = jnp.where(live, nxt, tok)
                return (tok, caches, pos, new_live), nxt

            (tok, caches, pos, live), toks = lax.scan(
                body, (tok, caches, pos, live), None, length=T)
            return toks.T, caches    # toks: [N, T]

        self._decode_prog = jax.jit(decode_tick, donate_argnums=(2,))
        return self._decode_prog

    def _get_verify_prog(self):
        """The batched verify-k program (speculative.py builds it; the
        trace hook is this engine's recompile counter, same contract as
        every other engine program)."""
        if self._verify_prog is not None:
            return self._verify_prog
        from .speculative import make_verify_program
        engine = self

        def hook():
            engine._trace_count += 1      # fires at trace time only

        self._verify_prog = make_verify_program(
            self.model, self._spec.k, self.paged, trace_hook=hook)
        return self._verify_prog

    # -- engine loop -----------------------------------------------------
    def _loop(self):
        while True:
            with self._cv:
                while (not self._stop_flag and not self._queue
                       and all(s.free for s in self._slots)):
                    self._cv.wait(timeout=1.0)
                if self._stop_flag:
                    return
            try:
                self._sweep_cancelled()
                self._admit_ready()
                if any(not s.free for s in self._slots):
                    self._tick()
                else:
                    with self._cv:
                        if self._queue and self._pool_blocked:
                            # nothing active to tick (and so nothing
                            # retiring to free pages) while the head
                            # request waits on the pool: only trie
                            # eviction can unblock, and _admit_paged
                            # already tried it — yield briefly instead
                            # of spinning the admission path hot
                            self._cv.wait(timeout=0.05)
            except BaseException as e:   # noqa: BLE001 — fail loudly
                with self._cv:
                    self._broken = e
                self._fail_all(e)
                return

    def _sweep_cancelled(self):
        """Retire every slot whose request was cancel()led since the
        last tick boundary — the slot frees and (paged) its pages
        decref before the next admission pass can want them."""
        with self._cv:
            idxs = [i for i, s in enumerate(self._slots)
                    if s.req is not None and s.req.cancelled]
        for i in idxs:
            self._retire(i)

    def _fail_all(self, exc: BaseException):
        with self._cv:
            pending = [(req, []) for req in self._queue]
            self._queue.clear()
            actives = [s for s in self._slots if not s.free]
            for s in actives:
                req, s.req = s.req, None
                s.alive = False
                pending.append((req, list(s.emitted)))
        for req, emitted in pending:
            if req is None or req.future.done():
                continue
            # surface the partial result on the error path too: the
            # router's journal reconciles against this engine truth
            # instead of silently losing whatever was generated
            req.future._ptpu_gen_info = {
                "tokens_generated": len(emitted),
                "partial_tokens": [int(t) for t in emitted]}
            req.future.set_exception(exc)

    def _admit_ready(self):
        while True:
            with self._cv:
                slot_idx = next((i for i, s in enumerate(self._slots)
                                 if s.free), None)
                if slot_idx is None or not self._queue:
                    return
                req = self._queue.pop(0)
            if not self._admit(req, slot_idx):
                # paged pool could not cover the head request right
                # now: keep FIFO order (put it back at the front) and
                # stop admitting — a retire or eviction re-opens the
                # path; admitting AROUND the head would starve large
                # requests forever under short-request pressure
                with self._cv:
                    self._queue.insert(0, req)
                return

    def _admit(self, req: _Request, b: int) -> bool:
        """Admit one request into slot ``b``; False when the paged pool
        cannot cover it right now (caller re-queues, nothing changed)."""
        P = req.prompt.shape[0]
        key = np.asarray(jax.random.PRNGKey(req.seed), np.uint32)
        t_adm = time.perf_counter() if self._obs else 0.0
        if self.paged:
            res = self._admit_paged(req, b, key)
            if res is None:
                return False
            tok0, bucket = res
        else:
            bucket = self._bucket_for(P)
            ids = np.zeros((1, bucket), np.int64)
            ids[0, :P] = req.prompt
            prog = self._get_admit_prog(bucket)
            with self._tp_scope():     # lazy path may trace here
                tok0_dev, self._caches = prog(
                    self._params, self._buffers, ids, np.int32(P - 1),
                    key, self._caches, np.int32(b))
            tok0 = int(tok0_dev)       # first-token host sync
            self.prefill_tokens += P
        if getattr(self._proposer, "kind", None) == "draft":
            # prefill the draft model's own cache row for this slot —
            # the prompt is the only context the draft ever needs ahead
            # of time (each tick's [prev, tok] sync block covers the
            # rest, speculative.py module docstring)
            self._proposer.admit(b, req.prompt, self._bucket_for(P))
        slot = self._slots[b]
        slot.req = req
        slot.pos = P
        slot.tok = tok0
        slot.key = key
        slot.emitted = [tok0]
        slot.remaining = req.max_new_tokens - 1
        slot.alive = (req.eos_token_id is None
                      or tok0 != req.eos_token_id)
        self.admitted += 1
        self._notify_progress(req, [tok0])
        if self._obs:
            # the request's contiguous phase timeline: queue-wait
            # (submit -> admission), prefill (admission program + the
            # first-token sync), then decode (below, -> retirement);
            # their sum is the engine-side end-to-end latency
            now = time.perf_counter()
            slot.t_dec0 = now
            self._m_admits.inc()
            self._m_queue_wait.observe((t_adm - req.t_submit) * 1e3)
            self._m_prefill.observe((now - t_adm) * 1e3)
            self._m_ttft.observe((now - req.t_submit) * 1e3)
            _obs.record_span("engine.queue_wait", req.t_submit, t_adm,
                             cat="engine", request_id=req.rid)
            # no separate TTFT span: its interval is exactly
            # queue_wait + prefill (a viewer derives it; the
            # histogram above carries the aggregate) — one less ring
            # event per request keeps the postmortem window long
            _obs.record_span("engine.prefill", t_adm, now, cat="engine",
                             request_id=req.rid, bucket=bucket,
                             prompt_len=P,
                             ttft_ms=round((now - req.t_submit) * 1e3,
                                           3))
        if slot.remaining <= 0 or not slot.alive:
            self._retire(b)
        return True

    def _admit_paged(self, req: _Request, b: int, key) -> Optional[tuple]:
        """Paged admission: prefix-trie match, page allocation (with
        LRU eviction under pressure), optional tail-page copy-on-write,
        then ONE suffix-prefill program that writes the un-cached
        tokens straight into the slot's pages. Returns (tok0, bucket)
        or None when the pool cannot cover the request yet (pool state
        is rolled back exactly)."""
        prompt, ps = req.prompt, self.page_size
        P = prompt.shape[0]
        n_complete = P // ps          # prompt pages shareable read-only
        page_keys = [tuple(int(t) for t in prompt[j * ps:(j + 1) * ps])
                     for j in range(n_complete)]
        matched = self._trie.match(page_keys) if self.prefix_cache \
            else []
        m = len(matched)
        cow_src = None
        if n_complete and m == n_complete and P % ps == 0:
            # every prompt page is cached: skip prefill entirely except
            # the LAST token (its logits seed decode) — copy-on-write
            # the tail page so that one recompute-write (and nothing
            # else, ever) lands in private memory
            cow_src = matched[-1]
            shared = matched[:-1]
            M = P - 1
        else:
            shared = matched
            M = m * ps
        total = _pages_needed(P + req.max_new_tokens + self._overshoot,
                              ps)
        # incref BEFORE any eviction below so matched pages are pinned
        self._allocator.incref(shared)
        need_priv = total - len(shared)
        priv = self._allocator.alloc(need_priv)
        if priv is None:
            self._trie.evict(need_priv - self._allocator.free_pages)
            priv = self._allocator.alloc(need_priv)
        if priv is None:
            self._allocator.decref(shared)   # exact rollback
            self._pool_blocked = True
            return None
        self._pool_blocked = False
        pages = list(shared) + priv          # logical page j = pages[j]
        bt_row = np.zeros(self.pages_per_slot, np.int32)
        bt_row[:len(pages)] = pages
        self._block_tables[b] = bt_row
        if cow_src is not None:
            with self._tp_scope():     # lazy path may trace here
                self._caches = self._get_copy_page_prog()(
                    self._caches, np.int32(cow_src),
                    np.int32(pages[n_complete - 1]))
        suffix = prompt[M:]
        S = suffix.shape[0]
        bucket = self._bucket_for(S)
        ids = np.zeros((1, bucket), np.int64)
        ids[0, :S] = suffix
        prog = self._get_admit_prog(bucket)
        with self._tp_scope():         # lazy path may trace here
            tok0_dev, self._caches = prog(
                self._params, self._buffers, ids, np.int32(S - 1),
                np.int32(M), np.int32(S), key, self._caches,
                bt_row[None])
        tok0 = int(tok0_dev)       # first-token host sync
        self._slots[b].pages = pages
        if self.prefix_cache:
            # freshly computed complete pages become shareable; keys
            # already cached are untouched (the COW copy never enters)
            self._trie.insert(page_keys, pages[:n_complete])
        if m:
            self.prefix_hits += 1
            self.prefix_tokens_saved += M
        else:
            self.prefix_misses += 1
        self.prefill_tokens += S
        if self._obs:
            (self._m_prefix_hits if m else self._m_prefix_misses).inc()
            self._g_pages_free.set(self._allocator.free_pages)
            self._g_pages_used.set(self._allocator.used_pages)
        return tok0, bucket

    def _tick(self):
        """One tick: plain decode, or — speculative — draft -> verify.
        The swap is per tick, not per engine: an n-gram engine whose
        contexts have nothing to match anywhere falls back to the plain
        tick (tick_tokens per dispatch) instead of paying a verify
        forward for one guaranteed token per slot."""
        # straggler fault site (latency injection, not death): wedges
        # THIS loop — the process stays alive, /healthz keeps
        # answering, only token progress stops. The router's hedged
        # decode is the recovery path under test.
        _resil.maybe_inject("replica_stall")
        if self._spec is None:
            self._tick_decode()
            return
        props, dlen = self._propose_all()
        if dlen.any():
            self._tick_verify(props, dlen)
        else:
            self._tick_decode()

    def _prev_token(self, s: "_Slot") -> int:
        """True token at index ``s.pos - 1`` (the draft sync block's
        first element). pos >= prompt_len >= 1 always, so it exists."""
        P = s.req.prompt.shape[0]
        j = s.pos - 1
        return int(s.req.prompt[j]) if j < P else s.emitted[j - P]

    def _propose_all(self):
        """(props [N, k] int32, dlen [N] int32) for every busy slot —
        ONE draft-model dispatch, or per-slot host n-gram lookups."""
        N, K = self.slots, self._spec.k
        props = np.zeros((N, K), np.int32)
        dlen = np.zeros(N, np.int32)
        if self._proposer.kind == "draft":
            prev = np.zeros(N, np.int32)
            tok = np.zeros(N, np.int32)
            pos = np.zeros(N, np.int32)
            busy = False
            for i, s in enumerate(self._slots):
                if s.free:
                    continue
                prev[i] = self._prev_token(s)
                tok[i] = s.tok
                pos[i] = s.pos
                dlen[i] = K
                busy = True
            if busy:
                props = self._proposer.propose(prev, tok, pos)
            return props, dlen
        for i, s in enumerate(self._slots):
            if s.free:
                continue
            ctx = np.concatenate([s.req.prompt,
                                  np.asarray(s.emitted, np.int64)])
            p, n = self._proposer.propose(ctx)
            props[i] = p
            dlen[i] = n
        return props, dlen

    def _tick_verify(self, props, dlen):
        """One draft->verify tick: ONE target forward scores all k+1
        positions for every slot; the host consumes the accepted prefix
        plus the correction token per row (1..k+1 tokens each — the
        multi-token tick). Every consumed token is the TARGET's argmax,
        so this path is bitwise token-identical to plain decode."""
        N = self.slots
        tok = np.zeros(N, np.int32)
        pos = np.zeros(N, np.int32)
        live = np.zeros(N, bool)
        n_live = 0
        for i, s in enumerate(self._slots):
            if s.free:
                continue
            tok[i] = s.tok
            pos[i] = s.pos
            if s.alive and s.remaining > 0:
                live[i] = True
                n_live += 1
        prog = self._get_verify_prog()
        t_tick = time.perf_counter() if self._obs else 0.0
        with self._tp_scope():         # lazy path may trace here
            if self.paged:
                toks_dev, acc_dev, self._caches = prog(
                    self._params, self._buffers, self._caches,
                    self._block_tables, tok, pos, live, props, dlen)
            else:
                toks_dev, acc_dev, self._caches = prog(
                    self._params, self._buffers, self._caches, tok, pos,
                    live, props, dlen)
        toks = np.asarray(toks_dev)       # the ONE host sync per tick
        n_acc = np.asarray(acc_dev)
        self.ticks += 1
        self.spec_ticks += 1
        if self._obs:
            now = time.perf_counter()
            self._m_ticks.inc()
            self._m_spec_ticks.inc()
            self._m_occupancy.observe(n_live)
            if now > t_tick:
                # the verify dispatch moves the single-pass k-token
                # bound's bytes, not tick_tokens passes
                self.last_tick_model_eff = _eff.model_bandwidth_eff(
                    self._verify_model_bytes, now - t_tick,
                    self._eff_chip)
                self._g_tick_eff.set(self.last_tick_model_eff)
            _obs.record_span("engine.tick", t_tick, now, cat="engine",
                             active=n_live, tick=self.ticks, spec=True)
            if self._tp is not None:
                # the per-block all-reduces run INSIDE the verify
                # program; this span brackets the dispatch that moved
                # them and carries the modeled per-chip wire bytes
                _obs.record_span(
                    "engine.tp_allreduce", t_tick, now, cat="engine",
                    tp=self.tp, tick=self.ticks,
                    modeled_comm_bytes=self.tp_verify_comm_bytes)
        for i, s in enumerate(self._slots):
            if s.free or not live[i]:
                continue
            drafted, accepted = int(dlen[i]), int(n_acc[i])
            self.tokens_drafted += drafted
            self.tokens_accepted += accepted
            self.tokens_rejected += drafted - accepted
            s.req.drafted += drafted
            s.req.accepted += accepted
            n = 0
            for t in range(accepted + 1):
                if s.remaining <= 0 or not s.alive:
                    break
                token = int(toks[i, t])
                s.emitted.append(token)
                s.remaining -= 1
                n += 1
                if (s.req.eos_token_id is not None
                        and token == s.req.eos_token_id):
                    s.alive = False
            # host mirror of the advance: rejected positions' in-cache
            # garbage sits above pos and is overwritten by the next
            # block before any query can attend it (no rollback)
            s.pos += n
            s.tok = s.emitted[-1]
            if n:
                self._notify_progress(s.req, s.emitted[-n:])
            self.spec_tokens_emitted += n
            self.spec_slot_ticks += 1
            if self._obs:
                self._m_spec_drafted.inc(drafted)
                self._m_spec_accepted.inc(accepted)
                self._m_spec_rejected.inc(drafted - accepted)
                self._m_spec_per_tick.observe(n)
            if s.remaining <= 0 or not s.alive:
                self._retire(i)

    def _tick_decode(self):
        N = self.slots
        tok = np.zeros(N, np.int32)
        pos = np.zeros(N, np.int32)
        live = np.zeros(N, bool)
        eos = np.full(N, -1, np.int32)
        keys = np.zeros((N, 2), np.uint32)
        n_live = 0
        for i, s in enumerate(self._slots):
            if s.free:
                continue
            tok[i] = s.tok
            pos[i] = s.pos
            if s.alive and s.remaining > 0:
                live[i] = True
                n_live += 1
            if s.req.eos_token_id is not None:
                eos[i] = s.req.eos_token_id
            keys[i] = s.key
        prog = self._get_decode_prog()
        t_tick = time.perf_counter() if self._obs else 0.0
        with self._tp_scope():         # lazy path may trace here
            if self.paged:
                toks_dev, self._caches = prog(
                    self._params, self._buffers, self._caches,
                    self._block_tables, tok, pos, live, eos, keys)
            else:
                toks_dev, self._caches = prog(
                    self._params, self._buffers, self._caches, tok,
                    pos, live, eos, keys)
        toks = np.asarray(toks_dev)       # the ONE host sync per tick
        self.ticks += 1
        if self._obs:
            now = time.perf_counter()
            self._m_ticks.inc()
            self._m_occupancy.observe(n_live)
            if now > t_tick:
                self.last_tick_model_eff = _eff.model_bandwidth_eff(
                    self._tick_model_bytes, now - t_tick,
                    self._eff_chip)
                self._g_tick_eff.set(self.last_tick_model_eff)
            _obs.record_span("engine.tick", t_tick, now, cat="engine",
                             active=n_live, tick=self.ticks)
            if self._tp is not None:
                # the per-block all-reduces run INSIDE the decode
                # program; this span brackets the dispatch that moved
                # them and carries the modeled per-chip wire bytes
                _obs.record_span(
                    "engine.tp_allreduce", t_tick, now, cat="engine",
                    tp=self.tp, tick=self.ticks,
                    modeled_comm_bytes=self.tp_tick_comm_bytes)
        for i, s in enumerate(self._slots):
            if s.free or not live[i]:
                continue
            n = 0
            for t in range(self.tick_tokens):
                if s.remaining <= 0 or not s.alive:
                    break
                token = int(toks[i, t])
                s.emitted.append(token)
                s.remaining -= 1
                n += 1
                if (s.req.eos_token_id is not None
                        and token == s.req.eos_token_id):
                    s.alive = False
            # host mirror of the in-program advance: continuing rows
            # consumed exactly tick_tokens live steps; retired rows'
            # in-program overshoot is irrelevant (slot is reset at the
            # next admission)
            s.pos += n
            s.tok = s.emitted[-1]
            if n:
                self._notify_progress(s.req, s.emitted[-n:])
            if s.remaining <= 0 or not s.alive:
                self._retire(i)

    def _retire(self, b: int):
        slot = self._slots[b]
        req, slot.req = slot.req, None
        slot.alive = False
        if self.paged and slot.pages:
            # drop this request's references; pages other requests (or
            # the prefix trie) still hold survive, the rest free. The
            # stale block-table row is harmless until reuse — dead
            # slots are write-masked and their reads causally masked —
            # but zero it anyway so state dumps read truthfully.
            self._allocator.decref(slot.pages)
            slot.pages = []
            self._block_tables[b] = 0
            self._pool_blocked = False    # freed pages: retry the head
            if self._obs:
                self._g_pages_free.set(self._allocator.free_pages)
                self._g_pages_used.set(self._allocator.used_pages)
        if self._obs:
            now = time.perf_counter()
            self._m_retires.inc()
            self._m_decode.observe((now - slot.t_dec0) * 1e3)
            self._m_e2e.observe((now - req.t_submit) * 1e3)
            _obs.record_span("engine.decode", slot.t_dec0, now,
                             cat="engine", request_id=req.rid,
                             tokens=len(slot.emitted))
        out = list(slot.emitted)
        # per-request generation accounting, readable off the future by
        # the serving layer AFTER result() resolves (set before
        # set_result, so publication orders correctly)
        info = {"tokens_generated": len(out)}
        if self._spec is not None:
            info["tokens_drafted"] = req.drafted
            info["tokens_accepted"] = req.accepted
        if req.cancelled:
            # cancelled mid-decode: the slot and pages above are
            # already reclaimed; publish the PARTIAL result on the
            # error path (no eos padding — these are exactly the
            # tokens generated) so the caller's journal reconciles
            # against engine truth instead of losing the work
            info["partial_tokens"] = [int(t) for t in out]
            req.future._ptpu_gen_info = info
            with self._cv:
                self.cancelled += 1
            if self._obs:
                self._m_cancels.inc()
            if not req.future.done():
                req.future.set_exception(
                    RequestCancelled(req.rid, len(out)))
            return
        req.future._ptpu_gen_info = info
        if len(out) < req.max_new_tokens:
            # finished early on eos: pad with eos — generate()'s contract
            out += [req.eos_token_id] * (req.max_new_tokens - len(out))
        result = np.concatenate(
            [req.prompt, np.asarray(out, np.int64)])
        self.completed += 1
        if not req.future.done():
            req.future.set_result(result)


# ---------------------------------------------------------------------------
# Config -> create_predictor surface (inference/predictor.py delegates
# here when Config.enable_continuous_batching was called)
# ---------------------------------------------------------------------------

class GenerationPredictor:
    """Predictor-shaped facade over a ContinuousBatchingEngine so
    serving code written against the Config -> create_predictor surface
    (reference: multi-stream AnalysisPredictor usage) drives the engine
    unchanged: one named int64 input, one named tokens output."""

    def __init__(self, engine: ContinuousBatchingEngine):
        self.engine = engine

    def generate(self, input_ids, max_new_tokens: int = 32, **kw):
        return self.engine.generate(input_ids, max_new_tokens, **kw)

    def get_input_names(self):
        return ["input_ids"]

    def get_output_names(self):
        return ["tokens"]

    def close(self):
        self.engine.stop()


def create_engine_predictor(config) -> GenerationPredictor:
    opts = dict(config._engine_opts)
    model = opts.pop("model", None)
    if model is None:
        raise ValueError(
            "Config.enable_continuous_batching needs a live model: the "
            "generation loop (cache-threaded forward + new_cache) cannot "
            "be reconstructed from an exported StableHLO program — pass "
            "enable_continuous_batching(model=the_causal_lm)")
    return GenerationPredictor(ContinuousBatchingEngine(model, **opts))
