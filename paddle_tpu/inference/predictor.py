"""Predictor implementation (see package docstring for the reference map)."""
from __future__ import annotations

import os
from enum import Enum
from typing import Dict, List, Optional

import numpy as np

import jax

__all__ = ["Config", "Predictor", "create_predictor", "Tensor", "PlaceType",
           "DataType", "PrecisionType", "PredictorPool", "get_version",
           "get_num_bytes_of_data_type", "get_trt_compile_version",
           "get_trt_runtime_version", "convert_to_mixed_precision",
           "_get_phi_kernel_name"]


class PlaceType(Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    XPU = 3


class Config:
    """Parity: paddle_infer.Config (api/analysis_config.cc) — the knobs
    that exist map onto XLA; GPU/TRT/MKLDNN toggles are accepted and
    recorded so ported serving code runs unchanged."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._model_prefix = prog_file
        self._params_file = params_file
        self._device = "tpu"
        self._device_id = 0
        self._memory_pool_mb = 0
        self._enable_profile = False
        self._glog_info = True
        self._int8 = False
        self._flags: Dict[str, object] = {}
        self._engine_opts: Optional[Dict[str, object]] = None

    # -- continuous-batching serving engine ------------------------------
    def enable_continuous_batching(self, model=None, slots=None,
                                   max_len=None, cache_dtype="bfloat16",
                                   prefill_buckets=None, tick_tokens=None,
                                   max_queue=None, do_sample=False,
                                   temperature=1.0, top_k=0, top_p=1.0):
        """Serve generate() traffic through the continuous-batching
        engine (inference/engine.py): create_predictor() then returns a
        GenerationPredictor multiplexing concurrent requests over a
        fixed slot pool with one compiled decode program. `model` must
        be the live causal-LM Layer (the decode loop cannot be rebuilt
        from an exported StableHLO program)."""
        self._engine_opts = {
            "model": model, "slots": slots, "max_len": max_len,
            "cache_dtype": cache_dtype,
            "prefill_buckets": prefill_buckets,
            "tick_tokens": tick_tokens, "max_queue": max_queue,
            "do_sample": do_sample, "temperature": temperature,
            "top_k": top_k, "top_p": top_p,
        }

    # -- model location (reference: SetModel/SetProgFile/SetParamsFile) --
    def set_model(self, prog_file: str, params_file: Optional[str] = None):
        if prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._model_prefix = prog_file
        self._params_file = params_file

    def prog_file(self):
        return (self._model_prefix or "") + ".pdmodel"

    def params_file(self):
        return self._params_file or (self._model_prefix or "") + ".pdiparams"

    def model_dir(self):
        return os.path.dirname(self._model_prefix or "")

    # -- device ----------------------------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        # accepted for parity; execution targets the available backend
        self._memory_pool_mb = memory_pool_init_size_mb
        self._device_id = device_id

    def enable_tpu(self, device_id: int = 0):
        self._device = "tpu"
        self._device_id = device_id

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self):
        return False

    def enable_int8(self):
        """Serve the int8-lowered program (reference role: TRT int8 with
        calibration, tensorrt_subgraph_pass.cc). XLA has no load-time
        subgraph rewriter — the int8 conversion happens ahead of time
        (quantization.convert_to_int8 + jit.save); this flag makes the
        Predictor prefer a `<prefix>_int8.pdmodel` sibling artifact and
        otherwise REQUIRE the loaded program to contain int8 dots, so a
        silently-f32 "int8 deployment" cannot happen."""
        self._int8 = True

    # -- accepted no-op toggles (XLA subsumes them) ----------------------
    def enable_tensorrt_engine(self, *a, **k):
        pass

    def enable_mkldnn(self):
        pass

    def switch_ir_optim(self, x=True):
        pass

    def enable_memory_optim(self):
        pass

    def switch_use_feed_fetch_ops(self, x=False):
        pass

    def switch_specify_input_names(self, x=True):
        pass

    def set_cpu_math_library_num_threads(self, n):
        pass

    def enable_profile(self):
        self._enable_profile = True

    def disable_glog_info(self):
        self._glog_info = False

    def summary(self) -> str:
        return (f"Config(model={self._model_prefix!r}, "
                f"device={self._device}:{self._device_id})")


class Tensor:
    """Zero-copy style IO handle.

    Parity: paddle_infer.Tensor (ZeroCopyTensor) — copy_from_cpu/
    copy_to_cpu naming kept; on TPU "copy" is a device_put/device_get.
    """

    def __init__(self, name: str, owner: "Predictor"):
        self.name = name
        self._owner = owner
        self._value: Optional[jax.Array] = None

    def reshape(self, shape):
        pass  # shapes flow from the copied array

    def copy_from_cpu(self, data: np.ndarray):
        self._value = jax.device_put(np.ascontiguousarray(data))

    def copy_to_cpu(self) -> np.ndarray:
        out = self._owner._outputs.get(self.name)
        if out is None:
            raise RuntimeError("run() has not produced this output yet")
        return np.asarray(out)

    def shape(self):
        v = self._owner._outputs.get(self.name, self._value)
        return list(v.shape) if v is not None else []


def _has_int8_dots(mlir: str) -> bool:
    """True when the program contains at least one dot_general over int8
    operands — a uint8 image input or an i8 mask cast elsewhere must NOT
    satisfy enable_int8()'s no-silent-f32 guarantee."""
    import re
    return bool(re.search(r"dot_general.*xi8>", mlir))


class Predictor:
    """Parity: paddle_infer.Predictor (AnalysisPredictor).

    Load = deserialize StableHLO + params, AOT-compile per input shape
    (cached). run() executes the compiled program; get_output_handle
    exposes results.
    """

    def __init__(self, config: Config):
        from ..jit.api import TranslatedLayer
        import pickle

        self.config = config
        prefix = config._model_prefix or ""
        used_sibling = False
        if config._int8 and os.path.exists(prefix + "_int8.pdmodel"):
            # prefer the int8-lowered sibling artifact; its params go
            # with it (an explicitly-set f32 params_file would feed the
            # wrong state tree to the int8 program)
            prefix = prefix + "_int8"
            used_sibling = True
        prog_file = (prefix + ".pdmodel" if used_sibling
                     else config.prog_file())
        with open(prog_file, "rb") as f:
            self._exported = jax.export.deserialize(f.read())
        if config._int8 and not _has_int8_dots(
                self._exported.mlir_module()):
            if used_sibling:
                raise RuntimeError(
                    f"Config.enable_int8(): {prefix}.pdmodel was found "
                    "and loaded but contains no int8 dots — it is not an "
                    "int8-lowered artifact. Re-export it: PTQ calibrate "
                    "-> convert() -> quantization.convert_to_int8(model) "
                    "-> paddle.jit.save(model, that prefix, input_spec)")
            raise RuntimeError(
                "Config.enable_int8(): the loaded program has no int8 "
                "dots and no `<prefix>_int8.pdmodel` sibling exists. "
                "Lower it first: quantization.PTQ calibrate -> "
                "convert() -> quantization.convert_to_int8(model) -> "
                "paddle.jit.save(model, prefix + '_int8', input_spec)")
        params_file = (prefix + ".pdiparams" if used_sibling
                       else config.params_file())
        with open(params_file, "rb") as f:
            meta = pickle.load(f)
        self._state = {n: jax.device_put(v)
                       for n, v in meta["state"].items()}
        self._input_spec = meta.get("input_spec") or None
        if self._input_spec:
            n_inputs = len(self._input_spec)
        else:
            # in_avals is the FLATTENED arg tree: one aval per state leaf
            # plus one per real input
            n_inputs = max(
                len(self._exported.in_avals) - len(meta["state"]), 1)
        self._input_names = [f"x{i}" for i in range(n_inputs)]
        self._inputs: Dict[str, Tensor] = {
            n: Tensor(n, self) for n in self._input_names}
        self._outputs: Dict[str, jax.Array] = {}
        self._output_names: List[str] = []

    # -- handles ---------------------------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_dtype(self, name: str) -> Optional[str]:
        """Declared dtype of an input (from the saved InputSpec), or None
        when the model was exported without specs."""
        if name not in self._input_names:
            raise KeyError(f"unknown input {name!r}; expected "
                           f"{self._input_names}")
        if self._input_spec is None:
            return None
        spec = self._input_spec[self._input_names.index(name)]
        # saved form (jit.api.save): (shape_strs, dtype_str)
        if isinstance(spec, (tuple, list)) and len(spec) == 2:
            return str(spec[1])
        dt = getattr(spec, "dtype", None)
        return str(dt) if dt is not None else None

    def get_input_handle(self, name: str) -> Tensor:
        return self._inputs[name]

    def get_output_names(self) -> List[str]:
        return list(self._output_names) or ["out0"]

    def get_output_handle(self, name: str) -> Tensor:
        t = Tensor(name, self)
        return t

    # -- execution -------------------------------------------------------
    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """Parity: Predictor.run() (ZeroCopyRun)."""
        if inputs is not None:
            for name, arr in zip(self._input_names, inputs):
                self._inputs[name].copy_from_cpu(np.asarray(arr))
        args = []
        for name in self._input_names:
            v = self._inputs[name]._value
            if v is None:
                raise RuntimeError(
                    f"input {name!r} not set; use get_input_handle("
                    f"{name!r}).copy_from_cpu(...)")
            args.append(v)
        out = self._exported.call(self._state, *args)
        leaves = jax.tree_util.tree_leaves(out)
        self._output_names = [f"out{i}" for i in range(len(leaves))]
        self._outputs = dict(zip(self._output_names, leaves))
        if inputs is not None:
            return [np.asarray(l) for l in leaves]
        return True

    def clear_intermediate_tensor(self):
        self._outputs.clear()


def create_predictor(config: Config):
    """Parity: paddle_infer.create_predictor. With
    Config.enable_continuous_batching this returns the engine-backed
    GenerationPredictor instead of a StableHLO Predictor."""
    if getattr(config, "_engine_opts", None):
        from .engine import create_engine_predictor
        return create_engine_predictor(config)
    return Predictor(config)


class DataType(Enum):
    """Parity: paddle_infer.DataType (api/paddle_tensor.h)."""
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6
    BOOL = 7


class PrecisionType(Enum):
    """Parity: paddle_infer.PrecisionType — kInt8 routes through the
    int8 lowering (Config.enable_int8)."""
    Float32 = 0
    Half = 1
    Int8 = 2
    Bfloat16 = 3


_DTYPE_BYTES = {DataType.FLOAT32: 4, DataType.INT64: 8, DataType.INT32: 4,
                DataType.UINT8: 1, DataType.INT8: 1, DataType.FLOAT16: 2,
                DataType.BFLOAT16: 2, DataType.BOOL: 1}


def get_num_bytes_of_data_type(dtype: "DataType") -> int:
    """Parity: paddle_infer.get_num_bytes_of_data_type."""
    return _DTYPE_BYTES[DataType(dtype)]


def get_version() -> str:
    """Parity: paddle_infer.get_version."""
    from ..version import full_version
    return f"paddle_tpu inference {full_version}"


def get_trt_compile_version():
    """No TensorRT in a TPU build (XLA is the engine)."""
    return (0, 0, 0)


def get_trt_runtime_version():
    return (0, 0, 0)


def _get_phi_kernel_name(op_name: str) -> str:
    """Parity: the op->phi kernel rename map; one dispatch layer here."""
    return op_name


def convert_to_mixed_precision(model_file, params_file, mixed_model_file,
                               mixed_params_file, mixed_precision=None,
                               backend=None, keep_io_types=True,
                               black_list=None, **kwargs):
    raise NotImplementedError(
        "convert_to_mixed_precision rewrites a Program's dtypes; StableHLO "
        "programs bake dtypes at trace time — re-export instead: load the "
        "Layer, call .bfloat16() (or .float16()), and paddle.jit.save it")


class PredictorPool:
    """Parity: paddle_infer.PredictorPool — N independent predictors over
    one Config for thread-per-worker serving."""

    def __init__(self, config: Config, size: int = 1):
        if size < 1:
            raise ValueError("PredictorPool size must be >= 1")
        self._predictors = [Predictor(config) for _ in range(size)]

    def retrive(self, idx: int) -> Predictor:   # reference spelling
        return self._predictors[idx]

    retrieve = retrive

    def __len__(self):
        return len(self._predictors)
