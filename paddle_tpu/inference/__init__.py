"""Paddle Inference parity: the deployment/serving path (SURVEY.md §2.7).

Reference: paddle_infer::CreatePredictor over AnalysisPredictor
(paddle/fluid/inference/api/analysis_predictor.cc:274 Init,
:555 PrepareProgram, :573 OptimizeInferenceProgram, :632 PrepareExecutor)
with AnalysisConfig (api/analysis_config.cc) and zero-copy IO handles.

TPU-native: the "optimized program" is a serialized StableHLO module
(produced by paddle.jit.save / static.save_inference_model); "analysis +
TRT subgraphs" collapse into XLA compilation at load (AOT — first run
pays no trace). The Config/Predictor/Tensor-handle API surface matches the
reference so serving code ports directly.
"""
from .predictor import (Config, PlaceType, Predictor, Tensor,
                        create_predictor)

__all__ = ["Config", "Predictor", "create_predictor", "Tensor",
           "PlaceType"]
