"""Paddle Inference parity: the deployment/serving path (SURVEY.md §2.7).

Reference: paddle_infer::CreatePredictor over AnalysisPredictor
(paddle/fluid/inference/api/analysis_predictor.cc:274 Init,
:555 PrepareProgram, :573 OptimizeInferenceProgram, :632 PrepareExecutor)
with AnalysisConfig (api/analysis_config.cc) and zero-copy IO handles.

TPU-native: the "optimized program" is a serialized StableHLO module
(produced by paddle.jit.save / static.save_inference_model); "analysis +
TRT subgraphs" collapse into XLA compilation at load (AOT — first run
pays no trace). The Config/Predictor/Tensor-handle API surface matches the
reference so serving code ports directly.
"""
from .engine import (CacheExhausted, ContinuousBatchingEngine,
                     EngineOverloaded, GenerationPredictor,
                     RequestCancelled)
from .speculative import (DraftModelProposer, NGramProposer,
                          SpeculativeConfig)
from .router import Replica, ReplicaSpec, Router
from .predictor import (Config, DataType, PlaceType, PrecisionType,
                        Predictor, PredictorPool, Tensor,
                        _get_phi_kernel_name,
                        convert_to_mixed_precision, create_predictor,
                        get_num_bytes_of_data_type,
                        get_trt_compile_version,
                        get_trt_runtime_version, get_version)

__all__ = ["Config", "Predictor", "create_predictor", "Tensor",
           "PlaceType", "DataType", "PrecisionType", "PredictorPool",
           "ContinuousBatchingEngine", "EngineOverloaded",
           "CacheExhausted", "RequestCancelled", "GenerationPredictor",
           "SpeculativeConfig", "NGramProposer", "DraftModelProposer",
           "Router", "ReplicaSpec", "Replica",
           "get_version", "get_num_bytes_of_data_type",
           "get_trt_compile_version", "get_trt_runtime_version",
           "convert_to_mixed_precision", "_get_phi_kernel_name"]
