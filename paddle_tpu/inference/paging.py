"""Host-side KV-cache paging: page allocator + shared-prefix trie.

The paged serving engine (inference/engine.py, ``paged=True``) carves
its KV cache into fixed-size pages (``PADDLE_TPU_KV_PAGE`` tokens each)
and gives every decode slot a BLOCK TABLE of physical page indices
instead of a worst-case ``max_len`` cache row. These two classes are
the entirely host-side half of that design — pure Python, no jax, unit
testable without a model:

- ``PageAllocator``: free-list + per-page refcounts. A page is owned
  by every slot whose block table references it PLUS (for pages
  registered as a shared prefix) the prefix trie; it returns to the
  free list only when the last reference drops. Refcounting is what
  makes cross-request page SHARING safe: a retiring request decrefs,
  it never frees pages another slot is still reading.

- ``PrefixTrie``: vLLM-style prefix cache over COMPLETE pages. A node
  keys on the exact ``page_size`` token ids of one page, children
  extend the prefix; each node pins one physical page (the trie holds
  its own allocator reference). Admission walks the prompt's complete
  pages through the trie — every match is a page of KV the engine does
  NOT recompute and does NOT duplicate in HBM — and registers the
  request's freshly computed complete pages for the next arrival.
  Eviction is LRU over leaves and never touches a page a live slot
  references (refcount > 1).

Safety invariant the engine builds on: a page registered in the trie
holds a COMPLETE page of prompt KV ([j*ps, (j+1)*ps) with
(j+1)*ps <= prompt_len), and decode only ever writes at positions
>= prompt_len — so shared pages are read-only for their whole life and
sharing them across slots can never corrupt. The one write that would
land in a fully-matched tail page goes through copy-on-write instead
(the engine copies the page and rebinds the slot's block table before
any write).
"""
from __future__ import annotations

import zlib
from typing import Dict, List, Optional, Tuple

__all__ = ["PageAllocator", "PrefixTrie", "pages_needed",
           "chain_hashes"]


def pages_needed(tokens: int, page_size: int) -> int:
    """Pages required to hold ``tokens`` cache positions."""
    return -(-int(tokens) // int(page_size))


def chain_hashes(tokens, page_size: int) -> List[int]:
    """crc32 chain hash of every COMPLETE page of ``tokens``: hash j
    folds page j's exact token tuple into hash j-1. The same fold
    :meth:`PrefixTrie.fingerprints` uses, so a router can hash an
    incoming prompt and intersect with the fingerprint set a replica
    reports — equal hashes <=> equal cached prefix chains, across
    processes (Python ``hash()`` is per-process salted; crc32 is not),
    without ever shipping token ids."""
    ps = int(page_size)
    if ps <= 0:
        return []
    out: List[int] = []
    h = 0
    for j in range(len(tokens) // ps):
        key = tuple(int(t) for t in tokens[j * ps:(j + 1) * ps])
        h = zlib.crc32(repr(key).encode(), h)
        out.append(h)
    return out


class PageAllocator:
    """Free-list page allocator with per-page reference counts.

    Pages are plain ints in [0, num_pages). ``alloc`` is all-or-nothing
    (a request either gets every page it needs or the pool state is
    untouched) so a failed admission never leaks a partial grant.
    """

    def __init__(self, num_pages: int):
        if num_pages < 1:
            raise ValueError("need at least one page")
        self.num_pages = int(num_pages)
        # pop() takes from the end: keep ascending ids popping first
        self._free: List[int] = list(range(self.num_pages - 1, -1, -1))
        self._refs: Dict[int, int] = {}

    # -- introspection ---------------------------------------------------
    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.num_pages - len(self._free)

    def refcount(self, page: int) -> int:
        return self._refs.get(page, 0)

    def check(self) -> None:
        """Invariant check (tests call it after churn): every page is
        either free exactly once or referenced, never both/neither."""
        free = set(self._free)
        if len(free) != len(self._free):
            raise AssertionError("duplicate page on the free list")
        held = set(self._refs)
        if free & held:
            raise AssertionError(f"pages both free and referenced: "
                                 f"{sorted(free & held)}")
        if free | held != set(range(self.num_pages)):
            raise AssertionError("pages leaked: neither free nor "
                                 "referenced")
        if any(r < 1 for r in self._refs.values()):
            raise AssertionError("non-positive refcount retained")

    # -- allocation ------------------------------------------------------
    def alloc(self, n: int) -> Optional[List[int]]:
        """Allocate ``n`` pages at refcount 1, or None (pool unchanged)
        when fewer than ``n`` are free."""
        if n < 0:
            raise ValueError("negative allocation")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for p in out:
            self._refs[p] = 1
        return out

    def incref(self, pages) -> None:
        for p in pages:
            if p not in self._refs:
                raise AssertionError(f"incref on unallocated page {p}")
            self._refs[p] += 1

    def decref(self, pages) -> int:
        """Drop one reference per page; pages reaching zero return to
        the free list. Returns how many pages were actually freed."""
        freed = 0
        for p in pages:
            r = self._refs.get(p)
            if r is None:
                raise AssertionError(f"decref on unallocated page {p}")
            if r == 1:
                del self._refs[p]
                self._free.append(p)
                freed += 1
            else:
                self._refs[p] = r - 1
        return freed


class _TrieNode:
    __slots__ = ("page", "children", "parent", "key", "last_used")

    def __init__(self, page: Optional[int], parent=None, key=None):
        self.page = page
        self.children: Dict[Tuple[int, ...], _TrieNode] = {}
        self.parent = parent
        self.key = key
        self.last_used = 0


class PrefixTrie:
    """Prefix cache over complete pages (see module docstring).

    The trie owns ONE allocator reference per node — ``insert`` increfs,
    ``evict`` decrefs. Pages a live slot still references (refcount > 1)
    are never evicted; eviction order is LRU over current leaves, and
    evicting a leaf exposes its parent, so an unreferenced chain drains
    fully when the pool is under pressure.
    """

    def __init__(self, allocator: PageAllocator):
        self.alloc = allocator
        self.root = _TrieNode(None)
        self._clock = 0
        self.pages_cached = 0

    def _touch(self, node: _TrieNode) -> None:
        self._clock += 1
        node.last_used = self._clock

    def match(self, page_keys: List[Tuple[int, ...]]) -> List[int]:
        """Longest cached chain of ``page_keys`` (each the exact token
        tuple of one complete page); returns the physical pages of the
        matched prefix, LRU-touched."""
        node, out = self.root, []
        for key in page_keys:
            child = node.children.get(key)
            if child is None:
                break
            self._touch(child)
            out.append(child.page)
            node = child
        return out

    def insert(self, page_keys: List[Tuple[int, ...]],
               pages: List[int]) -> int:
        """Register a chain of complete pages. Keys already cached are
        left untouched (first writer wins — the content is identical by
        construction); each NEW node takes one allocator reference on
        its physical page. Returns how many new pages were cached."""
        node, added = self.root, 0
        for key, page in zip(page_keys, pages):
            child = node.children.get(key)
            if child is None:
                child = _TrieNode(page, parent=node, key=key)
                node.children[key] = child
                self.alloc.incref([page])
                self.pages_cached += 1
                added += 1
            self._touch(child)
            node = child
        return added

    def _leaves(self):
        stack = [self.root]
        while stack:
            node = stack.pop()
            if node is not self.root and not node.children:
                yield node
            stack.extend(node.children.values())

    def evict(self, n_pages: int) -> int:
        """Free up to ``n_pages`` pages by dropping least-recently-used
        leaves whose pages only the trie still references. Returns the
        number of pages actually freed to the pool."""
        freed = 0
        while freed < n_pages:
            victims = [nd for nd in self._leaves()
                       if self.alloc.refcount(nd.page) == 1]
            if not victims:
                break
            victim = min(victims, key=lambda nd: nd.last_used)
            del victim.parent.children[victim.key]
            freed += self.alloc.decref([victim.page])
            self.pages_cached -= 1
        return freed

    def fingerprints(self, limit: int = 512) -> List[int]:
        """Chained crc32 ids of the cached prefix chains (one per
        node, bounded): node fingerprint = crc32(page key, parent
        fingerprint) — the cross-process identity a replica exports
        via /healthz for the router's prefix-affinity scoring (a
        prompt whose :func:`chain_hashes` prefix lands in this set has
        that many pages of KV already cached here)."""
        out: List[int] = []
        stack = [(self.root, 0)]
        while stack and len(out) < limit:
            node, h = stack.pop()
            for key, child in list(node.children.items()):
                ch = zlib.crc32(repr(key).encode(), h)
                out.append(ch)
                if len(out) >= limit:
                    break
                stack.append((child, ch))
        return out

    def reclaimable(self) -> int:
        """How many cached pages eviction could actually free right now
        (trie-only references — pages live slots also hold are pinned).
        The engine's truthful cache_exhausted shed reads this."""
        count = 0
        stack = [self.root]
        while stack:
            node = stack.pop()
            if node is not self.root \
                    and self.alloc.refcount(node.page) == 1:
                count += 1
            stack.extend(node.children.values())
        return count

    def evict_all(self) -> int:
        """Drop every droppable node (diagnostics/tests)."""
        return self.evict(self.pages_cached)
