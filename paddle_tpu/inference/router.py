"""Multi-replica serving tier: a health-aware router over N replicas.

One ``PredictorServer`` is one process; the millions-of-users north
star needs a fleet (ROADMAP item 5 — the reference's predictor-pool /
FleetExecutor DistModel fleet-serving role, MIGRATING.md). This module
composes the per-process robustness primitives PRs 1/2/5 already
provide into a tier that stays up, sheds truthfully, and rides through
replica death:

* **Replicas are subprocesses** the router spawns and supervises: each
  runs ``python -m paddle_tpu.inference.router --replica-child`` — a
  model built from a JSON :class:`ReplicaSpec`, a
  ``ContinuousBatchingEngine``, and a ``PredictorServer`` that AOT-warms
  through the shared executable store (``PADDLE_TPU_EXEC_STORE_DIR``):
  once one replica has compiled-and-stored, every successor reaches
  ready with ZERO XLA compiles (bench_cold_start-proven, asserted again
  by the rolling-restart test).
* **Health-aware admission**: a control loop polls every replica's
  ``/healthz`` (slot occupancy, queue depth, warming/draining state).
  ``/generate`` routes to the least-loaded READY replica — never to a
  warming, draining, ejected, unreachable, or dead one.
* **Failure handling**: each forward carries a deadline; connect
  failures / 5xx / injected ``router_forward`` faults retry on a
  DIFFERENT replica under ``resilience.RetryPolicy`` (full-jitter, the
  request's remaining budget as the retry-time budget). A replica with
  a failure streak is circuit-breaker-ejected for a cooldown. When no
  replica can admit, the tier answers a truthful 503 with
  ``Retry-After`` — zero hangs, zero connection resets, zero silent
  drops.
* **Self-healing + rolling restarts**: a replica that dies (kill -9, a
  wedged backend) is detected by the control loop and respawned.
  ``rolling_restart()`` replaces replicas one at a time: the successor
  warms from the store and joins the rotation BEFORE the predecessor
  drains (``POST /drain`` + ``stop(drain_s)``) and exits.
* **Queue-driven autoscaling**: when aggregate queue depth stays above
  the scale-up watermark the tier grows toward ``max_replicas``; when
  it sits idle it shrinks (drain-then-retire) toward ``min_replicas``,
  with a cooldown between actions. Both directions reuse the one spawn
  / retire path the rolling restart uses.
* **Work-conserving request recovery** (ISSUE 15): every journaled
  ``/generate`` forwards in the replica's incremental (NDJSON) mode —
  the router journals ``prompt + tokens_so_far`` per in-flight request
  as token events stream back. A replica dying MID-DECODE (kill -9,
  broken forward) no longer costs the client its generated tokens or
  an error: the router re-admits ``prompt + journal`` on a healthy
  replica and greedy determinism makes the continuation bitwise
  identical to the undisturbed run — the paged prefix trie turns the
  re-prefill into a page-table hit and the bucketed admit programs
  mean zero new XLA compiles. A request whose token progress stalls
  past the hedge budget (derived live from the inter-progress
  histogram p99, or ``PADDLE_TPU_TIER_HEDGE_S``) launches a BACKUP
  decode on a second replica; first to advance wins and the loser is
  truly cancelled (``POST /cancel`` -> engine slot retire -> pages
  freed, leak-free). Recoveries/hedges/cancels are counted
  (``ptpu_router_{recoveries,hedges,hedge_wins,cancels}_total``) and
  each recovery burst dumps a flight-recorder artifact naming the
  migrated request ids.
* **Streaming-first QoS front** (ISSUE 16): ``"stream": true`` on a
  journaled ``/generate`` relays incremental NDJSON token blocks to
  the CLIENT straight from the journal feed — the journal IS the
  stream, so a replica kill, a hedge win, or a rolling restart is an
  invisible mid-stream failover (the relay's read frontier + the
  journal's position-verified extends guarantee zero lost and zero
  duplicated tokens); a client that disconnects mid-stream propagates
  to real cancellation (engine slot retired, KV pages freed) on
  whichever replica currently owns the request. Admission stalls — no
  FIRST token past the live TTFT-histogram-derived budget — hedge
  onto a second replica under the same tier-wide hedge budget decode
  stalls use, and ``_pick`` blends load with prefix-trie affinity
  (replicas export chained-crc32 trie fingerprints via /healthz; the
  prompt's own chain hashes score how many pages of its KV each
  candidate already holds). Requests carry a tenant id + priority
  class (``X-PTPU-Tenant`` / ``X-PTPU-Class`` headers or ``tenant`` /
  ``qos_class`` body fields); admission runs through a weighted-fair
  scheduler — strict priority across classes, weighted round-robin by
  journal-accounted token charge inside one, starvation-aged — and
  overload degrades TRUTHFULLY per class: low classes shed first with
  per-class 429s whose Retry-After derives from the observed queue
  drain rate, never a blanket 503.

Greedy tokens through the tier are engine-identical to a direct
engine call: the router never touches payloads, and a retried request
re-runs the same deterministic greedy program on another replica over
identical weights (every replica seeds the same ``ReplicaSpec.seed``
before building the model).

CLI (tools/serve_tier.py wraps this): the module itself only exposes
the ``--replica-child`` entry point used by the spawner.

Env knobs (documented in COMPONENTS.md "Serving tier"):
  PADDLE_TPU_TIER_DEADLINE     per-request forward deadline (60 s)
  PADDLE_TPU_TIER_RETRIES      retry budget per request (2 retries)
  PADDLE_TPU_TIER_POLL_S       health-poll interval (0.5 s)
  PADDLE_TPU_TIER_EJECT_S      circuit-breaker ejection cooldown (5 s)
  PADDLE_TPU_TIER_HEDGE_S      hedge budget: seconds of token-progress
                               silence before a backup decode launches
                               (0 disables; unset = derived live from
                               the inter-progress histogram p99)
  PADDLE_TPU_TIER_HEDGE_MULT   multiplier on the derived p99 (20)
  PADDLE_TPU_TIER_HEDGE_FRAC   tier-wide hedge budget: backups may
                               occupy at most this fraction of the
                               live journaled requests (0.25, floor
                               1) — a saturated tier must not hedge
                               itself into double load
  PADDLE_TPU_TIER_JOURNAL_REQS max concurrently journaled requests —
                               the journal bound (128; overflow falls
                               back to the single-shot forward path,
                               0 disables recovery entirely)
  PADDLE_TPU_TIER_TTFT_HEDGE_S first-token hedge budget: seconds of
                               admission silence (no first token)
                               before a backup launches (0 disables;
                               unset = derived live from the TTFT
                               histogram p99)
  PADDLE_TPU_TIER_TTFT_MULT    multiplier on the derived TTFT p99 (3)
  PADDLE_TPU_TIER_AFFINITY_W   prefix-affinity weight blended into
                               replica scoring — pages of cached
                               prefix overlap each count this much
                               load-equivalent (0.5; 0 = load-only)
  PADDLE_TPU_TIER_QOS_CONCURRENCY admission capacity of the weighted-
                               fair scheduler (unset = engine slots x
                               max_replicas; 0 disables the gate)
  PADDLE_TPU_TIER_QOS_QUEUE    per-class wait-queue base depth (8;
                               cap = base x class weight, so low
                               classes shed first under overload)
  PADDLE_TPU_TIER_QOS_STARVATION_S age at which a waiter is served
                               regardless of class (5 s) — the
                               starvation-freedom bound
  PADDLE_TPU_EXEC_STORE_DIR    shared executable store (successors load)
"""
from __future__ import annotations

import http.client
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from .. import obs as _obs
from ..distributed import resilience as _resil
from .paging import chain_hashes
from .serve import (REQUEST_ID_HEADER, RETRY_AFTER_S, _env_float,
                    handle_admin_trace, send_json, send_text)

__all__ = ["ReplicaSpec", "Replica", "Router", "RespawnGovernor",
           "main", "single_device_child_env", "QOS_CLASSES"]

# tier-level 503 reasons extend the per-replica contract
TIER_RETRY_AFTER_S = dict(RETRY_AFTER_S)
TIER_RETRY_AFTER_S["no_replica_ready"] = 1.0

# per-tenant QoS (ISSUE 16): class -> (strict priority, fair-share
# weight). Priority orders classes absolutely (an interactive waiter
# always beats a batch waiter, starvation aging aside); the weight
# sets both the fair token share INSIDE a priority tier and the
# class's wait-queue depth (base x weight) — so under overload the
# batch queue fills and sheds first, interactive last.
QOS_CLASSES = {"interactive": (0, 4.0),
               "standard": (1, 2.0),
               "batch": (2, 1.0)}
QOS_DEFAULT = "standard"
TENANT_HEADER = "X-PTPU-Tenant"
CLASS_HEADER = "X-PTPU-Class"

# what a dying replica can throw at a reader besides the URLError
# family: a SIGKILL mid-response-write surfaces as IncompleteRead /
# BadStatusLine (http.client.HTTPException), and a truncated JSON body
# as ValueError — all must read as "that replica failed", never as an
# unhandled handler crash
_REPLICA_IO_ERRORS = (urllib.error.URLError, ConnectionError, OSError,
                      socket.timeout, http.client.HTTPException,
                      ValueError)


def single_device_child_env(platform: str = "cpu",
                            tp: int = 1) -> Dict[str, str]:
    """Env overrides for replica children. tp=1 (the default): a
    SINGLE-DEVICE serving process — force the platform (N processes
    cannot share one TPU chip) and drop the test harness's virtual-mesh
    flag if it leaked into the parent env. tp>1 (ISSUE 20): the replica
    is an N-chip TP slice — give the child EXACTLY tp virtual devices
    instead, so its engine mesh matches the spec. The one scrub shared
    by tools/serve_tier.py, tools/bench_serving.py --tier, and the
    tests."""
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    if tp > 1:
        flags.append(f"--xla_force_host_platform_device_count={tp}")
    return {"JAX_PLATFORMS": platform, "XLA_FLAGS": " ".join(flags)}


# ---------------------------------------------------------------------------
# ReplicaSpec — everything a replica child needs, JSON-serializable
# ---------------------------------------------------------------------------

class ReplicaSpec:
    """Recipe for one replica process.

    ``model`` is a dict: ``{"kind": "gpt", **GPTConfig kwargs}`` or
    ``{"kind": "factory", "path": "pkg.mod:callable"}`` (the callable
    returns a built causal-LM). ``engine`` holds
    ``ContinuousBatchingEngine`` kwargs (slots, max_len, cache_dtype,
    prefill_buckets, tick_tokens, ...). Every replica seeds ``seed``
    BEFORE building the model so the whole tier holds bitwise-identical
    weights — the token-identity oracle depends on it.

    ``env`` overrides the child environment on top of the router's own
    (the shared ``PADDLE_TPU_EXEC_STORE_DIR`` normally rides here or on
    the router).
    """

    def __init__(self, model: dict, engine: Optional[dict] = None,
                 warmup: bool = True, drain_s: float = 5.0,
                 seed: int = 0, host: str = "127.0.0.1",
                 env: Optional[Dict[str, str]] = None, tp: int = 1):
        self.model = dict(model)
        self.engine = dict(engine or {})
        self.warmup = bool(warmup)
        self.drain_s = float(drain_s)
        self.seed = int(seed)
        self.host = host
        self.env = dict(env or {})
        # tp>1: every replica spawned from this spec is an N-chip
        # tensor-parallel slice (ISSUE 20) — the child engine gets
        # tp= and the child env gets tp virtual devices
        self.tp = int(tp)

    def to_json(self) -> str:
        return json.dumps({
            "model": self.model, "engine": self.engine,
            "warmup": self.warmup, "drain_s": self.drain_s,
            "seed": self.seed, "host": self.host, "tp": self.tp})

    def argv(self, port_file: str) -> List[str]:
        return [sys.executable, "-m", "paddle_tpu.inference.router",
                "--replica-child", "--spec", self.to_json(),
                "--port-file", port_file]


def _build_model(model_spec: dict):
    spec = dict(model_spec)
    kind = spec.pop("kind", "gpt")
    if kind == "gpt":
        from ..models.gpt import GPTConfig, GPTForCausalLM
        return GPTForCausalLM(GPTConfig(**spec))
    if kind == "llama":
        from ..models.llama import LlamaConfig, LlamaForCausalLM
        return LlamaForCausalLM(LlamaConfig(**spec))
    if kind == "factory":
        import importlib
        mod, _, attr = spec["path"].partition(":")
        fn = getattr(importlib.import_module(mod), attr)
        return fn(**spec.get("kwargs", {}))
    raise ValueError(f"unknown model kind {kind!r}")


def _replica_child_main(args) -> int:
    """Entry point of one replica process: build, serve, drain on
    SIGTERM, die with the parent (orphan watchdog)."""
    spec = json.loads(args.spec)
    from ..framework import random as _rng
    _rng.seed(spec.get("seed", 0))           # identical weights tier-wide
    model = _build_model(spec["model"])
    from .engine import ContinuousBatchingEngine
    from .serve import PredictorServer
    eng_kw = dict(spec.get("engine", {}))
    tp = int(spec.get("tp", 1))
    if tp > 1:
        eng_kw.setdefault("tp", tp)       # replica = N-chip slice
    engine = ContinuousBatchingEngine(model, **eng_kw)
    srv = PredictorServer(engine=engine, host=spec.get("host", "127.0.0.1"),
                          port=0, warmup=spec.get("warmup", True)).start()
    # publish the kernel-assigned port atomically — the router polls for
    # this file; a half-written port number must be unobservable
    tmp = args.port_file + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(srv.port))
    os.replace(tmp, args.port_file)

    stop_evt = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *a: stop_evt.set())
    ppid = os.getppid()
    while not stop_evt.wait(0.25):
        if os.getppid() != ppid:
            break                      # router died: don't leak orphans
    # graceful exit: bounded drain of in-flight requests, then down
    srv.stop(drain_s=float(spec.get("drain_s", 5.0)))
    engine.stop()
    return 0


# ---------------------------------------------------------------------------
# Replica — the router's handle on one subprocess
# ---------------------------------------------------------------------------

class Replica:
    """Router-side state for one replica process. All mutation happens
    under the router's lock or on the control-loop thread."""

    def __init__(self, name: str, proc: subprocess.Popen,
                 port_file: str, log_path: str, host: str):
        self.name = name
        self.proc = proc
        self.port_file = port_file
        self.log_path = log_path
        self.host = host
        self.port: Optional[int] = None
        self.state = "starting"     # starting|warming|ready|unready|
        #                             draining|unreachable|dead
        self.draining = False
        self.inflight = 0           # router-side forwards in flight
        self.failure_streak = 0     # forward failures (circuit breaker)
        self.health_fail_streak = 0  # consecutive failed health polls
        self.ejected_until = 0.0
        self.health: dict = {}
        # chained-crc32 trie fingerprints from the last health poll —
        # the prefix-affinity signal (empty = unknown / not paged)
        self.prefix_fps: frozenset = frozenset()
        self.spawned_at = time.monotonic()
        self.last_health_at: Optional[float] = None  # last ANSWERED poll
        self.was_ready = False       # ever reached READY (not warming
        #                              503s — crash-loop governance key)

    @property
    def base_url(self) -> Optional[str]:
        if self.port is None:
            return None
        return f"http://{self.host}:{self.port}"

    def alive(self) -> bool:
        return self.proc.poll() is None

    def routable(self, now: float) -> bool:
        return (self.state == "ready" and not self.draining
                and self.port is not None and now >= self.ejected_until
                and self.alive())

    def load_score(self) -> tuple:
        """Least-loaded ordering: router-side in-flight first (freshest
        signal), then the replica's own reported queue + occupancy from
        the last health poll; name breaks ties deterministically."""
        eng = self.health.get("engine", {}) if self.health else {}
        return (self.inflight,
                int(eng.get("queued", 0)) + int(eng.get("active", 0)),
                self.name)

    def snapshot(self) -> dict:
        eng = self.health.get("engine", {}) if self.health else {}
        now = time.monotonic()
        return {"name": self.name, "state": self.state,
                "pid": self.proc.pid, "port": self.port,
                "draining": self.draining, "inflight": self.inflight,
                "failure_streak": self.failure_streak,
                "queued": int(eng.get("queued", 0)),
                "active": int(eng.get("active", 0)),
                # mesh geometry (ISSUE 20): how many chips this
                # replica's slice occupies — 1 for the classic
                # replica-per-chip tier
                "tp": int(eng.get("tp", 1)),
                "mesh_devices": int(eng.get("mesh_devices", 1)),
                **({"mesh": eng["mesh"]} if "mesh" in eng else {}),
                "ejected": now < self.ejected_until,
                # how old the queued/active numbers above are: None =
                # never answered a poll; a large age means the stats
                # are STALE (wedged/unreachable replica), not live
                "last_scrape_age_s": (
                    None if self.last_health_at is None
                    else round(now - self.last_health_at, 2)),
                "metrics_seq": int(self.health.get("metrics_seq", 0))
                if self.health else 0}


class RespawnGovernor:
    """Escalating respawn backoff + give-up for crash-looping replicas.

    A replica that dies at startup used to be respawned immediately and
    forever — a broken spec (bad model kwargs, poisoned store entry)
    hot-looped process churn. The governor watches each death: a
    replica that never became ready, or died within ``window_s`` of its
    spawn, extends a crash streak; each streak death pushes the next
    respawn out on the shared ``RetryPolicy`` schedule (exponential,
    capped), and past ``budget`` consecutive fast deaths the respawn is
    ABANDONED (``note_death`` returns None — the give-up the router
    counts as ``crash_loops`` and surfaces in stats//healthz). Any
    replica surviving past the window resets the streak.
    """

    def __init__(self, budget: int = 5, window_s: float = 10.0,
                 policy: Optional[_resil.RetryPolicy] = None,
                 clock=time.monotonic):
        self.budget = int(budget)
        self.window_s = float(window_s)
        self.policy = policy if policy is not None else _resil.RetryPolicy(
            max_attempts=max(2, self.budget + 1), base_delay=0.5,
            max_delay=30.0, jitter=0.0)
        self._clock = clock
        self.streak = 0

    def note_death(self, lifetime_s: float,
                   became_ready: bool) -> Optional[float]:
        """One replica died. Returns the earliest monotonic time its
        replacement may spawn, or None when the crash loop has burned
        the budget and this respawn is abandoned."""
        fast = (not became_ready) or lifetime_s < self.window_s
        if not fast:
            self.streak = 0
            return self._clock()
        self.streak += 1
        if self.streak > self.budget:
            return None
        return self._clock() + self.policy.delay(
            min(self.streak, self.policy.max_attempts - 1))

    def note_stable(self) -> None:
        """A replica proved healthy past the window: clear the streak."""
        self.streak = 0


# internal retryable forward outcomes -------------------------------------

class _RetryableForward(Exception):
    pass


class _ForwardFailed(_RetryableForward):
    """Connect failure / 5xx / injected fault against one replica —
    retry on a different one."""

    def __init__(self, replica: Replica, why: str):
        super().__init__(why)
        self.replica = replica


def _retry_after_hint(body: dict) -> Optional[float]:
    """The shed body's ``retry_after_s`` as a float, or None when it
    is absent or unparseable — a malformed hint from a replica (or
    from anything else answering on its port) must degrade to the
    tier's own default, never crash the forward path (RetryPolicy
    and send_json both arithmetic on the value)."""
    try:
        return (None if "retry_after_s" not in body
                else float(body["retry_after_s"]))
    except (TypeError, ValueError):
        return None


class _ShedByReplica(_RetryableForward):
    """A truthful 503 shed (overloaded/warming/draining) — the replica
    is healthy, just not admitting; retry elsewhere, no breaker hit.
    Carries the shed body's ``retry_after_s`` so the RetryPolicy
    honors the replica's own Retry-After hint instead of guessing
    with full-jitter (ISSUE 15 satellite)."""

    def __init__(self, replica: Replica, body: dict):
        super().__init__(str(body.get("error", "shed")))
        self.replica = replica
        self.body = body
        self.retry_after_s = _retry_after_hint(body)


class _NoReplica(Exception):
    pass


class _DeadlineExceeded(Exception):
    pass


# ---------------------------------------------------------------------------
# Work-conserving request recovery (ISSUE 15): journal + stream attempt
# ---------------------------------------------------------------------------

def _flatten_ids(v) -> Optional[List[int]]:
    """Flatten a JSON ``input_ids`` value (flat or nested int lists)
    into one token list; None when it isn't token-shaped (the opaque
    payload then takes the single-shot forward path and the replica
    judges it)."""
    out: List[int] = []

    def walk(x):
        if isinstance(x, bool):
            raise TypeError(x)
        if isinstance(x, int):
            out.append(x)
        elif isinstance(x, (list, tuple)):
            for y in x:
                walk(y)
        else:
            raise TypeError(x)
    try:
        walk(v)
    except TypeError:
        return None
    return out or None


class _ReqJournal:
    """Router-side token journal of ONE in-flight /generate — the
    original request plus every token any replica has streamed back.

    The journal IS the failover state: ``prompt + tokens`` re-admits
    on any healthy replica, and greedy determinism guarantees the
    continuation is bitwise identical to the undisturbed run. Extends
    are reconciled first-writer-wins: positions already journaled are
    VERIFIED against (a hedged duplicate must produce the same greedy
    tokens), never overwritten — a conflict fails the offending
    attempt, not the journal."""

    def __init__(self, prompt: List[int], max_new: int, eos, seed: int,
                 rid: Optional[str], hist=None, ttft_cb=None,
                 itl_cb=None):
        self.prompt = [int(t) for t in prompt]
        self.max_new = int(max_new)
        self.eos = None if eos is None else int(eos)
        self.seed = int(seed)
        self.rid = rid
        self.tokens: List[int] = []
        self.cond = _obs.make_condition("journal.cond")
        self.t0 = time.monotonic()          # submission (TTFT anchor)
        self.last_progress = self.t0
        self.mismatched = False
        self.source: Optional[str] = None   # last replica to advance us
        self._hist = hist                   # inter-progress-gap histogram
        self._ttft_cb = ttft_cb             # ms from submission to tok0
        self._itl_cb = itl_cb               # per-class inter-token ms

    def extend(self, base: int, toks, source: str) -> bool:
        """Merge a token block whose first element is journal position
        ``base``; False on a greedy-determinism conflict or a gap."""
        with self.cond:
            n0 = len(self.tokens)
            for i, t in enumerate(toks):
                t = int(t)
                j = base + i
                if j < n0:
                    if self.tokens[j] != t:
                        self.mismatched = True
                        self.cond.notify_all()
                        return False
                elif j == len(self.tokens):
                    self.tokens.append(t)
                else:            # a gap means events were lost: refuse
                    self.mismatched = True
                    self.cond.notify_all()
                    return False
            if len(self.tokens) > n0:
                now = time.monotonic()
                gap_ms = (now - self.last_progress) * 1e3
                if self._hist is not None:
                    self._hist.observe(gap_ms)
                if n0 == 0:
                    # first token EVER for this request — TTFT, whoever
                    # produced it (primary, TTFT hedge, or a recovery)
                    if self._ttft_cb is not None:
                        self._ttft_cb((now - self.t0) * 1e3)
                elif self._itl_cb is not None:
                    self._itl_cb(gap_ms)
                self.last_progress = now
                self.source = source
            self.cond.notify_all()
            return True

    def size(self) -> int:
        with self.cond:
            return len(self.tokens)

    def complete(self) -> bool:
        """Does the journal alone already hold the full output (token
        budget exhausted, or the eos landed)?"""
        with self.cond:
            return (len(self.tokens) >= self.max_new
                    or (self.eos is not None and bool(self.tokens)
                        and self.tokens[-1] == self.eos))

    def synthesize_body(self) -> dict:
        """The full client body from journal state alone — used when
        the journal completed but the terminal record died with its
        replica. Mirrors the engine's contract exactly: int64 row of
        prompt + generated, eos-padded to max_new on early finish."""
        with self.cond:
            toks = list(self.tokens)
            source = self.source
        out = list(toks)
        if len(out) < self.max_new:
            out += [self.eos] * (self.max_new - len(out))
        body = {"tokens": self.prompt + out,
                "prompt_len": len(self.prompt),
                "new_tokens": self.max_new,
                "tokens_generated": len(toks)}
        if self.rid:
            body["request_id"] = self.rid
        if source:
            body["served_by"] = source
        return body


class _StreamAttempt(threading.Thread):
    """One streaming forward of a journaled request's RESIDUAL
    (prompt + journaled prefix, remaining token budget) to one
    replica. Token events extend the shared journal as they arrive;
    terminal state lands in ``status`` ("done" | "failed") with the
    failure classified for the coordinator (io / shed / client_error /
    cancelled). Cancellable from the coordinator: close the response
    stream, then tell the replica to retire the engine request so its
    slot and KV pages reclaim."""

    def __init__(self, router: "Router", rep: Replica, j: _ReqJournal,
                 base: int, deadline_at: float, is_hedge: bool,
                 seq: int):
        name = f"tier-attempt-{j.rid or 'anon'}.{seq}"
        super().__init__(daemon=True, name=name)
        self.router = router
        self.rep = rep
        self.j = j
        self.base = int(base)
        self.deadline_at = float(deadline_at)
        self.is_hedge = bool(is_hedge)
        # each attempt gets a DISTINCT request id derived from the
        # client's: /cancel targets exactly one engine request, and
        # the obs spans of a hedge pair stay tellable apart
        self.rid = (f"{j.rid}.{seq}" if j.rid
                    else uuid.uuid4().hex[:16])
        self.status = "running"
        self.reaped = False          # coordinator bookkeeping
        self.kind: Optional[str] = None
        self.reason = ""
        self.code = 0
        self.body: Optional[dict] = None
        self.retry_after = None
        self.done_body: Optional[dict] = None
        self.streamed = False        # got a 200 head (mid-stream death
        #                              => work-conserving recovery)
        self.got = 0                 # tokens THIS attempt produced
        self._resp = None
        self._cancelled = threading.Event()

    def run(self):
        j, rep = self.j, self.rep
        with j.cond:
            # snapshot under the journal lock: the coordinator extends
            # j.tokens concurrently, and a torn read here would splice
            # a half-written prefix into the residual prompt
            residual = j.prompt + j.tokens[:self.base]
        payload: dict = {"input_ids": residual,
                         "max_new_tokens": j.max_new - self.base,
                         "seed": j.seed, "stream": True}
        if j.eos is not None:
            payload["eos_token_id"] = j.eos
        data = json.dumps(payload).encode()
        with self.router._lock:
            rep.inflight += 1
        span = (_obs.trace.begin_span(
            "router.forward", cat="router", replica=rep.name,
            request_id=self.rid, resumed_tokens=self.base,
            hedge=self.is_hedge) if self.router._obs else None)
        t0 = time.perf_counter()
        try:
            _resil.maybe_inject("router_forward")
            remaining = self.deadline_at - time.monotonic()
            if remaining <= 0:
                self._fail("io", "deadline exhausted before forward")
                return
            req = urllib.request.Request(
                rep.base_url + "/generate", data,
                {"Content-Type": "application/json",
                 REQUEST_ID_HEADER: self.rid})
            resp = urllib.request.urlopen(req, timeout=remaining)
            self._resp = resp
            self.streamed = True
            with resp:
                for raw in resp:
                    if self._cancelled.is_set():
                        self._fail("cancelled", "cancelled by "
                                                "coordinator")
                        return
                    raw = raw.strip()
                    if not raw:
                        continue
                    ev = json.loads(raw)
                    if "t" in ev:
                        if not j.extend(self.base + self.got, ev["t"],
                                        rep.name):
                            # greedy determinism violated — defensive:
                            # fail THIS attempt, keep the journal
                            self.router.stats_counters[
                                "recovery_mismatches"] += 1
                            self._fail("mismatch", "token mismatch "
                                                   "vs journal")
                            return
                        self.got += len(ev["t"])
                    elif "done" in ev:
                        body = ev["done"]
                        toks = body.get("tokens") or []
                        gen = int(body.get("tokens_generated", 0))
                        # reconcile the terminal truth (authoritative)
                        # into the journal before declaring victory —
                        # a terminal body CONFLICTING with journaled
                        # tokens is the same determinism violation as
                        # a conflicting token event: fail the attempt,
                        # never hand the client a divergent body
                        if not j.extend(
                                self.base,
                                toks[len(residual):len(residual) + gen],
                                rep.name):
                            self.router.stats_counters[
                                "recovery_mismatches"] += 1
                            self._fail("mismatch", "terminal body "
                                       "mismatches journal")
                            return
                        self.done_body = body
                        rep.failure_streak = 0
                        if self.router._obs:
                            self.router._m_forward.observe(
                                (time.perf_counter() - t0) * 1e3,
                                replica=rep.name)
                        self.status = "done"
                        self._notify()
                        return
                    elif "err" in ev:
                        rec = ev["err"]
                        # engine-truth partial reconciliation: the
                        # failure path surfaces tokens the stream may
                        # not have delivered yet (ISSUE 15 satellite)
                        part = rec.get("partial_tokens")
                        if part:
                            j.extend(self.base, part, rep.name)
                        self.router._note_failure(rep)
                        self._fail("io", str(rec.get("error", "err")))
                        return
            # EOF without a terminal record: the replica died mid-write
            self.router._note_failure(rep)
            self._fail("io", "stream truncated (replica died "
                             "mid-decode)")
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read())
            except (ValueError, OSError, http.client.HTTPException):
                body = {"error": f"http_{e.code}"}
            if e.code == 503:
                # truthful shed from a live server: retry elsewhere,
                # honoring ITS Retry-After hint — no breaker hit
                self.retry_after = _retry_after_hint(body)
                self.body = body
                self._fail("shed", str(body.get("error", "shed")))
            elif e.code >= 500:
                self.router._note_failure(rep)
                self._fail("io", str(body.get("error", f"http {e.code}")))
            else:
                self.code, self.body = e.code, body
                self._fail("client_error",
                           str(body.get("error", e.code)))
        except _resil.FaultInjected as e:
            self.router._note_failure(rep)
            self._fail("io", str(e))
        except _REPLICA_IO_ERRORS as e:
            if self._cancelled.is_set():
                self._fail("cancelled", "cancelled by coordinator")
            else:
                self.router._note_failure(rep)
                self._fail("io", str(e))
        except Exception as e:   # noqa: BLE001 — an attempt thread
            # must never die silently: every outcome is classified
            self._fail("io", f"{type(e).__name__}: {e}")
        finally:
            if span is not None:
                _obs.trace.end_span(span)
            with self.router._lock:
                rep.inflight -= 1
            if self.is_hedge:
                # pairs with the coordinator's _reserve_hedge: the
                # budget slot frees when the backup terminates (win,
                # loss, or cancellation)
                self.router._release_hedge()

    def _fail(self, kind: str, reason: str):
        self.kind = kind
        self.reason = str(reason)
        self.status = "failed"
        self._notify()

    def _notify(self):
        with self.j.cond:
            self.j.cond.notify_all()

    def cancel(self):
        """Best-effort loser-side cancellation: stop reading, then
        tell the replica to retire the engine request NOW (future
        cancel -> slot retire -> pages freed) instead of letting the
        duplicate decode to completion."""
        self._cancelled.set()
        resp = self._resp
        if resp is not None:
            # shut the raw SOCKET down, never resp.close(): the reader
            # thread blocked in readline() holds the BufferedReader's
            # internal lock, so close() from here would block until
            # the (possibly wedged) replica sends bytes again —
            # shutdown() needs no buffer lock and pops the blocked
            # recv with EOF instead
            try:
                sock = getattr(getattr(resp, "fp", None), "raw", None)
                sock = getattr(sock, "_sock", None)
                if sock is not None:
                    sock.shutdown(socket.SHUT_RDWR)
            except (OSError, AttributeError, ValueError):
                pass
        if self.rep.base_url and self.streamed:
            try:
                req = urllib.request.Request(
                    self.rep.base_url + "/cancel",
                    json.dumps({"request_id": self.rid}).encode(),
                    {"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=2.0):
                    pass
                self.router.stats_counters["cancels_sent"] += 1
                if self.router._obs:
                    self.router._m_cancels.inc()
            except _REPLICA_IO_ERRORS:
                pass             # dead replica: nothing left to cancel


# ---------------------------------------------------------------------------
# Per-tenant QoS admission (ISSUE 16): weighted-fair scheduler
# ---------------------------------------------------------------------------

class _QosWaiter:
    __slots__ = ("tenant", "qcls", "prio", "enq_at", "admitted")

    def __init__(self, tenant: str, qcls: str, prio: int, enq_at: float):
        self.tenant = tenant
        self.qcls = qcls
        self.prio = prio
        self.enq_at = enq_at
        self.admitted = False


class _QosScheduler:
    """Weighted-fair admission over the tier's serving capacity.

    ``capacity`` requests run concurrently; everyone else waits in a
    single ordered list and is dispatched strict-priority-first
    (:data:`QOS_CLASSES`), weighted-fair inside one priority tier —
    the tenant with the smallest weight-normalized token charge goes
    next, FIFO within a tenant. Charges accrue at release from the
    journal's own accounting (tokens actually generated), so a tenant
    burning long generations yields to one sipping short ones even at
    equal request rates. Starvation-freedom is explicit: any waiter
    older than ``starvation_s`` is served next regardless of class.

    Overload degrades truthfully per class: each class's wait queue is
    bounded at ``queue_limit x weight`` (batch fills and sheds first),
    and a shed's Retry-After derives from the OBSERVED drain rate —
    requests ahead at this priority divided by the EWMA of recent
    completions/second — never a made-up constant.

    Standalone (no router reference, injectable clock) so fairness is
    unit-testable without processes.
    """

    def __init__(self, capacity: int, queue_limit: int = 8,
                 starvation_s: float = 5.0, clock=time.monotonic):
        self.capacity = int(capacity)
        self.queue_limit = int(queue_limit)
        self.starvation_s = float(starvation_s)
        self._clock = clock
        self._cv = _obs.make_condition("qos.cv")
        self._inflight = 0
        self._waiting: List[_QosWaiter] = []     # enqueue order
        self._charge: Dict[str, float] = {}      # weight-normalized
        self._drain_ewma = 0.0                   # completions / second
        self._last_done: Optional[float] = None
        self.admitted_total = 0
        self.shed_total = 0

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    @staticmethod
    def class_of(qcls) -> str:
        q = str(qcls or QOS_DEFAULT)
        return q if q in QOS_CLASSES else QOS_DEFAULT

    def try_acquire(self, tenant: str, qcls: str, timeout: float):
        """Block until admitted or refused. Returns ``("admitted",
        None)``, ``("shed", retry_after_s)`` (class queue full) or
        ``("timeout", retry_after_s)`` (budget burned waiting)."""
        if not self.enabled:
            return "admitted", None
        prio, weight = QOS_CLASSES[self.class_of(qcls)]
        deadline = self._clock() + max(0.0, float(timeout))
        with self._cv:
            if self._inflight < self.capacity and not self._waiting:
                self._admit_locked(tenant)
                return "admitted", None
            cap = max(1, int(self.queue_limit * weight))
            if sum(1 for w in self._waiting if w.qcls == qcls) >= cap:
                self.shed_total += 1
                return "shed", self._retry_after_locked(prio)
            w = _QosWaiter(tenant, self.class_of(qcls), prio,
                           self._clock())
            self._waiting.append(w)
            while True:
                if w.admitted:
                    return "admitted", None
                left = deadline - self._clock()
                if left <= 0:
                    self._waiting.remove(w)
                    self.shed_total += 1
                    return "timeout", self._retry_after_locked(prio)
                self._cv.wait(timeout=min(left, 0.25))

    def release(self, tenant: str, qcls: str, tokens: int = 0):
        """One admitted request finished: charge its tenant the tokens
        it actually generated (journal-accounted), fold the completion
        into the drain-rate EWMA, dispatch the next waiter(s)."""
        _, weight = QOS_CLASSES[self.class_of(qcls)]
        with self._cv:
            self._inflight = max(0, self._inflight - 1)
            base = min(self._charge.values()) if self._charge else 0.0
            cur = self._charge.get(tenant, base)
            self._charge[tenant] = cur + max(0, int(tokens)) / weight
            if len(self._charge) > 1024:
                # bound the ledger: keep the busiest tenants, the rest
                # re-enter at the floor (no fairness cliff)
                top = sorted(self._charge.items(), key=lambda kv: -kv[1])
                self._charge = dict(top[:512])
            now = self._clock()
            if self._last_done is not None:
                inst = 1.0 / max(1e-3, now - self._last_done)
                self._drain_ewma = (inst if self._drain_ewma <= 0
                                    else 0.8 * self._drain_ewma
                                    + 0.2 * inst)
            self._last_done = now
            self._dispatch_locked()

    def _admit_locked(self, tenant: str):
        self._inflight += 1
        self.admitted_total += 1
        # a tenant first seen now starts at the CURRENT floor, not 0 —
        # otherwise arriving late would outrank every incumbent forever
        if tenant not in self._charge and self._charge:
            self._charge[tenant] = min(self._charge.values())

    def _dispatch_locked(self):
        while self._waiting and self._inflight < self.capacity:
            w = self._pick_locked()
            self._waiting.remove(w)
            w.admitted = True
            self._admit_locked(w.tenant)
        self._cv.notify_all()

    def _pick_locked(self) -> _QosWaiter:
        now = self._clock()
        aged = [w for w in self._waiting
                if now - w.enq_at >= self.starvation_s]
        if aged:
            # starvation-freedom beats class policy: the oldest waiter
            # goes, whatever its class
            return min(aged, key=lambda w: w.enq_at)
        top = min(w.prio for w in self._waiting)
        return min((w for w in self._waiting if w.prio == top),
                   key=lambda w: (self._charge.get(w.tenant, 0.0),
                                  w.enq_at))

    def _retry_after_locked(self, prio: int) -> float:
        """Honest Retry-After: work that drains before a retry at this
        priority could land (in-flight + same-or-higher-priority
        waiters) over the observed drain rate. Cold start (no
        completion observed yet) answers a conservative 1 s."""
        ahead = self._inflight + sum(1 for w in self._waiting
                                     if w.prio <= prio)
        if self._drain_ewma <= 0:
            return 1.0
        return round(min(60.0, max(0.05, (ahead + 1)
                                   / self._drain_ewma)), 3)

    def snapshot(self) -> dict:
        with self._cv:
            by_cls: Dict[str, int] = {}
            for w in self._waiting:
                by_cls[w.qcls] = by_cls.get(w.qcls, 0) + 1
            return {"capacity": self.capacity,
                    "inflight": self._inflight,
                    "waiting": len(self._waiting),
                    "waiting_by_class": by_cls,
                    "admitted_total": self.admitted_total,
                    "shed_total": self.shed_total,
                    "drain_per_s": round(self._drain_ewma, 3),
                    "tenants_charged": len(self._charge)}


# ---------------------------------------------------------------------------
# Client-facing stream relay (ISSUE 16): the journal IS the stream
# ---------------------------------------------------------------------------

class _ClientRelay(threading.Thread):
    """Streams one journaled request to the CLIENT as NDJSON — the
    replica stream contract verbatim ({"t": [...]} blocks, one
    terminal {"done": body} / {"err": record}, read-until-close), so
    a tier client and a single-replica client parse identically.

    The shared :class:`_ReqJournal` is the ONE token source. The relay
    tails it from its own read frontier (``sent``) under the journal
    condition, which is exactly what makes mid-stream failover
    invisible: a replica kill, hedge win, or rolling restart swaps the
    PRODUCER under the journal while position-verified extends refuse
    conflicts and gaps — the relay can neither re-emit a position nor
    skip one, so the client stream is zero-loss, zero-duplicate and
    bitwise-identical to the undisturbed run by greedy determinism.

    A write failing mid-stream means the client went away: ``dead``
    flips, the journal cond wakes the coordinator, and the coordinator
    cancels every live attempt — engine slot retired, KV pages freed
    on whichever replica currently owns the request. The terminal line
    is handed over by the coordinator via :meth:`finish` so error
    bodies (deadline, backend-gone) reach a mid-stream client as a
    truthful ``err`` record instead of a bare EOF."""

    def __init__(self, handler, rid: Optional[str]):
        super().__init__(daemon=True,
                         name=f"tier-relay-{rid or 'anon'}")
        self.handler = handler
        self.rid = rid
        self.started_http = False     # 200 + NDJSON head on the wire
        self.dead = False             # client disconnected
        self.sent = 0                 # relay frontier (tokens emitted)
        self._st: Optional[_ReqJournal] = None
        self._terminal = None         # ("done"|"err", body)
        self._done = threading.Event()

    def begin(self, st: _ReqJournal):
        """Arm on the journal and start streaming. Called by the
        coordinator once the request is committed to the journaled
        path (first attempt launched) — every earlier failure stays a
        plain JSON response."""
        self._st = st
        self.start()

    def finish(self, kind: str, body: dict):
        """Coordinator hands over the terminal line; blocks (bounded)
        until the relay has flushed trailing tokens + terminal."""
        st = self._st
        if st is None:
            return
        with st.cond:
            self._terminal = (kind, dict(body))
            st.cond.notify_all()
        self._done.wait(timeout=10.0)

    def run(self):
        st, h = self._st, self.handler
        t0 = time.perf_counter()
        try:
            h.send_response(200)
            h.send_header("Content-Type", "application/x-ndjson")
            h.send_header("Connection", "close")
            h.end_headers()
            h.close_connection = True
            self.started_http = True
            while True:
                with st.cond:
                    while (len(st.tokens) <= self.sent
                           and self._terminal is None):
                        st.cond.wait(timeout=0.25)
                    toks = list(st.tokens[self.sent:])
                    term = self._terminal
                if toks:
                    self._write({"t": toks})
                    self.sent += len(toks)
                    continue      # terminal never jumps the token queue
                if term is not None:
                    kind, body = term
                    self._write({kind: body})
                    return
        except (BrokenPipeError, ConnectionError, OSError):
            self.dead = True
            if st is not None:
                with st.cond:
                    st.cond.notify_all()   # wake the coordinator NOW
        finally:
            if _obs.enabled():
                now = time.perf_counter()
                _obs.record_span("router.stream_relay", t0, now,
                                 cat="router", request_id=self.rid,
                                 tokens=self.sent,
                                 disconnected=self.dead)
            self._done.set()

    def _write(self, obj):
        self.handler.wfile.write((json.dumps(obj) + "\n").encode())
        self.handler.wfile.flush()


# ---------------------------------------------------------------------------
# Router
# ---------------------------------------------------------------------------

class Router:
    """Health-aware load balancer + supervisor over N replica
    subprocesses (module docstring has the full story).

    ``replicas`` is the starting count; ``min_replicas``/
    ``max_replicas`` bound the autoscaler (equal min/max = autoscaling
    off). ``exec_store_dir`` (or the inherited
    ``PADDLE_TPU_EXEC_STORE_DIR``) is the shared executable store every
    replica warms from.
    """

    def __init__(self, spec: ReplicaSpec, replicas: int = 2,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 deadline_s: Optional[float] = None,
                 retries: Optional[int] = None,
                 poll_s: Optional[float] = None,
                 eject_s: Optional[float] = None,
                 breaker_threshold: int = 3,
                 unreachable_after: int = 3,
                 restart_unreachable_after: int = 10,
                 respawn: bool = True,
                 scale_up_queued: Optional[int] = None,
                 scale_cycles: int = 3,
                 scale_cooldown_s: float = 30.0,
                 crash_loop_budget: Optional[int] = None,
                 crash_loop_window_s: Optional[float] = None,
                 respawn_policy: Optional[_resil.RetryPolicy] = None,
                 exec_store_dir: Optional[str] = None,
                 jax_cache_dir: Optional[str] = None,
                 workdir: Optional[str] = None,
                 recovery: bool = True,
                 hedge_s: Optional[float] = None,
                 hedge_mult: Optional[float] = None,
                 hedge_frac: Optional[float] = None,
                 journal_max: Optional[int] = None,
                 ttft_hedge_s: Optional[float] = None,
                 ttft_hedge_mult: Optional[float] = None,
                 affinity_w: Optional[float] = None,
                 prewarm: Optional[bool] = None,
                 qos_concurrency: Optional[int] = None,
                 qos_queue_limit: Optional[int] = None,
                 qos_starvation_s: Optional[float] = None):
        if replicas < 1:
            raise ValueError("need at least one replica")
        self.spec = spec
        self.min_replicas = int(min_replicas if min_replicas is not None
                                else replicas)
        self.max_replicas = int(max_replicas if max_replicas is not None
                                else replicas)
        if not (1 <= self.min_replicas <= replicas <= self.max_replicas):
            raise ValueError("need 1 <= min <= replicas <= max")
        self._initial = int(replicas)
        self.deadline_s = (deadline_s if deadline_s is not None
                           else _env_float("PADDLE_TPU_TIER_DEADLINE",
                                           60.0))
        retries = int(retries if retries is not None
                      else _env_float("PADDLE_TPU_TIER_RETRIES", 2))
        # the ONE retry schedule (resilience.RetryPolicy): full-jitter
        # backoff decorrelates concurrent retriers; each run() gets the
        # request's remaining budget as its retry-time deadline
        self.retry_policy = _resil.RetryPolicy(
            max_attempts=max(1, retries + 1), base_delay=0.05,
            max_delay=0.5, full_jitter=True,
            retry_on=(_RetryableForward,))
        self.poll_s = (poll_s if poll_s is not None
                       else _env_float("PADDLE_TPU_TIER_POLL_S", 0.5))
        self.eject_s = (eject_s if eject_s is not None
                        else _env_float("PADDLE_TPU_TIER_EJECT_S", 5.0))
        self.breaker_threshold = int(breaker_threshold)
        self.unreachable_after = int(unreachable_after)
        self.restart_unreachable_after = int(restart_unreachable_after)
        self.respawn = bool(respawn)
        # crash-loop governance: a replica dying at startup no longer
        # respawns immediately and forever — escalating backoff, then
        # give-up (counted as crash_loops in stats and /healthz)
        self.respawn_governor = RespawnGovernor(
            budget=int(crash_loop_budget if crash_loop_budget is not None
                       else _env_float("PADDLE_TPU_TIER_CRASH_BUDGET", 5)),
            window_s=(crash_loop_window_s
                      if crash_loop_window_s is not None
                      else _env_float("PADDLE_TPU_TIER_CRASH_WINDOW_S",
                                      10.0)),
            policy=respawn_policy)
        self._pending_respawns = 0
        self._respawn_at = 0.0
        self._last_fast_death = 0.0
        # autoscaler watermarks: scale up when aggregate queued tokens
        # requests exceed this for scale_cycles consecutive polls
        slots = int(self.spec.engine.get("slots", 8))
        self.scale_up_queued = (int(scale_up_queued)
                                if scale_up_queued is not None
                                else max(1, slots // 2))
        self.scale_cycles = int(scale_cycles)
        self.scale_cooldown_s = float(scale_cooldown_s)
        # work-conserving recovery + hedged decode (ISSUE 15)
        self.recovery = bool(recovery)
        self.hedge_s = (float(hedge_s) if hedge_s is not None
                        else _env_float("PADDLE_TPU_TIER_HEDGE_S",
                                        -1.0))
        self.hedge_mult = (float(hedge_mult) if hedge_mult is not None
                           else _env_float("PADDLE_TPU_TIER_HEDGE_MULT",
                                           20.0))
        # tier-wide hedge budget (Tail-at-Scale style): backups may
        # occupy at most this fraction of the live journaled requests
        # (floor 1, so a lone straggler always gets its backup). The
        # per-request stall clock starts at submission, which under
        # saturation makes EVERY queued request look silent — without
        # this cap a loaded tier would hedge itself into double load
        # exactly when it has no headroom.
        self.hedge_frac = (float(hedge_frac) if hedge_frac is not None
                           else _env_float("PADDLE_TPU_TIER_HEDGE_FRAC",
                                           0.25))
        self._hedges_live = 0        # concurrent backups, tier-wide
        self.journal_max = int(
            journal_max if journal_max is not None
            else _env_float("PADDLE_TPU_TIER_JOURNAL_REQS", 128))
        self._journaled = 0          # live journals (bounded)
        self._recovered_rids: List[dict] = []   # since last flight dump
        self._last_recovery_dump = 0.0
        # streaming-first QoS front (ISSUE 16)
        self.ttft_hedge_s = (
            float(ttft_hedge_s) if ttft_hedge_s is not None
            else _env_float("PADDLE_TPU_TIER_TTFT_HEDGE_S", -1.0))
        self.ttft_hedge_mult = (
            float(ttft_hedge_mult) if ttft_hedge_mult is not None
            else _env_float("PADDLE_TPU_TIER_TTFT_MULT", 3.0))
        self.affinity_w = (
            float(affinity_w) if affinity_w is not None
            else _env_float("PADDLE_TPU_TIER_AFFINITY_W", 0.5))
        # standby prefix pre-warming (ISSUE 17): while a journaled
        # stream runs, the router feeds the prompt+journal prefix to a
        # standby replica's paged KV trie ahead of any failover, so a
        # cutover's resumed prefill lands on trie hits instead of
        # recomputing the prefix. PADDLE_TPU_TIER_PREWARM=0 disables.
        self.prewarm = (bool(prewarm) if prewarm is not None
                        else _env_float("PADDLE_TPU_TIER_PREWARM",
                                        1.0) > 0)
        qos_cap = (int(qos_concurrency) if qos_concurrency is not None
                   else int(_env_float(
                       "PADDLE_TPU_TIER_QOS_CONCURRENCY", -1)))
        if qos_cap < 0:
            # derived default: what the tier can actually decode at
            # once — engine slots per replica times the replica ceiling
            qos_cap = max(4, int(self.spec.engine.get("slots", 8))
                          * self.max_replicas)
        self.qos = _QosScheduler(
            capacity=qos_cap,
            queue_limit=(int(qos_queue_limit)
                         if qos_queue_limit is not None
                         else int(_env_float("PADDLE_TPU_TIER_QOS_QUEUE",
                                             8))),
            starvation_s=(float(qos_starvation_s)
                          if qos_starvation_s is not None
                          else _env_float(
                              "PADDLE_TPU_TIER_QOS_STARVATION_S", 5.0)))
        self.exec_store_dir = (exec_store_dir
                               or os.environ.get("PADDLE_TPU_EXEC_STORE_DIR"))

        self._owns_workdir = workdir is None
        self.workdir = workdir or tempfile.mkdtemp(prefix="paddle_tpu_tier_")
        os.makedirs(self.workdir, exist_ok=True)
        # the executable store covers the big engine programs; the jax
        # persistent cache covers the tiny eager helper ops — BOTH are
        # needed for a successor to reach ready with zero XLA compiles.
        # Tier-private by default (only this tier's own single-device
        # entries can ever land in it — the multi-device reload hazard
        # tests/conftest.py documents cannot arise); "" disables.
        self.jax_cache_dir = (jax_cache_dir if jax_cache_dir is not None
                              else os.path.join(self.workdir,
                                                "xla_cache"))

        self._lock = _obs.make_rlock("router.lock")
        self._replicas: List[Replica] = []
        self._seq = 0
        self._stopping = False
        self._started = time.monotonic()
        self._rolling_lock = _obs.make_lock("router.rolling")
        self._rolling = False
        self._control_thread: Optional[threading.Thread] = None
        self._up_streak = 0          # autoscaler pressure counters
        self._idle_streak = 0
        self._last_scale = 0.0
        self.stats_counters = {
            "forwards": 0, "retries": 0, "tier_unavailable_503": 0,
            "deadline_503": 0, "relayed_503": 0, "backend_503": 0,
            "respawns": 0, "ejections": 0, "rolling_restarts": 0,
            "scale_ups": 0, "scale_downs": 0, "spawn_failures": 0,
            "crash_loops": 0,
            # work-conserving recovery + hedging (ISSUE 15)
            "recoveries": 0, "hedges": 0, "hedge_wins": 0,
            "cancels_sent": 0, "resume_fallbacks": 0,
            "recovery_mismatches": 0,
            # streaming-first QoS front (ISSUE 16)
            "streams": 0, "client_disconnects": 0,
            "ttft_hedges": 0, "qos_admitted": 0, "qos_shed": 0,
            # standby prefix pre-warming (ISSUE 17)
            "prewarms": 0, "prewarmed_resumes": 0,
        }
        # observability (paddle_tpu.obs): the stats above keep their
        # dict face (/healthz, tests); the registry carries the
        # exported view — per-replica forward latency (BOUNDED label
        # set: replica names grow r1..rN over months of restarts, the
        # histogram folds overflow into one _other series), retry and
        # ejection counters, breaker state. /metrics additionally
        # scrapes every replica and aggregates ptpu_tier_* series.
        self._obs = _obs.enabled()
        if self._obs:
            reg = _obs.metrics.registry
            self._m_forward = reg.histogram(
                "ptpu_router_forward_ms",
                "router->replica forward latency (successes)",
                labels=("replica",), max_series=32)
            self._m_forwards = reg.counter(
                "ptpu_router_forwards_total", "forwarded requests")
            self._m_retries = reg.counter(
                "ptpu_router_retries_total",
                "forward attempts retried on another replica")
            self._m_ejections = reg.counter(
                "ptpu_router_ejections_total",
                "circuit-breaker ejections")
            self._m_breaker = reg.gauge(
                "ptpu_router_breaker_open",
                "1 while the replica is breaker-ejected",
                labels=("replica",), max_series=32)
            self._m_ready = reg.gauge(
                "ptpu_router_ready_replicas", "routable replicas")
            self._m_recoveries = reg.counter(
                "ptpu_router_recoveries_total",
                "journaled requests resumed on another replica after "
                "a mid-decode failure (work-conserving failover)")
            self._m_hedges = reg.counter(
                "ptpu_router_hedges_total",
                "backup decodes launched for stalled requests")
            self._m_hedge_wins = reg.counter(
                "ptpu_router_hedge_wins_total",
                "hedged backups that beat the stalled primary")
            self._m_cancels = reg.counter(
                "ptpu_router_cancels_total",
                "loser-side /cancel requests sent to replicas")
            # inter-progress gaps of streamed forwards: the LIVE
            # decode-latency signal the hedge budget derives from
            self._m_progress = reg.histogram(
                "ptpu_router_token_progress_ms",
                "gap between successive token-progress events across "
                "journaled requests",
                buckets=(1, 2, 5, 10, 25, 50, 100, 250, 500, 1000,
                         2500, 5000, 10000))
            # streaming-first QoS front (ISSUE 16). The unlabeled TTFT
            # histogram feeds the TTFT hedge budget (snap() on a
            # labeled family needs exact labels — budget derivation
            # must stay label-free); the ptpu_tier_* families are the
            # per-class client-facing view, named in tier space
            # directly since render_tier passes router-own series
            # through verbatim (replica aggregates land under
            # different names).
            _lat_buckets = (1, 2, 5, 10, 25, 50, 100, 250, 500, 1000,
                            2500, 5000, 10000, 30000)
            self._m_ttft = reg.histogram(
                "ptpu_router_ttft_ms",
                "submission-to-first-token latency of journaled "
                "requests (the TTFT hedge budget derives from its "
                "p99)", buckets=_lat_buckets)
            self._m_ttft_class = reg.histogram(
                "ptpu_tier_ttft_ms",
                "per-QoS-class submission-to-first-token latency",
                labels=("qos_class",), max_series=8,
                buckets=_lat_buckets)
            self._m_itl_class = reg.histogram(
                "ptpu_tier_itl_ms",
                "per-QoS-class inter-token latency (journal progress "
                "gaps past the first token)",
                labels=("qos_class",), max_series=8,
                buckets=_lat_buckets)
            self._m_qos_admitted = reg.counter(
                "ptpu_tier_qos_admitted_total",
                "requests admitted by the weighted-fair scheduler",
                labels=("qos_class",), max_series=8)
            self._m_qos_shed = reg.counter(
                "ptpu_tier_qos_shed_total",
                "requests shed (429) or queue-timed-out by the "
                "weighted-fair scheduler",
                labels=("qos_class",), max_series=8)
            self._m_streams = reg.counter(
                "ptpu_router_streams_total",
                "client-facing NDJSON stream relays started")
            self._m_disconnects = reg.counter(
                "ptpu_router_client_disconnects_total",
                "mid-stream client disconnects propagated to "
                "cancellation")
            self._m_ttft_hedges = reg.counter(
                "ptpu_router_ttft_hedges_total",
                "backups launched for admission (first-token) stalls")
            # standby prefix pre-warming (ISSUE 17)
            self._m_prewarms = reg.counter(
                "ptpu_router_prewarms_total",
                "journaled prefixes pre-warmed on standby replicas")
            self._m_prewarmed_resumes = reg.counter(
                "ptpu_router_prewarmed_resumes_total",
                "resumes/hedges that landed on a replica whose trie "
                "the router had pre-warmed for that request")

        self.httpd = ThreadingHTTPServer((host, port),
                                         self._make_handler())
        self.host, self.port = self.httpd.server_address[:2]
        self._http_thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------
    def start(self):
        """Spawn the initial replicas (in parallel; they become
        routable as their health flips), start the control loop and the
        HTTP front. Non-blocking — use wait_ready() to gate traffic."""
        for _ in range(self._initial):
            try:
                self._spawn_replica()
            except Exception:
                self.stats_counters["spawn_failures"] += 1
                # the control loop keeps trying to reach min_replicas
        self._control_thread = threading.Thread(
            target=self._control_loop, daemon=True, name="tier-control")
        self._control_thread.start()
        self._http_thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True,
            name="tier-http")
        self._http_thread.start()
        return self

    def wait_ready(self, count: Optional[int] = None,
                   timeout: float = 300.0) -> bool:
        """Block until ``count`` (default min_replicas) replicas are
        routable, or the timeout passes (False)."""
        want = self.min_replicas if count is None else int(count)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.ready_count() >= want:
                return True
            time.sleep(0.05)
        return False

    def ready_count(self) -> int:
        now = time.monotonic()
        with self._lock:
            return sum(1 for r in self._replicas if r.routable(now))

    def replicas(self) -> List[dict]:
        with self._lock:
            return [r.snapshot() for r in self._replicas]

    def stop(self, drain_s: float = 0.0):
        """Tear the tier down: stop routing, retire every replica
        (graceful when ``drain_s`` > 0), stop the HTTP front."""
        with self._lock:
            self._stopping = True
            reps = list(self._replicas)
        for r in reps:
            self._terminate(r, drain_timeout=drain_s)
        with self._lock:
            self._replicas.clear()
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._http_thread is not None:
            self._http_thread.join(timeout=5)
        if self._control_thread is not None:
            self._control_thread.join(timeout=self.poll_s * 4 + 1)
        if self._owns_workdir:
            shutil.rmtree(self.workdir, ignore_errors=True)

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.stop()
        return False

    def _stopping_flag(self) -> bool:
        with self._lock:
            return self._stopping

    def _rolling_flag(self) -> bool:
        with self._lock:
            return self._rolling

    # -- spawn / retire (the ONE path restarts + autoscaling share) ------
    def _spawn_replica(self) -> Replica:
        _resil.maybe_inject("replica_spawn")
        with self._lock:
            self._seq += 1
            name = f"r{self._seq}"
        port_file = os.path.join(self.workdir, f"{name}.port")
        log_path = os.path.join(self.workdir, f"{name}.log")
        env = dict(os.environ)
        if self.exec_store_dir:
            env["PADDLE_TPU_EXEC_STORE_DIR"] = self.exec_store_dir
        if self.jax_cache_dir:
            env.setdefault("JAX_COMPILATION_CACHE_DIR",
                           self.jax_cache_dir)
            env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                           "0")
        # children must resolve `-m paddle_tpu.inference.router`
        # wherever the router process happens to run from
        pkg_parent = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = (pkg_parent + os.pathsep + env["PYTHONPATH"]
                             if env.get("PYTHONPATH") else pkg_parent)
        env.update(self.spec.env)
        if getattr(self.spec, "tp", 1) > 1:
            # a TP-slice replica needs its tp devices visible: on the
            # cpu/virtual-mesh platform that means forcing the host
            # device count (a scrubbed single-device env would make
            # build_tp_mesh fail loudly in the child)
            flags = [f for f in env.get("XLA_FLAGS", "").split()
                     if not f.startswith(
                         "--xla_force_host_platform_device_count")]
            flags.append("--xla_force_host_platform_device_count="
                         f"{self.spec.tp}")
            env["XLA_FLAGS"] = " ".join(flags)
        log_f = open(log_path, "ab")
        try:
            proc = subprocess.Popen(
                self.spec.argv(port_file), env=env,
                stdout=log_f, stderr=subprocess.STDOUT,
                cwd=os.getcwd())
        finally:
            log_f.close()        # child holds its own fd now
        rep = Replica(name, proc, port_file, log_path, self.spec.host)
        with self._lock:
            self._replicas.append(rep)
        return rep

    @staticmethod
    def _read_port(rep: Replica) -> bool:
        """Pick up the port the child published (atomic file); True
        once known."""
        if rep.port is not None:
            return True
        try:
            with open(rep.port_file) as f:
                rep.port = int(f.read().strip())
            return True
        except (OSError, ValueError):
            return False

    def _wait_replica_ready(self, rep: Replica, timeout: float) -> bool:
        """Poll the port file, then /healthz, until the replica reports
        ready. Runs health updates inline so a caller (rolling restart)
        does not depend on control-loop timing."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if not rep.alive():
                return False
            if not self._read_port(rep):
                time.sleep(0.05)
                continue
            self._poll_health(rep)
            if rep.state == "ready":
                return True
            time.sleep(0.1)
        return False

    def _terminate(self, rep: Replica, drain_timeout: float = 0.0):
        """Retire one replica: pull it from rotation, ask it to drain,
        wait (bounded) for in-flight work, then SIGTERM -> SIGKILL."""
        rep.draining = True                 # out of rotation NOW
        if drain_timeout and drain_timeout > 0 and rep.base_url \
                and rep.alive():
            try:
                req = urllib.request.Request(rep.base_url + "/drain",
                                             b"{}")
                with urllib.request.urlopen(req, timeout=2.0):
                    pass
            except (urllib.error.URLError, OSError, ValueError):
                pass                        # dead/wedged: just kill it
            deadline = time.monotonic() + drain_timeout
            while time.monotonic() < deadline and rep.alive():
                if rep.inflight <= 0 and self._polled_inflight(rep) == 0:
                    break
                time.sleep(0.05)
        if rep.alive():
            # SIGTERM runs the child's stop(drain_s) path — a second,
            # in-process bounded drain — then a clean exit
            try:
                rep.proc.terminate()
                rep.proc.wait(timeout=max(5.0, drain_timeout + 5.0))
            except (subprocess.TimeoutExpired, OSError):
                try:
                    rep.proc.kill()
                    rep.proc.wait(timeout=5.0)
                except OSError:
                    pass
        rep.state = "dead"
        with self._lock:
            if rep in self._replicas:
                self._replicas.remove(rep)
        self._drop_replica_series(rep)
        for p in (rep.port_file,):
            try:
                os.unlink(p)
            except OSError:
                pass

    def _drop_replica_series(self, rep: Replica):
        """A retired/dead replica's breaker gauge must not export 1
        forever (its name never comes back — respawns mint fresh ones)
        nor hold a slot against the family's series cap."""
        if self._obs:
            self._m_breaker.remove(replica=rep.name)

    def _polled_inflight(self, rep: Replica) -> int:
        """One direct /healthz read of the replica's in-flight count
        (drain progress); unreachable reads as drained."""
        if rep.base_url is None:
            return 0
        try:
            with urllib.request.urlopen(rep.base_url + "/healthz",
                                        timeout=1.0) as r:
                return int(json.loads(r.read()).get("inflight", 0))
        except urllib.error.HTTPError as e:
            try:
                return int(json.loads(e.read()).get("inflight", 0))
            except (ValueError, OSError, http.client.HTTPException):
                return 0
        except _REPLICA_IO_ERRORS:
            return 0

    # -- health polling / supervision ------------------------------------
    def _poll_health(self, rep: Replica):
        if rep.base_url is None:
            return
        try:
            _resil.maybe_inject("replica_health")
            with urllib.request.urlopen(rep.base_url + "/healthz",
                                        timeout=max(1.0, self.poll_s * 2)
                                        ) as r:
                body = json.loads(r.read())
            rep.health = body
            try:
                fps = (body.get("engine") or {}).get(
                    "prefix_fingerprints")
                rep.prefix_fps = (frozenset(int(h) for h in fps)
                                  if fps else frozenset())
            except (TypeError, ValueError):
                rep.prefix_fps = frozenset()
            rep.health_fail_streak = 0
            rep.last_health_at = time.monotonic()
            rep.state = "ready"
            rep.was_ready = True
            if self._obs:
                self._m_breaker.set(
                    1.0 if time.monotonic() < rep.ejected_until else 0.0,
                    replica=rep.name)
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read())
            except (ValueError, OSError, http.client.HTTPException):
                body = {}
            rep.health = body
            rep.health_fail_streak = 0
            rep.last_health_at = time.monotonic()  # answered, just 503
            status = body.get("status", "unready")
            rep.state = status if status in ("warming", "draining") \
                else "unready"
        except (_resil.FaultInjected,) + _REPLICA_IO_ERRORS:
            rep.health_fail_streak += 1
            if rep.health_fail_streak >= self.unreachable_after:
                # a wedged replica answers nothing but its process
                # lives: it must leave the rotation just like a dead one
                rep.state = "unreachable"

    def _control_loop(self):
        while True:
            time.sleep(self.poll_s)
            with self._lock:
                if self._stopping:
                    return
                reps = list(self._replicas)
            dead = []
            for rep in reps:
                if rep.draining:
                    continue
                if not rep.alive():
                    rep.state = "dead"
                    dead.append(rep)
                    continue
                if not self._read_port(rep):
                    continue            # still binding its listener
                self._poll_health(rep)
                if (rep.state == "unreachable"
                        and rep.health_fail_streak
                        >= self.restart_unreachable_after):
                    # wedged beyond hope: treat as dead (kill + respawn)
                    try:
                        rep.proc.kill()
                    except OSError:
                        pass
                    dead.append(rep)
            if dead and not self._stopping_flag():
                # postmortem: dump the flight recorder BEFORE the
                # respawn path erases the scene — the artifact carries
                # the ring (recent forwards, health polls) plus every
                # span still open, i.e. the request ids in flight when
                # the replica died. Best-effort: forensics must never
                # take the tier down with it.
                try:
                    _obs.dump_flight(
                        "replica_death",
                        extra={"replicas": [r.name for r in dead],
                               "pids": [r.proc.pid for r in dead]})
                except Exception:   # noqa: BLE001
                    pass
            now = time.monotonic()
            for rep in dead:
                with self._lock:
                    if rep in self._replicas:
                        self._replicas.remove(rep)
                    stopping = self._stopping
                self._drop_replica_series(rep)
                if stopping or not self.respawn:
                    continue
                # crash-loop governance: a fast death (never became
                # ready, or died inside the window) escalates the next
                # respawn on the backoff schedule; past the budget the
                # respawn is abandoned and counted — no more hot-loop
                prev_streak = self.respawn_governor.streak
                spawn_at = self.respawn_governor.note_death(
                    now - rep.spawned_at,
                    became_ready=rep.was_ready)
                if self.respawn_governor.streak > prev_streak:
                    self._last_fast_death = now
                if spawn_at is None:
                    self.stats_counters["crash_loops"] += 1
                    continue
                self._pending_respawns += 1
                self._respawn_at = max(self._respawn_at, spawn_at)
            # a replica spawned after the latest fast death that
            # reached READY and survived past the window proves the
            # spec healthy again
            if self.respawn_governor.streak:
                for rep in reps:
                    if (rep not in dead and rep.alive() and rep.was_ready
                            and rep.spawned_at >= self._last_fast_death
                            and now - rep.spawned_at
                            > self.respawn_governor.window_s):
                        self.respawn_governor.note_stable()
                        break
            while (self._pending_respawns > 0
                   and not self._stopping_flag()
                   and time.monotonic() >= self._respawn_at):
                self._pending_respawns -= 1
                try:
                    self._spawn_replica()
                    self.stats_counters["respawns"] += 1
                except Exception:
                    self.stats_counters["spawn_failures"] += 1
                    # the slot is still owed a replica: keep the
                    # pending respawn, retry on a later pass instead
                    # of (a) hot-spinning now or (b) dropping it
                    self._pending_respawns += 1
                    self._respawn_at = time.monotonic() + \
                        max(self.poll_s, 0.5)
                    break
            if not self._stopping_flag():
                if self._obs:
                    self._m_ready.set(self.ready_count())
                self._autoscale()
                self._trim_surplus()

    def _trim_surplus(self):
        """Keep the replica count <= max_replicas. A rare race (a
        replica dying exactly as a rolling restart snapshots it) can
        leave one extra; retire the newest, drained, on the next
        pass."""
        with self._lock:
            if self._rolling or self._stopping:
                return
            reps = [r for r in self._replicas if not r.draining]
            if len(reps) <= self.max_replicas:
                return
            victim = max(reps, key=lambda r: r.spawned_at)
        threading.Thread(
            target=self._terminate, args=(victim,),
            kwargs={"drain_timeout": self.spec.drain_s},
            daemon=True, name="tier-trim").start()

    def _autoscale(self):
        if self.max_replicas <= self.min_replicas:
            return
        now = time.monotonic()
        with self._lock:
            if self._rolling:            # restarts own the spawn path
                return
            # draining replicas are leaving: they neither count toward
            # capacity (a drainer must not block a needed scale-up) nor
            # qualify as a scale-down victim (no double-terminate)
            reps = [r for r in self._replicas if not r.draining]
        n = len(reps)
        queued = inflight = active = 0
        for r in reps:
            eng = r.health.get("engine", {}) if r.health else {}
            queued += int(eng.get("queued", 0))
            active += int(eng.get("active", 0))
            inflight += r.inflight
        if queued >= self.scale_up_queued:
            self._up_streak += 1
            self._idle_streak = 0
        elif queued == 0 and active == 0 and inflight == 0:
            self._idle_streak += 1
            self._up_streak = 0
        else:
            self._up_streak = self._idle_streak = 0
        if now - self._last_scale < self.scale_cooldown_s:
            return
        if self._up_streak >= self.scale_cycles and n < self.max_replicas:
            try:
                self._spawn_replica()
                self.stats_counters["scale_ups"] += 1
            except Exception:
                self.stats_counters["spawn_failures"] += 1
            self._last_scale = now
            self._up_streak = 0
        elif (self._idle_streak >= self.scale_cycles
              and n > self.min_replicas):
            # retire the newest replica (oldest have the warmest OS
            # caches); drain first — scale-down must never drop work
            victim = max(reps, key=lambda r: r.spawned_at)
            self.stats_counters["scale_downs"] += 1
            self._last_scale = now
            self._idle_streak = 0
            threading.Thread(
                target=self._terminate, args=(victim,),
                kwargs={"drain_timeout": self.spec.drain_s},
                daemon=True, name="tier-scale-down").start()

    # -- rolling restart -------------------------------------------------
    def rolling_restart(self, ready_timeout: float = 300.0,
                        drain_timeout: Optional[float] = None) -> dict:
        """Replace every replica, one at a time: spawn the successor
        (store-warm — ZERO XLA compiles when the shared executable
        store is primed), wait until it is routable, then drain and
        retire the predecessor. The tier keeps serving throughout —
        capacity never drops below the pre-restart count."""
        if drain_timeout is None:
            drain_timeout = self.spec.drain_s
        if not self._rolling_lock.acquire(blocking=False):
            raise RuntimeError("rolling restart already in progress")
        replaced, failed = [], []
        try:
            with self._lock:
                self._rolling = True
                olds = list(self._replicas)
            for old in olds:
                with self._lock:
                    if self._stopping:
                        break
                    if old not in self._replicas or not old.alive():
                        # died (and the control loop owns its respawn):
                        # replacing it HERE too would double the slot
                        continue
                try:
                    new = self._spawn_replica()
                except Exception as e:
                    self.stats_counters["spawn_failures"] += 1
                    failed.append(f"spawn: {e}")
                    break
                if not self._wait_replica_ready(new, ready_timeout):
                    # successor never came up: keep the predecessor —
                    # a rolling restart must not shrink the tier
                    failed.append(f"{new.name} not ready in "
                                  f"{ready_timeout}s")
                    self._terminate(new, drain_timeout=0.0)
                    break
                self._terminate(old, drain_timeout=drain_timeout)
                replaced.append((old.name, new.name))
            self.stats_counters["rolling_restarts"] += 1
        finally:
            with self._lock:
                self._rolling = False
            self._rolling_lock.release()
        return {"replaced": replaced, "failed": failed,
                "ok": not failed}

    # -- forwarding ------------------------------------------------------
    def _tier_page_size(self) -> int:
        """The paged engines' page size, read from live health (0 when
        the tier is not paged / not yet polled) — what the router
        hashes incoming prompts with for affinity scoring."""
        with self._lock:
            for r in self._replicas:
                eng = r.health.get("engine", {}) if r.health else {}
                if eng.get("paged") and eng.get("page_size"):
                    try:
                        return int(eng["page_size"])
                    except (TypeError, ValueError):
                        return 0
        return 0

    def _pick(self, exclude: set,
              prompt_hashes: Optional[List[int]] = None
              ) -> Optional[Replica]:
        now = time.monotonic()
        with self._lock:
            cands = [r for r in self._replicas
                     if r.name not in exclude and r.routable(now)]
            if not cands:
                return None
            if not prompt_hashes or self.affinity_w <= 0:
                return min(cands, key=Replica.load_score)

            # prefix-affinity blend (ISSUE 16): score = load minus
            # affinity_w per page of cached prefix overlap — a replica
            # already holding the prompt's KV wins ties (and modest
            # load gaps) because routing there turns the prefill into
            # trie hits; load still dominates when the gap is real, so
            # affinity can never pile every shared-prefix client onto
            # one drowning replica. Overlap is the longest chain-hash
            # prefix present in the replica's fingerprint set (chains
            # fold parents in, so membership of hash j implies the
            # whole j-page prefix is cached).
            def score(r: Replica):
                overlap = 0
                if r.prefix_fps:
                    for h in prompt_hashes:
                        if h not in r.prefix_fps:
                            break
                        overlap += 1
                eng = r.health.get("engine", {}) if r.health else {}
                load = r.inflight + 0.5 * (int(eng.get("queued", 0))
                                           + int(eng.get("active", 0)))
                return (load - self.affinity_w * overlap, r.name)
            return min(cands, key=score)

    def _note_failure(self, rep: Replica):
        rep.failure_streak += 1
        if rep.failure_streak >= self.breaker_threshold:
            # circuit breaker: eject for a cooldown; health polls keep
            # running, so a recovered replica rejoins after the window
            rep.ejected_until = time.monotonic() + self.eject_s
            rep.failure_streak = 0
            self.stats_counters["ejections"] += 1
            if self._obs:
                self._m_ejections.inc()
                self._m_breaker.set(1.0, replica=rep.name)

    def forward_generate(self, payload: bytes,
                         deadline_s: Optional[float] = None,
                         request_id: Optional[str] = None,
                         tenant: Optional[str] = None,
                         qos_class: Optional[str] = None,
                         relay: Optional[_ClientRelay] = None):
        """Forward one /generate body. Returns ``(code, body_dict,
        retry_after_or_None)`` — every outcome is a clean JSON
        response, never an exception to the HTTP handler.
        ``request_id`` rides the X-PTPU-Request-Id header on every
        attempt, so the tier's spans (router forward) and the serving
        replica's (engine queue-wait/prefill/decode) correlate under
        one id.

        Token-shaped payloads take the JOURNALED path (streamed
        forward + work-conserving failover + hedged decode, module
        docstring); opaque ones — and overflow past the journal bound
        — fall back to the single-shot forward.

        Every request passes the weighted-fair QoS gate first (tenant
        + class from the caller or the body; queue wait burns the
        request's own deadline, so admission latency is never hidden).
        With ``relay`` set ("stream": true clients) the journaled path
        streams the journal feed to the client as NDJSON; when the
        payload cannot be journaled the stream request is REFUSED
        up-front (400 / 503) rather than breaking the protocol with a
        single-shot JSON body."""
        deadline_s = (self.deadline_s if deadline_s is None
                      else float(deadline_s))
        t0 = time.monotonic()
        self.stats_counters["forwards"] += 1
        if self._obs:
            self._m_forwards.inc()
        parsed = None
        try:
            parsed = json.loads(payload or b"{}")
        except ValueError:
            parsed = None
        if isinstance(parsed, dict):
            tenant = parsed.get("tenant") or tenant
            qos_class = parsed.get("qos_class") or qos_class
        tenant = str(tenant or "anon")
        qcls = _QosScheduler.class_of(qos_class)

        # -- weighted-fair admission (ISSUE 16) ------------------------
        in_qos = False
        if self.qos.enabled:
            state, ra = self.qos.try_acquire(tenant, qcls,
                                             timeout=deadline_s)
            if state != "admitted":
                self.stats_counters["qos_shed"] += 1
                if self._obs:
                    self._m_qos_shed.inc(**{"qos_class": qcls})
                if state == "timeout":
                    # the whole deadline burned waiting in queue: the
                    # 503 face the deadline contract already promises,
                    # with the drain-truthful hint attached
                    self.stats_counters["deadline_503"] += 1
                    return (503, {"error": "deadline_exceeded",
                                  "deadline_s": deadline_s,
                                  "qos_class": qcls,
                                  "tenant": tenant}, ra)
                return (429, {"error": "qos_shed",
                              "qos_class": qcls, "tenant": tenant}, ra)
            in_qos = True
            self.stats_counters["qos_admitted"] += 1
            if self._obs:
                self._m_qos_admitted.inc(**{"qos_class": qcls})

        result = None
        try:
            result = self._dispatch_generate(
                payload, parsed, deadline_s, request_id, t0, qcls,
                relay)
            return result
        finally:
            if in_qos:
                toks = 0
                if result is not None and isinstance(result[1], dict):
                    try:
                        toks = int(result[1].get("tokens_generated", 0))
                    except (TypeError, ValueError):
                        toks = 0
                self.qos.release(tenant, qcls, toks)

    def _dispatch_generate(self, payload: bytes, parsed, deadline_s,
                           request_id, t0, qcls: str,
                           relay: Optional[_ClientRelay]):
        """Route one admitted request: journaled (streaming/recovering)
        path when the payload is token-shaped and the journal has
        room, single-shot otherwise."""
        journal_on = self.recovery and self.journal_max > 0
        if (journal_on and isinstance(parsed, dict)
                and "input_ids" in parsed):
            prompt = _flatten_ids(parsed.get("input_ids"))
            ok = prompt is not None
            if ok:
                try:
                    max_new = int(parsed.get("max_new_tokens", 32))
                    eos = parsed.get("eos_token_id")
                    eos = None if eos is None else int(eos)
                    seed = int(parsed.get("seed", 0))
                except (TypeError, ValueError):
                    ok = False
            if ok and max_new >= 1:
                with self._lock:
                    admit = self._journaled < self.journal_max
                    if admit:
                        self._journaled += 1
                if admit:
                    try:
                        return self._forward_recovering(
                            prompt, max_new, eos, seed, deadline_s,
                            request_id, t0, qcls=qcls, relay=relay)
                    finally:
                        with self._lock:
                            self._journaled -= 1
                if relay is not None:
                    # the journal IS the client stream — at capacity
                    # the stream request sheds truthfully instead of
                    # degrading to a protocol-breaking JSON body
                    self.stats_counters["relayed_503"] += 1
                    return (503, {"error": "overloaded",
                                  "reason": "journal at capacity"},
                            TIER_RETRY_AFTER_S["overloaded"])
        if relay is not None:
            # stream requested but unservable: not token-shaped, or
            # journaling is off — refuse up-front, before any NDJSON
            # head could be written
            if not journal_on:
                return (503, {"error": "stream_unavailable",
                              "reason": "journaling disabled on this "
                                        "tier"},
                        TIER_RETRY_AFTER_S["overloaded"])
            return (400, {"error": "stream_requires_token_ids"}, None)
        if isinstance(parsed, dict) and parsed.get("stream"):
            # the single-shot fallback is non-streaming to replicas;
            # never let a leaked stream flag make a replica answer the
            # single-shot path with NDJSON it cannot parse
            parsed = {k: v for k, v in parsed.items() if k != "stream"}
            payload = json.dumps(parsed).encode()
        return self._forward_plain(payload, deadline_s, request_id, t0)

    def _forward_plain(self, payload: bytes, deadline_s: float,
                       request_id: Optional[str], t0: float):
        """The single-shot (pre-recovery) forward path: one whole
        response per attempt, retry-on-a-different-replica under the
        shared RetryPolicy (which honors each shed's Retry-After
        hint). Kept for opaque payloads and journal-bound overflow."""
        tried: set = set()
        first_attempt = True

        def attempt():
            nonlocal first_attempt
            if not first_attempt:
                self.stats_counters["retries"] += 1
                if self._obs:
                    self._m_retries.inc()
            first_attempt = False
            remaining = deadline_s - (time.monotonic() - t0)
            if remaining <= 0:
                raise _DeadlineExceeded()
            rep = self._pick(tried)
            if rep is None and tried:
                # every replica tried once: a retry may still land (a
                # shed clears, an ejection lapses) — reopen the field
                # rather than fail inside the remaining budget
                tried.clear()
                rep = self._pick(tried)
            if rep is None:
                raise _NoReplica()
            tried.add(rep.name)
            with self._lock:
                rep.inflight += 1
            fwd_token = (_obs.trace.begin_span(
                "router.forward", cat="router", replica=rep.name,
                request_id=request_id) if self._obs else None)
            t_fwd = time.perf_counter()
            try:
                _resil.maybe_inject("router_forward")
                headers = {"Content-Type": "application/json"}
                if request_id:
                    headers[REQUEST_ID_HEADER] = request_id
                req = urllib.request.Request(
                    rep.base_url + "/generate", payload, headers)
                with urllib.request.urlopen(req,
                                            timeout=remaining) as r:
                    body = json.loads(r.read())
                rep.failure_streak = 0
                if self._obs:
                    self._m_forward.observe(
                        (time.perf_counter() - t_fwd) * 1e3,
                        replica=rep.name)
                body["served_by"] = rep.name
                return 200, body, None
            except urllib.error.HTTPError as e:
                try:
                    body = json.loads(e.read())
                except (ValueError, OSError):
                    body = {"error": f"http_{e.code}"}
                if e.code == 503:
                    # truthful shed from a live server — not a breaker
                    # hit; retry on a different replica
                    exc = _ShedByReplica(rep, body)
                    if self._pick(tried) is not None:
                        # an UNTRIED replica is routable: the hint
                        # describes THIS replica's capacity, not the
                        # tier's — retry elsewhere on the fast
                        # jittered schedule instead of serving one
                        # replica's Retry-After against another. The
                        # hint still reaches the client on the relay
                        # path (re-derived from the body).
                        exc.retry_after_s = None
                    raise exc
                if e.code >= 500:
                    self._note_failure(rep)
                    raise _ForwardFailed(
                        rep, body.get("error", f"http {e.code}"))
                body["served_by"] = rep.name
                return e.code, body, None    # 4xx: the client's problem
            except _resil.FaultInjected as e:
                self._note_failure(rep)
                raise _ForwardFailed(rep, str(e))
            except _REPLICA_IO_ERRORS as e:
                reason = getattr(e, "reason", e)
                if isinstance(reason, (socket.timeout, TimeoutError)) \
                        or "timed out" in str(e).lower():
                    # the forward burned the request's whole remaining
                    # budget inside one replica: no budget left to retry
                    self._note_failure(rep)
                    raise _DeadlineExceeded()
                self._note_failure(rep)
                raise _ForwardFailed(rep, str(e))
            finally:
                if fwd_token is not None:
                    _obs.trace.end_span(fwd_token)
                with self._lock:
                    rep.inflight -= 1

        try:
            remaining = deadline_s - (time.monotonic() - t0)
            return self.retry_policy.run(attempt, deadline=remaining)
        except _NoReplica:
            self.stats_counters["tier_unavailable_503"] += 1
            with self._lock:
                n = len(self._replicas)
            return (503,
                    {"error": "no_replica_ready", "replicas": n,
                     "ready": self.ready_count()},
                    TIER_RETRY_AFTER_S["no_replica_ready"]
                    + self.poll_s)
        except _DeadlineExceeded:
            self.stats_counters["deadline_503"] += 1
            return (503, {"error": "deadline_exceeded",
                          "deadline_s": deadline_s},
                    TIER_RETRY_AFTER_S["deadline_exceeded"])
        except _ShedByReplica as e:
            # retries exhausted and the last word was a truthful shed:
            # relay it (it already carries the replica's retry hint)
            self.stats_counters["relayed_503"] += 1
            body = dict(e.body)
            body["served_by"] = e.replica.name
            # re-derive from the body: retry_after_s may have been
            # nulled for SLEEP purposes (untried replica available),
            # but the relay owes the client the replica's truth
            ra = _retry_after_hint(e.body)
            return (503, body,
                    ra if ra is not None
                    else TIER_RETRY_AFTER_S["overloaded"])
        except _ForwardFailed as e:
            self.stats_counters["backend_503"] += 1
            return (503, {"error": f"backend_unavailable: {e}"},
                    TIER_RETRY_AFTER_S["backend_unavailable"])

    # -- work-conserving recovery + hedged decode (ISSUE 15) -------------
    def _hedge_budget(self) -> Optional[float]:
        """Seconds of token-progress silence before a backup decode
        launches. An explicit PADDLE_TPU_TIER_HEDGE_S wins (0 turns
        hedging off); otherwise the budget derives from the LIVE
        inter-progress histogram — hedge_mult x p99, clamped to
        [0.25s, deadline/4] — so it tracks whatever the tier's real
        decode cadence is. A cold tier (sparse histogram) uses a
        conservative 2s default."""
        if self.hedge_s == 0:
            return None
        if self.hedge_s > 0:
            return float(self.hedge_s)
        hi = max(0.5, self.deadline_s / 4.0)
        if self._obs:
            snap = self._m_progress.snap()
            if snap.count >= 32:
                b = snap.percentile(0.99) / 1e3 * self.hedge_mult
                return min(max(b, 0.25), hi)
        return min(2.0, hi)

    def _ttft_budget(self) -> Optional[float]:
        """Seconds of FIRST-token silence before an admission-stall
        backup launches (ISSUE 16) — the decode-stall twin above only
        watches requests that already produced a token, so a replica
        wedging in prefill/queue used to stall the client until the
        deadline. Same shape as the decode budget: an explicit
        PADDLE_TPU_TIER_TTFT_HEDGE_S wins (0 disables), else
        ttft_hedge_mult x the live TTFT histogram p99, clamped to
        [0.25s, deadline/4]; a cold tier (sparse histogram) uses a
        conservative 2s default."""
        if self.ttft_hedge_s == 0:
            return None
        if self.ttft_hedge_s > 0:
            return float(self.ttft_hedge_s)
        hi = max(0.5, self.deadline_s / 4.0)
        if self._obs:
            snap = self._m_ttft.snap()
            if snap.count >= 32:
                b = snap.percentile(0.99) / 1e3 * self.ttft_hedge_mult
                return min(max(b, 0.25), hi)
        return min(2.0, hi)

    def _reserve_hedge(self) -> bool:
        """Atomically claim one slot of the tier-wide hedge budget:
        at most ``hedge_frac`` of the live journaled requests (floor
        1) may be running a backup at once. The stall clock starts at
        submission, so on a saturated tier EVERY queued request looks
        silent past the budget — uncapped, hedging would double the
        tier's own load exactly when it has no headroom, amplifying
        the overload it was meant to absorb. A lone straggler always
        clears the floor."""
        with self._lock:
            cap = max(1, int(self._journaled * self.hedge_frac))
            if self._hedges_live >= cap:
                return False
            self._hedges_live += 1
            return True

    def _release_hedge(self):
        with self._lock:
            self._hedges_live -= 1

    def _note_recovery(self, rid, resumed_tokens: int, to_name: str):
        """Book one work-conserving failover: counters, a recovery
        span, and a flight-recorder artifact naming the migrated
        request ids (throttled: bursts fold into one dump)."""
        self.stats_counters["recoveries"] += 1
        if self._obs:
            self._m_recoveries.inc()
            now = time.perf_counter()
            _obs.record_span("router.recover", now, now, cat="router",
                             request_id=rid,
                             resumed_tokens=resumed_tokens,
                             to_replica=to_name)
        batch = None
        with self._lock:
            self._recovered_rids.append(
                {"request_id": rid, "resumed_tokens": resumed_tokens,
                 "to_replica": to_name})
            if time.monotonic() - self._last_recovery_dump >= 2.0:
                batch, self._recovered_rids = self._recovered_rids, []
                self._last_recovery_dump = time.monotonic()
        if batch:
            try:
                _obs.dump_flight("request_recovery",
                                 extra={"migrated": batch})
            except Exception:   # noqa: BLE001 — forensics best-effort
                pass

    def _prewarm_standby(self, rid, toks: List[int], exclude: set,
                         page_size: int) -> Optional[str]:
        """Push ``toks`` (prompt + journaled prefix) through a STANDBY
        replica's /prewarm so its paged trie already holds the pages a
        failover's resumed prefill would otherwise recompute (ISSUE
        17). Best-effort and off the request's critical path (the
        coordinator fires it on a daemon thread): a shed, a dead
        standby, or no standby at all costs the stream nothing but the
        head start. Returns the warmed replica's name, or None."""
        rep = self._pick(exclude)
        if rep is None:
            return None
        hdrs = {"Content-Type": "application/json"}
        if rid:
            hdrs[REQUEST_ID_HEADER] = f"{rid}.prewarm"
        try:
            req = urllib.request.Request(
                rep.base_url + "/prewarm",
                json.dumps({"input_ids": list(toks)}).encode(), hdrs)
            with urllib.request.urlopen(req, timeout=30.0) as resp:
                body = json.loads(resp.read() or b"{}")
        except _REPLICA_IO_ERRORS:
            return None
        if not body.get("prewarmed"):
            return None
        self.stats_counters["prewarms"] += 1
        if self._obs:
            self._m_prewarms.inc()
        # fold the warm pages into the standby's fingerprint view NOW:
        # a cutover can beat the next health poll, and affinity scoring
        # must already see the pre-warmed prefix for the resume to land
        # there (the poll later replaces this with the replica's own
        # healthz truth)
        if page_size:
            fps = frozenset(chain_hashes(list(toks), page_size))
            with self._lock:
                rep.prefix_fps = rep.prefix_fps | fps
        return rep.name

    def _forward_recovering(self, prompt: List[int], max_new: int,
                            eos, seed: int, deadline_s: float,
                            rid: Optional[str], t0: float,
                            qcls: str = QOS_DEFAULT,
                            relay: Optional[_ClientRelay] = None):
        """The per-request recovery state machine (module docstring).

        One primary :class:`_StreamAttempt` streams the request; the
        coordinator below watches the shared journal and reacts:

        * attempt DONE -> compose the client body (rewriting
          prompt_len / tokens_generated back to the client's original
          frame — a resumed attempt's response is already the full
          token sequence, only its accounting is shifted);
        * journal COMPLETE but no terminal record (the replica died
          after the last token, before ``done``) -> synthesize the
          body from the journal alone;
        * attempt FAILED mid-stream -> relaunch on another replica
          from ``prompt + journal`` (a recovery — bitwise-exact by
          greedy determinism, prefix-trie-cheap, zero new compiles);
          consecutive no-progress launches are budgeted by the retry
          policy (sheds honor the replica's Retry-After hint), but a
          launch that ADVANCED the journal resets the budget — forward
          progress is never punished as a retry storm;
        * token progress STALLED past the hedge budget -> launch a
          backup on a second replica; first to advance wins, the loser
          is cancelled (engine slot + pages reclaimed) and a winning
          hedge books a breaker strike against the straggler. Before
          the FIRST token the stall clock runs against the TTFT budget
          instead (``_ttft_budget``) — admission stalls hedge too;
        * with a client ``relay`` armed, every terminal outcome is
          handed to the relay as the stream's terminal line, and a
          relay reporting the client gone cancels all live attempts.
        """
        ttft_cb = itl_cb = None
        if self._obs:
            def ttft_cb(ms, _c=qcls):
                self._m_ttft.observe(ms)
                self._m_ttft_class.observe(ms, **{"qos_class": _c})

            def itl_cb(ms, _c=qcls):
                self._m_itl_class.observe(ms, **{"qos_class": _c})
        st = _ReqJournal(prompt, max_new, eos, seed, rid,
                         hist=(self._m_progress if self._obs else None),
                         ttft_cb=ttft_cb, itl_cb=itl_cb)
        # prefix-affinity: the prompt's chain hashes, computed once —
        # launch() re-hashes prompt+journal on a resume so cutover
        # lands on the replica whose trie the resumed prefill will
        # warm/hit
        _ps = self._tier_page_size() if self.affinity_w > 0 else 0
        prompt_hashes = chain_hashes(prompt, _ps) if _ps else None

        def respond(code, body, ra=None):
            """Every terminal outcome funnels here: with a client
            relay armed, the body becomes the stream's terminal line
            (200 -> done, anything else -> a truthful err record with
            the code + retry hint inlined, since a mid-stream client
            can no longer see HTTP status)."""
            if relay is not None and relay.started_http:
                if code == 200:
                    relay.finish("done", body)
                else:
                    err = dict(body)
                    err["code"] = code
                    if ra is not None:
                        err.setdefault("retry_after_s", ra)
                    relay.finish("err", err)
            return code, body, ra
        deadline_at = t0 + deadline_s
        attempts: List[_StreamAttempt] = []
        tried: set = set()
        seq = 0
        nprog = 0                # consecutive launches without progress
        len_at_launch = -1
        recovered = 0
        hedges_launched = 0
        need_launch = False      # a failed attempt awaits relaunch —
        #                          persists across poll iterations so a
        #                          momentarily replica-less tier (the
        #                          survivor ejected, the respawn still
        #                          warming) keeps retrying launch()
        #                          instead of idling to the deadline
        # Seeding a resume with journaled tokens is only deterministic
        # for greedy decode: a sampling engine rolls tok0 from the raw
        # key at admit but fold_in(key, pos) in the decode loop, so a
        # resumed base would re-roll DIFFERENT tokens and mismatch the
        # journal on its first block. A sampling tier still journals,
        # recovers, and hedges — every relaunch just re-runs from
        # scratch (same seed => same tokens) and the journal VERIFIES
        # the regenerated prefix instead of seeding it: token-exact,
        # not work-saving. Also set later on resume-reject / mismatch.
        force_full = bool(self.spec.engine.get("do_sample", False))
        pending_hint = None
        last_shed: Optional[_StreamAttempt] = None
        last_fail = "no attempt"

        complete_since = None    # journal complete, waiting (briefly)
        #                          for the live attempt's terminal line

        # standby prefix pre-warming (ISSUE 17): as the journal crosses
        # page boundaries, a daemon thread pushes prompt+journal through
        # a standby's /prewarm — the failover target's trie then already
        # holds the resumed prefill's pages when a cutover happens
        prewarmed: set = set()   # replicas warmed for THIS request
        pw_busy = [False]        # one in-flight prewarm at a time
        pw_pages = [0]           # page count already pushed

        def maybe_prewarm(live_names: set):
            if (not self.prewarm or not _ps or pw_busy[0]
                    or st.complete()):
                return
            with st.cond:
                cur = list(st.tokens)
            pages = (len(prompt) + len(cur)) // _ps
            if pages <= pw_pages[0]:
                return
            pw_pages[0] = pages
            pw_busy[0] = True
            toks = prompt + cur

            # NOT excluding already-warmed standbys: re-picking the
            # same one extends its trie with the grown prefix, which is
            # exactly what keeps the failover target current
            def _pw(toks=toks, ex=set(tried) | set(live_names)):
                try:
                    name = self._prewarm_standby(rid, toks, ex, _ps)
                    if name:
                        prewarmed.add(name)
                finally:
                    pw_busy[0] = False
            threading.Thread(target=_pw, daemon=True,
                             name=f"tier-prewarm-{rid or 'anon'}"
                             ).start()

        def cancel_all(exclude=None, wait=True):
            losers = [a for a in attempts
                      if a is not exclude and a.status == "running"]
            if not losers:
                return
            if wait:
                for a in losers:
                    a.cancel()
                return
            # winner path: don't make the winning client's response
            # wait on loser-side /cancel round trips
            threading.Thread(
                target=lambda: [a.cancel() for a in losers],
                daemon=True, name="tier-cancel-losers").start()

        def launch(is_hedge=False):
            nonlocal seq, nprog, len_at_launch, recovered, \
                hedges_launched
            live_names = {a.rep.name for a in attempts
                          if a.status == "running"}
            keys = prompt_hashes
            if _ps and st.size() > 0:
                # resuming mid-flight: score by prompt + journaled
                # prefix — the residual prefill warms (or already
                # hits) exactly those pages on the target, so the
                # cutover lands where the work is cheapest
                with st.cond:
                    cur = list(st.tokens)
                keys = chain_hashes(prompt + cur, _ps)
            # keys=None keeps the legacy one-arg call shape (tests
            # stub _pick with single-parameter callables)
            rep = (self._pick(tried | live_names, keys) if keys
                   else self._pick(tried | live_names))
            if rep is None and tried:
                # every replica was tried once: a retry may still land
                # (a shed clears, an ejection lapses) — reopen the
                # field, same policy as the single-shot path
                tried.clear()
                rep = (self._pick(set(live_names), keys) if keys
                       else self._pick(set(live_names)))
            if rep is None:
                return None
            if seq > 0 and rep.name in prewarmed:
                # the cutover landed where the router pre-warmed: the
                # resumed prefill (or hedge re-run) starts on trie hits
                self.stats_counters["prewarmed_resumes"] += 1
                if self._obs:
                    self._m_prewarmed_resumes.inc()
            base = 0 if force_full else st.size()
            if not is_hedge:
                if seq > 0:
                    self.stats_counters["retries"] += 1
                    if self._obs:
                        self._m_retries.inc()
                    if st.size() > 0:
                        recovered += 1
                        self._note_recovery(rid, base, rep.name)
                nprog = 1 if st.size() > len_at_launch else nprog + 1
                len_at_launch = st.size()
            else:
                hedges_launched += 1
                self.stats_counters["hedges"] += 1
                if self._obs:
                    self._m_hedges.inc()
                    now = time.perf_counter()
                    _obs.record_span("router.hedge", now, now,
                                     cat="router", request_id=rid,
                                     replica=rep.name,
                                     journal_tokens=base)
            a = _StreamAttempt(self, rep, st, base, deadline_at,
                               is_hedge, seq)
            seq += 1
            attempts.append(a)
            with st.cond:
                # the stall clock measures token SILENCE, not failover
                # latency: a fresh launch re-arms it, so the reap ->
                # backoff -> relaunch window of a recovery doesn't
                # read as a stall and hedge a healthy resumed attempt
                # (a winning hedge would then strike the innocent
                # primary's breaker)
                st.last_progress = time.monotonic()
            a.start()
            return a

        if launch() is None:
            # pre-stream failure: the relay never began, so the client
            # gets a plain JSON 503 (no NDJSON head on the wire yet)
            self.stats_counters["tier_unavailable_503"] += 1
            with self._lock:
                n = len(self._replicas)
            return (503,
                    {"error": "no_replica_ready", "replicas": n,
                     "ready": self.ready_count()},
                    TIER_RETRY_AFTER_S["no_replica_ready"]
                    + self.poll_s)
        if relay is not None:
            # committed to the journaled path: from here on the
            # journal feed IS the client's response stream
            self.stats_counters["streams"] += 1
            if self._obs:
                self._m_streams.inc()
            relay.begin(st)

        while True:
            now = time.monotonic()
            if relay is not None and relay.dead:
                # the client hung up mid-stream: cancel EVERY live
                # attempt on whichever replica owns the request now —
                # slot retired, pages freed — and account the tokens
                # the journal actually produced
                cancel_all(wait=False)
                self.stats_counters["client_disconnects"] += 1
                if self._obs:
                    self._m_disconnects.inc()
                return 499, {"error": "client_disconnected",
                             "tokens_generated": st.size()}, None
            if now >= deadline_at:
                # wait=False on every response-returning path: a
                # half-dead loser's /cancel round trip (2s timeout
                # each) must never delay the client's answer
                cancel_all(wait=False)
                self.stats_counters["deadline_503"] += 1
                return respond(
                    503, {"error": "deadline_exceeded",
                          "deadline_s": deadline_s},
                    TIER_RETRY_AFTER_S["deadline_exceeded"])
            winner = next((a for a in attempts if a.status == "done"),
                          None)
            if winner is not None:
                if winner.is_hedge:
                    self.stats_counters["hedge_wins"] += 1
                    if self._obs:
                        self._m_hedge_wins.inc()
                    # the straggler earned a breaker strike: a replica
                    # that keeps losing its own requests to hedges
                    # must leave the rotation for a cooldown
                    for a in attempts:
                        if (a is not winner and not a.is_hedge
                                and a.status == "running"):
                            self._note_failure(a.rep)
                cancel_all(exclude=winner, wait=False)
                body = dict(winner.done_body or {})
                toks = body.get("tokens") or []
                body["served_by"] = winner.rep.name
                # rewrite accounting into the CLIENT's frame: the
                # resumed attempt saw prompt+journal as its prompt
                body["prompt_len"] = len(prompt)
                body["new_tokens"] = max(0, len(toks) - len(prompt))
                body["tokens_generated"] = winner.base + int(
                    body.get("tokens_generated", 0))
                # ... and the request id: the replica echoed the
                # ATTEMPT's derived id ("<rid>.<seq>") — correlation
                # belongs to the client's original
                if rid:
                    body["request_id"] = rid
                else:
                    body.pop("request_id", None)
                if recovered:
                    body["recovered"] = recovered
                if winner.is_hedge:
                    body["hedged"] = True
                return respond(200, body)
            live = [a for a in attempts if a.status == "running"]
            maybe_prewarm({a.rep.name for a in live})
            if st.complete():
                # the journal alone already holds the full output.
                # Normally the live attempt's terminal record is
                # microseconds behind its last token event — give it a
                # short grace so the replica's own body wins; past the
                # grace (or with no attempt left: the replica died
                # between its last token and `done`) synthesize from
                # the journal — greedy determinism + the engine's
                # eos-padding contract make it exact.
                if live and complete_since is None:
                    complete_since = now
                if not live or now - complete_since >= 1.0:
                    cancel_all(wait=False)
                    body = st.synthesize_body()
                    if recovered:
                        body["recovered"] = recovered
                    return respond(200, body)
            else:
                complete_since = None
            relaunch = False
            for a in attempts:
                if a.status != "failed" or a.reaped:
                    continue
                a.reaped = True
                if a.kind == "cancelled":
                    continue
                if a.kind == "client_error":
                    if a.base > 0:
                        # the replica 400'd a RESUMED prompt (outgrew
                        # its prefill buckets): fall back to a
                        # from-scratch re-run — the journal then
                        # VERIFIES the regenerated prefix instead of
                        # seeding it (token-exact, just not
                        # work-saving)
                        force_full = True
                        self.stats_counters["resume_fallbacks"] += 1
                        relaunch = not live
                        continue
                    cancel_all(wait=False)
                    body = dict(a.body or {"error": "client error"})
                    body["served_by"] = a.rep.name
                    return respond(a.code, body)
                if a.kind == "mismatch":
                    # determinism violated against the journal (e.g. a
                    # hedge pair diverging, or a resumed base on an
                    # engine whose key path is position-dependent):
                    # same verdict as the resume-reject path above —
                    # fall back to a from-scratch re-run, which the
                    # journal VERIFIES instead of seeds. Retrying the
                    # resume at the same base would mismatch forever.
                    force_full = True
                    self.stats_counters["resume_fallbacks"] += 1
                    last_fail = a.reason
                    relaunch = not live
                    continue
                if a.kind == "shed":
                    last_shed = a
                    pending_hint = a.retry_after
                    tried.add(a.rep.name)
                    relaunch = not live
                    continue
                tried.add(a.rep.name)       # io-class failure
                last_fail = a.reason
                relaunch = not live
            need_launch = need_launch or relaunch
            if need_launch and not live:
                if nprog >= self.retry_policy.max_attempts:
                    # no forward progress across the whole budget:
                    # same verdicts as the single-shot path
                    if last_shed is not None:
                        self.stats_counters["relayed_503"] += 1
                        body = dict(last_shed.body or {})
                        body["served_by"] = last_shed.rep.name
                        return respond(
                            503, body,
                            last_shed.retry_after
                            if last_shed.retry_after is not None
                            else TIER_RETRY_AFTER_S["overloaded"])
                    self.stats_counters["backend_503"] += 1
                    return respond(
                        503,
                        {"error":
                         f"backend_unavailable: {last_fail}"},
                        TIER_RETRY_AFTER_S["backend_unavailable"])
                if relaunch and st.size() <= len_at_launch:
                    # no progress since the last launch: back off on
                    # the shared schedule — honoring the replica's own
                    # Retry-After hint when the failure was a shed. A
                    # mid-stream death WITH progress relaunches
                    # immediately: failover must be work-conserving in
                    # time too. (Gated on `relaunch` — the freshly
                    # reaped failure — so the waiting-for-a-respawn
                    # path below doesn't re-pay the backoff on every
                    # poll.)
                    hint, pending_hint = pending_hint, None
                    if hint is not None and self._pick(tried) is not None:
                        # an untried replica is routable: the shed
                        # hint is the SHED replica's capacity story —
                        # relaunch elsewhere on the fast schedule
                        hint = None
                    budget = deadline_at - time.monotonic()
                    if budget > 0:
                        self.retry_policy.sleep(
                            min(max(nprog, 1),
                                max(1, self.retry_policy.max_attempts
                                    - 1)),
                            budget=budget, hint=hint)
                if launch() is not None:
                    need_launch = False
                elif st.size() == 0:
                    self.stats_counters["tier_unavailable_503"] += 1
                    with self._lock:
                        n = len(self._replicas)
                    return respond(
                        503,
                        {"error": "no_replica_ready",
                         "replicas": n,
                         "ready": self.ready_count()},
                        TIER_RETRY_AFTER_S["no_replica_ready"]
                        + self.poll_s)
                else:
                    # journaled work exists: WAIT for a replica (a
                    # respawn is usually poll_s away) instead of
                    # throwing the tokens away — `need_launch` keeps
                    # launch() retried on every pass until one lands,
                    # bounded by the request deadline above
                    time.sleep(min(self.poll_s,
                                   max(0.05,
                                       deadline_at - time.monotonic())))
                continue
            # live attempts exist: watch for stalls, then wait for
            # journal/attempt events. Before the FIRST token the
            # silence clock runs against the TTFT budget (admission
            # stalls — wedged prefill, stuck queue); after it, the
            # decode-progress budget. Both draw on the ONE tier-wide
            # hedge reservation.
            first_token_pending = st.size() == 0
            hb = (self._ttft_budget() if first_token_pending
                  else self._hedge_budget())
            with st.cond:
                silent = now - st.last_progress
            if (hb is not None and len(live) == 1
                    and silent >= hb and hedges_launched < 2
                    and not st.complete()
                    and self._reserve_hedge()):
                if launch(is_hedge=True) is None:
                    # no second replica yet: hand the budget slot back
                    # and re-check on the next wake
                    self._release_hedge()
                elif first_token_pending:
                    self.stats_counters["ttft_hedges"] += 1
                    if self._obs:
                        self._m_ttft_hedges.inc()
            with st.cond:
                timeout = 0.25
                if hb is not None and len(live) == 1:
                    # wake exactly when the hedge budget expires — but
                    # only while it HASN'T yet: once stalled with no
                    # launchable backup (budget-blocked, or no second
                    # replica), stay on the 0.25s cadence instead of
                    # spinning at the 0.01s floor
                    left = hb - (time.monotonic() - st.last_progress)
                    if left > 0:
                        timeout = min(timeout, max(0.01, left))
                timeout = min(timeout,
                              max(0.01, deadline_at - time.monotonic()))
                st.cond.wait(timeout=timeout)

    # -- introspection ---------------------------------------------------
    def _readiness(self):
        reps = self.replicas()
        ready = sum(1 for r in reps
                    if r["state"] == "ready" and not r["draining"]
                    and not r["ejected"])
        body = {"status": "ready" if ready else "unready",
                "tier": True,
                "uptime_s": round(time.monotonic() - self._started, 1),
                "metrics_seq": _obs.metrics.registry.seq(),
                "replicas_total": len(reps), "ready_replicas": ready,
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "rolling_restart_in_progress": self._rolling_flag(),
                "queued_total": sum(r["queued"] for r in reps),
                "active_total": sum(r["active"] for r in reps),
                "inflight_total": sum(r["inflight"] for r in reps),
                "replicas": reps,
                "qos": self.qos.snapshot(),
                "stats": dict(self.stats_counters)}
        if not ready:
            body["reason"] = "no replica ready"
        return ready > 0, body

    def stats(self) -> dict:
        _, body = self._readiness()
        return body

    def render_metrics(self) -> str:
        """The tier /metrics body: the router's own registry, every
        reachable replica's scrape re-labeled ``replica="rN"``, and
        ``ptpu_tier_*`` aggregates summed across replicas (counters
        and cumulative histogram buckets sum exactly — tier-level
        phase percentiles come straight out of the summed buckets)."""
        with self._lock:
            reps = [(r.name, r.base_url) for r in self._replicas
                    if r.base_url is not None and not r.draining]
        # scrape CONCURRENTLY with one bounded join: tier scrape
        # latency must not grow linearly with replica count, and one
        # wedged replica (socket accepts, never answers) must cost the
        # scrape its own 2s budget at most, not 2s x N serialized
        scraped: Dict[str, str] = {}

        def pull(name, base):
            try:
                with urllib.request.urlopen(base + "/metrics",
                                            timeout=2.0) as r:
                    scraped[name] = r.read().decode()
            except _REPLICA_IO_ERRORS:
                pass            # a dead replica just drops out
        threads = [threading.Thread(target=pull, args=rb, daemon=True)
                   for rb in reps]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 2.5
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        return _obs.metrics.render_tier(
            _obs.metrics.registry.render(), dict(scraped))

    # -- HTTP front ------------------------------------------------------
    def _make_handler(self):
        router = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, obj, retry_after=None):
                # serve.send_json is the ONE Retry-After writer; the
                # tier only widens the reason table (no_replica_ready)
                send_json(self, code, obj, retry_after=retry_after,
                          retry_after_table=TIER_RETRY_AFTER_S)

            def _drain_body(self):
                try:
                    self.rfile.read(
                        int(self.headers.get("Content-Length", "0")))
                except (ValueError, OSError):
                    pass

            def do_GET(self):
                if self.path == "/health":
                    self._send(200, {"status": "ok"})
                elif self.path == "/healthz":
                    ready, body = router._readiness()
                    self._send(200 if ready else 503, body)
                elif self.path == "/metrics":
                    send_text(self, 200, router.render_metrics())
                elif self.path == "/metadata":
                    self._send(200, {"inputs": ["input_ids"],
                                     "outputs": ["tokens"]})
                else:
                    self._send(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path.startswith("/admin/trace"):
                    handle_admin_trace(self, self._drain_body)
                    return
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    payload = self.rfile.read(n)
                except (ValueError, OSError):
                    payload = b""
                if self.path == "/generate":
                    # the tier is where a request id is BORN (unless
                    # the client brought one): it rides the header to
                    # the replica and comes back in the body, so a
                    # client can resolve its own phase spans later
                    rid = self.headers.get(REQUEST_ID_HEADER) or (
                        uuid.uuid4().hex[:16] if router._obs else None)
                    relay = None
                    if b'"stream"' in payload:
                        try:
                            want = bool(json.loads(
                                payload or b"{}").get("stream"))
                        except (ValueError, AttributeError):
                            want = False
                        if want:
                            relay = _ClientRelay(self, rid)
                    code, body, ra = router.forward_generate(
                        payload, request_id=rid,
                        tenant=self.headers.get(TENANT_HEADER),
                        qos_class=self.headers.get(CLASS_HEADER),
                        relay=relay)
                    if relay is not None and relay.started_http:
                        return    # the relay already answered NDJSON
                    if rid and isinstance(body, dict):
                        body.setdefault("request_id", rid)
                    try:
                        self._send(code, body, retry_after=ra)
                    except (BrokenPipeError, ConnectionError, OSError):
                        pass      # client gone before the JSON answer
                elif self.path == "/admin/rolling_restart":
                    # answer 409 from the HANDLER: Thread.start() never
                    # raises the in-progress error, the restart itself
                    # does (inside the daemon thread). The pre-check
                    # races a concurrent POST by a hair, so the thread
                    # target still swallows a lost race instead of
                    # dumping an uncaught exception to stderr
                    if router._rolling_lock.locked():
                        self._send(409, {"error": "rolling restart "
                                                  "already in progress"})
                        return

                    def _roll():
                        try:
                            router.rolling_restart()
                        except RuntimeError:
                            pass          # lost the race: one restart
                            #               is already running
                    threading.Thread(target=_roll, daemon=True,
                                     name="tier-rolling").start()
                    self._send(202, {"status": "rolling"})
                else:
                    self._send(404, {"error": f"no route {self.path}"})

        return Handler


# ---------------------------------------------------------------------------
# module entry: the replica-child hook the spawner uses
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="serving-tier internals (replica child entry; the "
                    "operator CLI is tools/serve_tier.py)")
    ap.add_argument("--replica-child", action="store_true")
    ap.add_argument("--spec", help="ReplicaSpec JSON")
    ap.add_argument("--port-file", help="where the child publishes its "
                                        "bound port")
    args = ap.parse_args(argv)
    if not args.replica_child:
        ap.error("this entry point only serves --replica-child; use "
                 "tools/serve_tier.py to launch a tier")
    if not args.spec or not args.port_file:
        ap.error("--replica-child needs --spec and --port-file")
    return _replica_child_main(args)


if __name__ == "__main__":
    sys.exit(main())
