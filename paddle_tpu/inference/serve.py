"""HTTP serving front-end over the inference predictor.

Serving-path role (BASELINE.json north star: "ERNIE-3.0 served
end-to-end"): the reference serves through AnalysisPredictor embedded in
C++ servers or the FleetExecutor DistModel service
(fleet_executor/dist_model.cc). TPU-native equivalent: the AOT-compiled
predictor (inference/predictor.py) behind a threaded stdlib HTTP server —
zero extra dependencies, JSON tensors in/out.

Endpoints:
  GET  /health    -> {"status": "ok"} (liveness — the process answers)
  GET  /healthz   -> readiness: 200 once the predictor can serve, 503
                     with a reason while degraded (failure streak,
                     saturated queue); with an engine attached the body
                     carries slot occupancy + queue depth; always
                     carries uptime_s + metrics_seq (the obs registry's
                     mutation sequence — stale stats are tellable from
                     live ones)
  GET  /metrics   -> Prometheus-style text from the obs registry
                     (paddle_tpu.obs): engine tick/occupancy/phase
                     histograms, host syncs, XLA compiles, ...
  POST /admin/trace?duration_s=S[&profile=1]
                  -> capture the obs flight recorder for S seconds
                     (0 = snapshot the whole ring now) and return
                     Chrome/Perfetto trace JSON; profile=1 also runs a
                     programmatic jax.profiler capture over the window
  GET  /metadata  -> input/output names (+ dtypes/shapes once known)
  POST /predict   -> {"inputs": {name: nested-list | {"data": ...,
                      "dtype": "float32"}}} -> {"outputs": {name: ...}}
  POST /generate  -> {"input_ids": [...], "max_new_tokens": n,
                      "eos_token_id": opt, "seed": opt} -> {"tokens":
                      [...]} — served by the continuous-batching engine
                      (inference/engine.py): requests from concurrent
                      clients multiplex through ONE compiled batched
                      decode program, each resolved by its own future.
                      With "stream": true the response is incremental
                      NDJSON (read-until-close): one {"t": [tokens]}
                      line per emitted block as the engine produces it
                      (first token at admission, then per tick), then
                      a terminal {"done": {...full body...}} line — or
                      {"err": {"error": ..., "tokens_generated": n,
                      "partial_tokens": [...]}} when the request dies
                      or is cancelled mid-decode, carrying the partial
                      result so a router's token journal can reconcile
                      against engine truth. The router's
                      work-conserving failover and hedged decode ride
                      this side-channel (inference/router.py).
  POST /cancel    -> {"request_id": rid} -> {"cancelled": bool} — real
                      request cancellation: a queued request resolves
                      immediately, an admitted one retires at the next
                      tick boundary (slot freed, KV pages decref'd —
                      leak-free); its waiter gets 409 "cancelled" (or
                      the stream's err line) with the partial result
  POST /admin/inject -> {"site": s, "count": n, "wedge_s": opt} — arm
                      a resilience fault site in THIS live replica
                      (e.g. replica_stall to wedge the decode loop);
                      chaos tooling only, 403 unless the process runs
                      with PADDLE_TPU_CHAOS_ADMIN=1

Graceful degradation (resilience subsystem, distributed/resilience.py):
every /predict carries a deadline (PADDLE_TPU_SERVE_DEADLINE, default
30s) — a wedged backend yields a fast 503, never a hung client; when
more than PADDLE_TPU_SERVE_MAX_QUEUE requests are already waiting the
server sheds load with an immediate 503 instead of queueing into its
own deadline.

Every 503 carries a ``Retry-After`` header (and a ``retry_after_s``
body field) so routers and external clients back off on the server's
word instead of guessing — the contract the serving-tier router
(inference/router.py) builds its retry schedule on.

Draining (rolling restarts): POST /drain flips the server into a
draining state — /healthz goes unready (reason "draining"), new
/predict + /generate admissions shed 503 "draining", in-flight
requests run to completion. ``stop(drain_s=K)`` waits (bounded) for
in-flight work before shutting the listener down; the default
``drain_s=0`` keeps the historical fast-stop behavior.

CLI: python -m paddle_tpu.inference.serve --model m.pdmodel --port 8866
"""
from __future__ import annotations

import argparse
import json
import os
import queue as _queue
import threading
import time
import urllib.parse
import uuid
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from .. import obs as _obs
from ..distributed import resilience as _resil
from .predictor import Config, create_predictor

__all__ = ["PredictorServer", "main"]

#: request-id propagation header (router -> replica -> engine): one
#: request's spans correlate across the whole tier under this id
REQUEST_ID_HEADER = "X-PTPU-Request-Id"


# the ONE float-knob parser (framework/env.py); the old private name
# stays as a face — router.py and tests import it from here
from ..framework.env import bool_env as _env_bool  # noqa: E402
from ..framework.env import float_env as _env_float  # noqa: E402


# How long a client should wait before retrying each 503 reason. The
# values are advisory backoff hints, not promises: "overloaded" clears
# as soon as a slot frees (fast), "warming_up" waits on an XLA compile
# or store load (slow). Routers treat any 503 carrying one of these as
# retryable-on-another-replica.
RETRY_AFTER_S = {
    "overloaded": 1.0,
    # paged engine's KV page pool is the binding constraint — clears
    # when a request retires and frees its pages (slower than a bare
    # slot freeing, the retiring request must finish decoding)
    "cache_exhausted": 2.0,
    "warming_up": 5.0,
    "deadline_exceeded": 2.0,
    "backend_unavailable": 2.0,
    "draining": 2.0,
    "unready": 1.0,
}


def send_json(handler, code, obj, retry_after=None,
              retry_after_table=None):
    """The ONE json-response writer for serving handlers (this server
    AND the router tier front-end — the Retry-After contract must not
    fork). ``retry_after`` (seconds) rides any 503 as both the HTTP
    ``Retry-After`` header (integer, per spec) and a ``retry_after_s``
    body field (exact float); when omitted on a 503 it is derived from
    the body's ``error`` reason via ``retry_after_table`` so no shed
    response can ship without one."""
    table = RETRY_AFTER_S if retry_after_table is None \
        else retry_after_table
    if code == 503 and retry_after is None:
        reason = str(obj.get("error", "")).split(":")[0]
        retry_after = table.get(reason, table["unready"])
    if retry_after is not None:
        obj.setdefault("retry_after_s", float(retry_after))
    body = json.dumps(obj).encode()
    handler.send_response(code)
    handler.send_header("Content-Type", "application/json")
    if retry_after is not None:
        handler.send_header("Retry-After",
                            str(max(1, int(-(-retry_after // 1)))))
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


def send_text(handler, code, text,
              content_type="text/plain; version=0.0.4; charset=utf-8"):
    """Plain-text response writer (the /metrics exposition body — the
    Prometheus text format's conventional content type). Shared with
    the router tier front-end."""
    body = text.encode()
    handler.send_response(code)
    handler.send_header("Content-Type", content_type)
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


def handle_admin_trace(handler, drain_body_fn):
    """POST /admin/trace?duration_s=S[&profile=1] — shared by the
    replica server and the router front-end: capture the obs flight
    recorder over the window and answer Chrome-trace JSON."""
    drain_body_fn()
    q = urllib.parse.parse_qs(
        urllib.parse.urlsplit(handler.path).query)
    try:
        duration = float(q.get("duration_s", ["0"])[0])
    except ValueError:
        send_json(handler, 400, {"error": "bad duration_s"})
        return
    profile = q.get("profile", ["0"])[0] not in ("0", "", "false")
    doc = _obs.trace.capture(min(max(duration, 0.0), 60.0),
                             jax_profile=profile)
    send_json(handler, 200, doc)


class PredictorServer:
    """Owns one predictor and an HTTP server bound to host:port.

    The predictor is not thread-safe (zero-copy handles are shared
    state), so requests serialize on a lock — concurrency comes from the
    XLA program itself, which is where the time goes.
    """

    def __init__(self, model_path_or_config=None, host: str = "127.0.0.1",
                 port: int = 8866, deadline_s: float = None,
                 max_queue: int = None, engine=None, warmup: bool = None):
        if model_path_or_config is None and engine is None:
            raise ValueError(
                "need a model path/Config (predict path), an engine "
                "(generate path), or both")
        self.engine = engine             # ContinuousBatchingEngine|None
        self._owned_predictor = None     # engine whose lifecycle is OURS
        if model_path_or_config is not None:
            cfg = (model_path_or_config
                   if isinstance(model_path_or_config, Config)
                   else Config(model_path_or_config))
            self.predictor = create_predictor(cfg)
            from .engine import GenerationPredictor
            if isinstance(self.predictor, GenerationPredictor):
                # a Config with enable_continuous_batching() serves the
                # GENERATE path: wire its engine in, there is no tensor
                # predictor behind /predict. We created this engine, so
                # stop() must also shut it down (an explicitly-passed
                # `engine=` stays caller-owned)
                if self.engine is None:
                    self.engine = self.predictor.engine
                    self._owned_predictor = self.predictor
                self.predictor = None
        else:
            self.predictor = None
        self._lock = threading.Lock()
        self.deadline_s = (deadline_s if deadline_s is not None
                           else _env_float("PADDLE_TPU_SERVE_DEADLINE",
                                           30.0))
        self.max_queue = int(max_queue if max_queue is not None
                             else _env_float("PADDLE_TPU_SERVE_MAX_QUEUE",
                                             8))
        # ONE predict worker: the predictor serializes anyway (zero-copy
        # handles are shared state); running it in a dedicated thread is
        # what lets a handler ABANDON a wedged call at its deadline —
        # the handler thread is never the one stuck in the runtime
        self._pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="predict")
        self._depth = 0                 # requests submitted, not done
        self._depth_lock = threading.Lock()
        self._resp_inflight = 0         # admitted requests whose
        #                                 response is not yet written
        #                                 (BOTH paths — what a drain
        #                                 actually waits on)
        self._draining = False          # /drain flips; stop() waits
        self._failure_streak = 0        # consecutive 5xx-class outcomes
        # AOT warmup (paddle_tpu.compilation): compile-or-load the
        # engine's programs BEFORE the first request instead of on it.
        # /healthz reports "warming" (503) until done and /generate
        # sheds with the 503 contract — an orchestrator keeps traffic
        # off a process that would stall it on a compile.
        if warmup is None:
            from ..framework.env import bool_env
            warmup = bool_env("PADDLE_TPU_SERVE_WARMUP", False)
        self._warmup_requested = bool(warmup)
        self._warm_state = "warming" if self._warmup_requested else "ready"
        self._warm_error = None
        self._warmup_thread = None
        self._started = time.monotonic()
        self.httpd = ThreadingHTTPServer((host, port),
                                         self._make_handler())
        self.host, self.port = self.httpd.server_address[:2]
        self._thread = None
        if self._warmup_requested:
            # warm on a side thread so the listener binds (and answers
            # /health + a truthful warming /healthz) immediately —
            # readiness flips, liveness never blocks on a compile
            self._warmup_thread = threading.Thread(
                target=self._run_warmup, daemon=True,
                name="serve-warmup")
            self._warmup_thread.start()

    def _run_warmup(self):
        try:
            from ..compilation import prime_helper_ops
            prime_helper_ops()
            if self.engine is not None and hasattr(self.engine, "warmup"):
                self.engine.warmup()
        except Exception as e:   # noqa: BLE001 — a failed warmup must
            # not brick the server: first traffic falls back to the
            # lazy-jit compile it would have paid anyway
            self._warm_error = f"{type(e).__name__}: {e}"
        finally:
            self._warm_state = "ready"

    # ------------------------------------------------------------------
    def inflight(self) -> int:
        """Requests admitted but not yet responded to (both paths) —
        what a drain waits on."""
        with self._depth_lock:
            return self._resp_inflight + self._depth

    def begin_drain(self) -> int:
        """Stop admitting new requests; in-flight ones run to
        completion. /healthz goes unready (reason "draining") so a
        router pulls this replica out of rotation immediately; the
        listener stays up so health polls and in-flight responses still
        flow. Returns the in-flight count at the moment of the flip.
        Idempotent — a second /drain just re-reports. The flip happens
        under the depth lock, atomically against the admission paths'
        own locked check-and-increment — stop(drain_s)'s wait can never
        observe inflight()==0 with an admitted request not yet
        counted."""
        with self._depth_lock:
            self._draining = True
            return self._resp_inflight + self._depth

    # ------------------------------------------------------------------
    def _metadata(self):
        if self.predictor is not None:
            return {"inputs": self.predictor.get_input_names(),
                    "outputs": self.predictor.get_output_names()}
        return {"inputs": ["input_ids"], "outputs": ["tokens"]}

    def _readiness(self):
        """(ready, body) for /healthz. Degraded conditions are reported
        with a reason so an orchestrator can tell shed-load from dead.
        With an engine attached the body carries slot occupancy and
        generate-queue depth so an autoscaler can see saturation."""
        with self._depth_lock:
            draining = self._draining
        body = {"status": "ready",
                "uptime_s": round(time.monotonic() - self._started, 1),
                # obs-registry mutation sequence: moves whenever any
                # metric moves, so a scraper (the router's per-replica
                # view) can tell live stats from a wedged process
                # re-serving stale ones
                "metrics_seq": _obs.metrics.registry.seq(),
                "queue_depth": self._depth,
                "inflight": self.inflight(),
                "draining": draining,
                "max_queue": self.max_queue,
                "failure_streak": self._failure_streak}
        try:
            from ..compilation import log as _clog
            body["compilation"] = _clog.summary()
        except Exception:
            pass
        st = None
        if self.engine is not None:
            st = self.engine.stats()
            body["engine"] = {k: st[k] for k in
                              ("slots", "active", "free", "queued",
                               "max_queue", "ticks",
                               "compiled_programs",
                               # obs.efficiency live gauge mirror: last
                               # tick's modeled-bytes/s fraction of the
                               # efficiency chip's HBM bandwidth
                               "tick_model_eff")}
            body["engine"]["warm"] = getattr(self.engine, "warm", True)
            # mesh geometry (ISSUE 20): a tier replica may be an N-chip
            # TP slice, not a chip — the router's replica snapshot and
            # any autoscaler need the real footprint
            body["engine"]["tp"] = st.get("tp", 1)
            body["engine"]["mesh_devices"] = st.get("mesh_devices", 1)
            if "mesh" in st:
                body["engine"]["mesh"] = st["mesh"]
            if st.get("paged"):
                # paged KV pool health: an autoscaler reads page
                # pressure (pool near-full with slots free = grow
                # cache, not replicas) and the prefix hit rate
                body["engine"].update({
                    k: st[k] for k in
                    ("paged", "page_size", "pages_total", "pages_free",
                     "pages_used", "page_utilization", "prefix_hits",
                     "prefix_misses", "prefix_hit_rate",
                     # chained-crc32 trie node ids — the router's
                     # prefix-affinity routing intersects a prompt's
                     # own chain hashes with this set (ISSUE 16)
                     "prefix_fingerprints") if k in st})
            if st.get("speculative"):
                # speculative decoding health: acceptance rate and
                # accepted-tokens-per-tick are the knobs an operator
                # tunes k / the drafter against
                body["engine"].update({
                    k: st[k] for k in
                    ("speculative", "spec_k", "spec_ticks",
                     "tokens_drafted", "tokens_accepted",
                     "tokens_rejected", "acceptance_rate",
                     "accepted_tokens_per_tick")})
        if draining:
            # draining dominates every other state: in-flight requests
            # are finishing, nothing new may be routed here
            body.update(status="draining", reason="draining for restart")
            return False, body
        if self._warm_state == "warming":
            # truthful readiness: programs are still compiling (or
            # loading from the executable store); traffic sent now
            # would stall behind the compile
            body.update(status="warming", reason="warmup in progress")
            return False, body
        if self._warm_error is not None:
            # warmup failed — the server still serves (lazy compile on
            # first request is the degraded-but-correct fallback), the
            # orchestrator just gets to see why readiness was late
            body["warmup_error"] = self._warm_error
        if st is not None and st["queued"] >= st["max_queue"]:
            body.update(status="unready",
                        reason="engine request queue saturated")
            return False, body
        if self.predictor is None and self.engine is None:
            body.update(status="unready", reason="no predictor loaded")
            return False, body
        if self._failure_streak >= 3:
            body.update(status="unready",
                        reason=f"{self._failure_streak} consecutive "
                               "predict failures (backend unavailable?)")
            return False, body
        if self._depth >= self.max_queue:
            body.update(status="unready", reason="request queue saturated")
            return False, body
        return True, body

    def _predict(self, payload):
        # fault sites: a wedged backend (hangs until the request
        # deadline trips) and an unavailable one (raises; mapped to 503)
        _resil.maybe_inject("serve_hang")
        _resil.maybe_inject("serve_backend")
        if self.predictor is None:
            raise ValueError(
                "no predictor loaded (this server only has a generation "
                "engine — POST /generate)")
        inputs = payload.get("inputs")
        if not isinstance(inputs, dict):
            raise ValueError('body must be {"inputs": {name: tensor}}')
        names = self.predictor.get_input_names()
        unknown = set(inputs) - set(names)
        if unknown:
            raise ValueError(f"unknown input(s) {sorted(unknown)}; "
                             f"expected {names}")
        missing = set(names) - set(inputs)
        if missing:
            raise ValueError(f"missing input(s) {sorted(missing)}")
        with self._lock:
            for name in names:
                v = inputs[name]
                dtype = v.get("dtype") if isinstance(v, dict) else None
                data = v["data"] if isinstance(v, dict) else v
                if dtype is None:
                    # JSON numbers arrive as int64/float64: coerce to the
                    # model's declared input dtype when it is known
                    dtype = self.predictor.get_input_dtype(name)
                arr = np.asarray(data, dtype=dtype)
                self.predictor.get_input_handle(name).copy_from_cpu(arr)
            self.predictor.run()
            outs = {}
            for name in self.predictor.get_output_names():
                a = np.asarray(
                    self.predictor.get_output_handle(name).copy_to_cpu())
                outs[name] = {"data": a.tolist(), "dtype": str(a.dtype),
                              "shape": list(a.shape)}
        return {"outputs": outs}

    # ------------------------------------------------------------------
    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):        # quiet by default
                pass

            def _send(self, code, obj, retry_after=None):
                send_json(self, code, obj, retry_after=retry_after)

            def do_GET(self):
                if self.path == "/health":
                    self._send(200, {"status": "ok"})
                elif self.path == "/healthz":
                    ready, body = server._readiness()
                    ra = None
                    if not ready:
                        ra = (RETRY_AFTER_S["warming_up"]
                              if body.get("status") == "warming"
                              else RETRY_AFTER_S["draining"]
                              if body.get("status") == "draining"
                              else RETRY_AFTER_S["unready"])
                    self._send(200 if ready else 503, body,
                               retry_after=ra)
                elif self.path == "/metrics":
                    send_text(self, 200, _obs.metrics.registry.render())
                elif self.path == "/metadata":
                    self._send(200, server._metadata())
                else:
                    self._send(404, {"error": f"no route {self.path}"})

            def _drain_body(self):
                """Read (and discard) any unread request body —
                responding with unread POST bytes on the socket resets
                the connection instead of delivering the response."""
                try:
                    self.rfile.read(
                        int(self.headers.get("Content-Length", "0")))
                except (ValueError, OSError):
                    pass

            def _read_json_body(self):
                """Parsed JSON request body, or None when it is
                unreadable or malformed — the ONE body-read idiom for
                every POST route (each caller picks its own error
                response; a half-sent or non-JSON body is the
                client's fault, never a 500)."""
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    return json.loads(self.rfile.read(n) or b"{}")
                except (ValueError, OSError):
                    return None

            def do_POST(self):
                if self.path == "/drain":
                    # admin: flip into draining (idempotent). The
                    # caller (router rolling restart / serve_tier)
                    # polls /healthz "inflight" to watch it empty, then
                    # terminates the process, whose SIGTERM path runs
                    # stop(drain_s) as a belt-and-braces second wait
                    self._drain_body()
                    n = server.begin_drain()
                    self._send(200, {"status": "draining",
                                     "inflight": n})
                    return
                if self.path.startswith("/admin/trace"):
                    handle_admin_trace(self, self._drain_body)
                    return
                if self.path == "/cancel":
                    self._do_cancel()
                    return
                if self.path.startswith("/admin/inject"):
                    self._do_admin_inject()
                    return
                if self.path == "/generate":
                    self._do_generate()
                    return
                if self.path == "/prewarm":
                    self._do_prewarm()
                    return
                if self.path != "/predict":
                    self._send(404, {"error": f"no route {self.path}"})
                    return
                if server.predictor is None:
                    # mirror of /generate on an engine-less server: the
                    # route does not exist HERE (404), it is not the
                    # client's request that is malformed (400)
                    self._send(404, {"error": "no predictor loaded "
                                              "(engine-only server — "
                                              "POST /generate)"})
                    return
                # load shedding BEFORE reading the body into the queue:
                # a saturated predict worker means every queued request
                # would blow its deadline anyway — 503 now is cheaper
                # for the client than 503 in deadline_s seconds. The
                # draining check lives in the SAME locked block as the
                # depth increment (atomic against begin_drain's flip),
                # and every shed drains the unread body first — a 503
                # on unread POST bytes is a connection reset, not a
                # delivered response
                with server._depth_lock:
                    if server._draining:
                        shed, depth = "draining", server._depth
                    elif server._depth >= server.max_queue:
                        shed, depth = "overloaded", server._depth
                    else:
                        shed = None
                        server._depth += 1
                        # depth alone is NOT the drain signal: the
                        # worker releases it when the predict call
                        # finishes, which can be BEFORE this handler
                        # writes the response — the response counter
                        # keeps the drain waiting until the bytes are
                        # actually out
                        server._resp_inflight += 1
                if shed is not None:
                    self._drain_body()
                    self._send(503, {"error": shed,
                                     "queue_depth": depth})
                    return

                def release():
                    with server._depth_lock:
                        server._depth -= 1

                # depth is released by whoever last holds the work: the
                # WORKER once the call actually finishes (a wedged call
                # abandoned at its deadline keeps occupying depth, so
                # the gate above sheds followers immediately), or this
                # handler if the work never reached the worker
                def run_and_release(payload):
                    try:
                        return server._predict(payload)
                    finally:
                        release()

                submitted = False
                try:
                    payload = self._read_json_body()
                    if payload is None:
                        self._send(400, {"error": "bad body"})
                        return
                    fut = server._pool.submit(run_and_release, payload)
                    submitted = True
                    try:
                        out = fut.result(timeout=server.deadline_s)
                    except FutureTimeout:
                        # abandon the call: if still queued the cancel
                        # wins (release here); if running, the worker
                        # stays wedged holding its depth slot and THIS
                        # client gets its 503 now
                        if fut.cancel():
                            release()
                        server._failure_streak += 1
                        self._send(503, {
                            "error": "deadline_exceeded",
                            "deadline_s": server.deadline_s})
                        return
                    server._failure_streak = 0
                    self._send(200, out)
                except (_resil.FaultInjected, ConnectionError) as e:
                    server._failure_streak += 1
                    self._send(503, {"error":
                                     f"backend_unavailable: {e}"})
                except (ValueError, KeyError) as e:
                    self._send(400, {"error": str(e)})
                except Exception as e:   # noqa: BLE001 — report, keep serving
                    server._failure_streak += 1
                    code = 503 if "unavailable" in str(e).lower() else 500
                    self._send(code,
                               {"error": f"{type(e).__name__}: {e}"})
                finally:
                    with server._depth_lock:
                        server._resp_inflight -= 1
                    if not submitted:
                        release()

            def _do_generate(self):
                """Generate through the continuous-batching engine.
                Load shedding is the ENGINE's queue cap (its tick loop
                is the one worker); each request parks on its own
                future until its slot retires it."""
                if server.engine is None:
                    self._send(404, {"error": "no generation engine "
                                              "attached to this server"})
                    return
                if server._warm_state == "warming":
                    # shed with the load-shedding 503 contract instead
                    # of queueing the request behind the compile — an
                    # orchestrator retries against a ready replica.
                    # Drain the request body first: responding with
                    # unread bytes on the socket resets the connection
                    # instead of delivering the 503
                    self._drain_body()
                    self._send(503, {"error": "warming_up",
                                     "queue_depth": 0})
                    return
                # draining check + in-flight increment are ONE atomic
                # step against begin_drain's locked flip: either this
                # request is counted before the drain waiter can read
                # inflight()==0, or it sheds — an admitted request is
                # never abandoned by a graceful shutdown
                with server._depth_lock:
                    draining = server._draining
                    if not draining:
                        server._resp_inflight += 1
                if draining:
                    # rolling restart in progress: nothing new may be
                    # admitted; the router already saw /healthz flip
                    self._drain_body()
                    self._send(503, {"error": "draining"})
                    return
                try:
                    self._generate_admitted()
                finally:
                    with server._depth_lock:
                        server._resp_inflight -= 1

            def _do_cancel(self):
                """POST /cancel {"request_id": rid} — real request
                cancellation through the engine: queued requests
                resolve now, admitted ones retire at the next tick
                boundary (slot + KV pages reclaimed). The cancelled
                request's own waiter gets its 409 / stream err line
                with the partial result; THIS response only reports
                whether a live request matched."""
                payload = self._read_json_body() or {}
                if server.engine is None:
                    self._send(404, {"error": "no generation engine "
                                              "attached to this server"})
                    return
                rid = (payload.get("request_id")
                       or self.headers.get(REQUEST_ID_HEADER))
                if not rid:
                    self._send(400, {"error": "request_id required"})
                    return
                ok = server.engine.cancel(str(rid))
                self._send(200, {"cancelled": bool(ok),
                                 "request_id": str(rid)})

            def _do_prewarm(self):
                """POST /prewarm {"input_ids": [...]} — warm the paged
                KV prefix cache with a prompt WITHOUT a client waiting
                on the output: one-token generate through the normal
                admission path (prefill writes the prompt's pages, the
                trie keeps them as reusable prefix after the slot
                retires), result discarded. The router fires this at a
                STANDBY replica while a journaled stream runs elsewhere,
                so a failover's resumed prefill lands on trie hits
                instead of recomputing the whole prefix (ISSUE 17).
                Best-effort by contract: a busy/warming/unpaged replica
                sheds with the standard 503/200 truth — the caller loses
                nothing but the head start."""
                from .engine import EngineOverloaded
                if server.engine is None:
                    self._send(404, {"error": "no generation engine "
                                              "attached to this server"})
                    return
                if server._warm_state == "warming" or server._draining:
                    self._drain_body()
                    self._send(503, {"error": "warming_up"
                                     if server._warm_state == "warming"
                                     else "draining"})
                    return
                payload = self._read_json_body()
                if payload is None or "input_ids" not in payload:
                    self._send(400, {"error": "input_ids required"})
                    return
                paged = bool(getattr(server.engine, "paged", False))
                try:
                    fut = server.engine.submit(payload["input_ids"], 1,
                                               seed=0)
                except EngineOverloaded as e:
                    self._send(503, {"error": e.reason,
                                     "queue_depth": e.queue_depth})
                    return
                except (ValueError, KeyError, TypeError) as e:
                    self._send(400, {"error": str(e)})
                    return
                except Exception as e:   # noqa: BLE001 — broken engine
                    self._send(503, {"error":
                                     f"backend_unavailable: {e}"})
                    return
                try:
                    fut.result(timeout=server.deadline_s)
                except Exception as e:   # noqa: BLE001 — best-effort
                    self._send(503, {"error":
                                     f"prewarm_failed: {e}"})
                    return
                n = len(np.asarray(payload["input_ids"]).reshape(-1))
                self._send(200, {"prewarmed": paged,
                                 "prompt_len": n, "paged": paged})

            def _do_admin_inject(self):
                """POST /admin/inject {"site": s, "count": n,
                "wedge_s": opt} — arm a resilience fault site in this
                LIVE process (chaos tooling: the tier bench wedges one
                replica's decode loop with `replica_stall` to exercise
                hedged decode). Refused unless the process was started
                with PADDLE_TPU_CHAOS_ADMIN=1 — production replicas
                must not expose a self-sabotage endpoint."""
                payload = self._read_json_body()
                if payload is None:
                    self._send(400, {"error": "bad body"})
                    return
                if not _env_bool("PADDLE_TPU_CHAOS_ADMIN", False):
                    self._send(403, {"error": "chaos admin disabled "
                                              "(PADDLE_TPU_CHAOS_ADMIN)"})
                    return
                site = payload.get("site")
                count = payload.get("count", 1)
                wedge_s = payload.get("wedge_s")
                try:
                    _resil.arm_fault(str(site), int(count),
                                     None if wedge_s is None
                                     else float(wedge_s))
                except (ValueError, TypeError) as e:
                    self._send(400, {"error": str(e)})
                    return
                self._send(200, {"armed": str(site),
                                 "count": int(count),
                                 "wedge_s": wedge_s})

            def _generate_admitted(self):
                # request-id propagation: honor the router's header,
                # mint one otherwise — every response can be resolved
                # to its engine spans (queue-wait/prefill/decode)
                rid = self.headers.get(REQUEST_ID_HEADER) or (
                    uuid.uuid4().hex[:16] if _obs.enabled() else None)
                # the handler-wall span: what the engine phases don't
                # cover (json parse, future wait wakeup, response
                # write) is visible as serve.generate minus their sum
                with _obs.span("serve.generate", cat="serve",
                               request_id=rid):
                    self._generate_traced(rid)

            def _generate_traced(self, rid):
                from .engine import EngineOverloaded
                stream = False
                evq = None
                try:
                    payload = self._read_json_body()
                    if payload is None:
                        self._send(400, {"error": "bad body"})
                        return
                    ids = payload["input_ids"]
                    stream = bool(payload.get("stream"))
                    progress = None
                    if stream:
                        # incremental mode: the engine's per-tick
                        # progress callback feeds an event queue this
                        # handler drains into NDJSON lines — the
                        # token side-channel the router journals
                        evq = _queue.Queue()
                        progress = (lambda toks, q=evq:
                                    q.put(("t", toks)))
                    fut = server.engine.submit(
                        ids,
                        int(payload.get("max_new_tokens", 32)),
                        payload.get("eos_token_id"),
                        int(payload.get("seed", 0)),
                        request_id=rid, progress_cb=progress)
                except EngineOverloaded as e:
                    # identical record shape to the predictor path's
                    # load shedding — orchestrators see ONE contract;
                    # the reason is the engine's truthful verdict
                    # ("cache_exhausted" when the paged KV pool, not
                    # slot count, is what is binding)
                    body = {"error": e.reason,
                            "queue_depth": e.queue_depth}
                    if getattr(e, "free_pages", None) is not None:
                        body["free_pages"] = e.free_pages
                        body["num_pages"] = e.num_pages
                    self._send(503, body)
                    return
                except (_resil.FaultInjected, ConnectionError) as e:
                    server._failure_streak += 1
                    self._send(503, {"error":
                                     f"backend_unavailable: {e}"})
                    return
                except (ValueError, KeyError, TypeError) as e:
                    self._send(400, {"error": str(e)})
                    return
                except Exception as e:   # noqa: BLE001 — broken engine
                    # e.g. submit() on a broken/stopped engine raises
                    # RuntimeError; the client still gets its 503, not
                    # a dropped socket
                    server._failure_streak += 1
                    self._send(503, {"error":
                                     f"backend_unavailable: {e}"})
                    return
                prompt_len = len(np.asarray(ids).reshape(-1))
                if stream:
                    self._generate_stream_body(fut, evq, rid,
                                               prompt_len)
                    return
                from .engine import RequestCancelled
                try:
                    out = fut.result(timeout=server.deadline_s)
                except FutureTimeout:
                    server._failure_streak += 1
                    if rid:
                        # the waiter is giving up: stop decoding for a
                        # client that will never read the result
                        server.engine.cancel(rid)
                    self._send(503, {"error": "deadline_exceeded",
                                     "deadline_s": server.deadline_s})
                    return
                except RequestCancelled:
                    # cancelled via POST /cancel (hedge loser, client
                    # disconnect elsewhere): 409 with the PARTIAL
                    # result — tokens generated before the cancel are
                    # surfaced, never discarded
                    info = getattr(fut, "_ptpu_gen_info", None) or {}
                    body = {"error": "cancelled"}
                    body.update(info)
                    if rid:
                        body["request_id"] = rid
                    self._send(409, body)
                    return
                except Exception as e:   # noqa: BLE001 — engine fault
                    server._failure_streak += 1
                    body = {"error": f"backend_unavailable: {e}"}
                    # partial-result accounting rides the error path
                    # too (engine attaches it in _fail_all)
                    body.update(getattr(fut, "_ptpu_gen_info", None)
                                or {})
                    self._send(503, body)
                    return
                server._failure_streak = 0
                # detokenize/respond phase: array -> JSON body (the
                # closest thing this token server has to detokenizing)
                with _obs.span("serve.detokenize", cat="serve",
                               request_id=rid):
                    body = {"tokens": out.tolist(),
                            "prompt_len": prompt_len,
                            "new_tokens": len(out) - prompt_len}
                    # per-request generation accounting the engine
                    # published on the future at retirement:
                    # tokens_generated (actual emissions, eos padding
                    # excluded) always; drafted/accepted on
                    # speculative engines. The router forwards these
                    # body fields unchanged (test_router.py).
                    info = getattr(fut, "_ptpu_gen_info", None)
                    if info:
                        body.update(info)
                    if rid:
                        body["request_id"] = rid
                self._send(200, body)

            # -- incremental (streaming) generate ----------------------
            def _write_event(self, obj):
                self.wfile.write((json.dumps(obj) + "\n").encode())
                self.wfile.flush()

            def _generate_stream_body(self, fut, evq, rid, prompt_len):
                """Write the NDJSON event stream for one admitted
                request: {"t": [...]} per emitted block, then one
                terminal {"done": body} / {"err": record} line, then
                close (read-until-close framing — no chunked encoding
                needed, and a dead replica is unmistakable: EOF
                without a terminal line). The terminal body is
                authoritative; token lines exist so the reader can
                journal progress and detect stalls."""
                from .engine import RequestCancelled
                fut.add_done_callback(lambda f: evq.put(("fin", None)))
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/x-ndjson")
                self.send_header("Connection", "close")
                self.end_headers()
                self.close_connection = True
                deadline = time.monotonic() + server.deadline_s
                sent = 0
                try:
                    while True:
                        timeout = deadline - time.monotonic()
                        if timeout <= 0:
                            server._failure_streak += 1
                            if rid:
                                server.engine.cancel(rid)
                            self._write_event({"err": {
                                "error": "deadline_exceeded",
                                "deadline_s": server.deadline_s,
                                "tokens_generated": sent}})
                            return
                        try:
                            kind, toks = evq.get(
                                timeout=min(timeout, 0.5))
                        except _queue.Empty:
                            continue
                        if kind == "t":
                            self._write_event({"t": toks})
                            sent += len(toks)
                            continue
                        break                    # fin: future resolved
                    try:
                        out = fut.result(timeout=0)
                    except RequestCancelled:
                        info = getattr(fut, "_ptpu_gen_info",
                                       None) or {}
                        rec = {"error": "cancelled"}
                        rec.update(info)
                        if rid:
                            rec["request_id"] = rid
                        self._write_event({"err": rec})
                        return
                    except Exception as e:   # noqa: BLE001 — engine
                        server._failure_streak += 1
                        rec = {"error": f"backend_unavailable: {e}"}
                        rec.update(getattr(fut, "_ptpu_gen_info",
                                           None) or {})
                        self._write_event({"err": rec})
                        return
                    server._failure_streak = 0
                    with _obs.span("serve.detokenize", cat="serve",
                                   request_id=rid):
                        body = {"tokens": out.tolist(),
                                "prompt_len": prompt_len,
                                "new_tokens": len(out) - prompt_len}
                        info = getattr(fut, "_ptpu_gen_info", None)
                        if info:
                            body.update(info)
                        if rid:
                            body["request_id"] = rid
                    self._write_event({"done": body})
                except (BrokenPipeError, ConnectionError, OSError):
                    # the reader (router/client) went away mid-stream:
                    # stop generating for a stream nobody reads —
                    # cancellation reclaims the slot and its pages
                    if rid:
                        server.engine.cancel(rid)

        return Handler

    # ------------------------------------------------------------------
    def start(self, background: bool = True):
        if background:
            self._thread = threading.Thread(
                target=self.httpd.serve_forever, daemon=True)
            self._thread.start()
        else:
            self.httpd.serve_forever()
        return self

    def stop(self, drain_s: float = 0.0):
        """Shut the server down. ``drain_s > 0`` is the graceful path:
        flip into draining (new admissions shed 503 "draining", the
        listener keeps answering so in-flight responses and health
        polls still flow), wait — bounded by ``drain_s`` — for every
        admitted request to finish, THEN tear the listener down. The
        default 0 keeps the historical fast stop: shut down now and
        abandon whatever is in flight (a wedged predict call must not
        be able to hold shutdown hostage)."""
        if drain_s and drain_s > 0:
            self.begin_drain()
            deadline = time.monotonic() + float(drain_s)
            while self.inflight() > 0 and time.monotonic() < deadline:
                time.sleep(0.02)
        self.httpd.shutdown()
        self.httpd.server_close()
        # past the (bounded) drain: don't wait for a possibly-wedged
        # predict call — abandon it
        self._pool.shutdown(wait=False, cancel_futures=True)
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._warmup_thread is not None:
            # a mid-compile warmup thread is daemon + side-effect-free
            # past this point; don't block shutdown on it
            self._warmup_thread.join(timeout=1)
            self._warmup_thread = None
        if self._owned_predictor is not None:
            # engine built from OUR Config: stop its tick thread and
            # release the slot cache (an explicitly-passed engine is
            # the caller's to stop)
            self._owned_predictor.close()
            self._owned_predictor = None
            self.engine = None


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Serve a saved paddle_tpu model over HTTP")
    ap.add_argument("--model", required=True,
                    help="path to the saved .pdmodel")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8866)
    ap.add_argument("--warmup", action="store_true",
                    help="AOT-warm the engine's programs before "
                         "accepting /generate traffic (healthz reports "
                         "warming until done); default from "
                         "PADDLE_TPU_SERVE_WARMUP")
    args = ap.parse_args(argv)
    srv = PredictorServer(args.model, args.host, args.port,
                          warmup=args.warmup or None)
    print(f"serving {args.model} on http://{srv.host}:{srv.port}",
          flush=True)
    srv.start(background=False)


if __name__ == "__main__":
    main()
