"""HTTP serving front-end over the inference predictor.

Serving-path role (BASELINE.json north star: "ERNIE-3.0 served
end-to-end"): the reference serves through AnalysisPredictor embedded in
C++ servers or the FleetExecutor DistModel service
(fleet_executor/dist_model.cc). TPU-native equivalent: the AOT-compiled
predictor (inference/predictor.py) behind a threaded stdlib HTTP server —
zero extra dependencies, JSON tensors in/out.

Endpoints:
  GET  /health    -> {"status": "ok"}
  GET  /metadata  -> input/output names (+ dtypes/shapes once known)
  POST /predict   -> {"inputs": {name: nested-list | {"data": ...,
                      "dtype": "float32"}}} -> {"outputs": {name: ...}}

CLI: python -m paddle_tpu.inference.serve --model m.pdmodel --port 8866
"""
from __future__ import annotations

import argparse
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from .predictor import Config, create_predictor

__all__ = ["PredictorServer", "main"]


class PredictorServer:
    """Owns one predictor and an HTTP server bound to host:port.

    The predictor is not thread-safe (zero-copy handles are shared
    state), so requests serialize on a lock — concurrency comes from the
    XLA program itself, which is where the time goes.
    """

    def __init__(self, model_path_or_config, host: str = "127.0.0.1",
                 port: int = 8866):
        cfg = (model_path_or_config
               if isinstance(model_path_or_config, Config)
               else Config(model_path_or_config))
        self.predictor = create_predictor(cfg)
        self._lock = threading.Lock()
        self.httpd = ThreadingHTTPServer((host, port),
                                         self._make_handler())
        self.host, self.port = self.httpd.server_address[:2]
        self._thread = None

    # ------------------------------------------------------------------
    def _metadata(self):
        return {"inputs": self.predictor.get_input_names(),
                "outputs": self.predictor.get_output_names()}

    def _predict(self, payload):
        inputs = payload.get("inputs")
        if not isinstance(inputs, dict):
            raise ValueError('body must be {"inputs": {name: tensor}}')
        names = self.predictor.get_input_names()
        unknown = set(inputs) - set(names)
        if unknown:
            raise ValueError(f"unknown input(s) {sorted(unknown)}; "
                             f"expected {names}")
        missing = set(names) - set(inputs)
        if missing:
            raise ValueError(f"missing input(s) {sorted(missing)}")
        with self._lock:
            for name in names:
                v = inputs[name]
                dtype = v.get("dtype") if isinstance(v, dict) else None
                data = v["data"] if isinstance(v, dict) else v
                if dtype is None:
                    # JSON numbers arrive as int64/float64: coerce to the
                    # model's declared input dtype when it is known
                    dtype = self.predictor.get_input_dtype(name)
                arr = np.asarray(data, dtype=dtype)
                self.predictor.get_input_handle(name).copy_from_cpu(arr)
            self.predictor.run()
            outs = {}
            for name in self.predictor.get_output_names():
                a = np.asarray(
                    self.predictor.get_output_handle(name).copy_to_cpu())
                outs[name] = {"data": a.tolist(), "dtype": str(a.dtype),
                              "shape": list(a.shape)}
        return {"outputs": outs}

    # ------------------------------------------------------------------
    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):        # quiet by default
                pass

            def _send(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/health":
                    self._send(200, {"status": "ok"})
                elif self.path == "/metadata":
                    self._send(200, server._metadata())
                else:
                    self._send(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path != "/predict":
                    self._send(404, {"error": f"no route {self.path}"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    payload = json.loads(self.rfile.read(n) or b"{}")
                    self._send(200, server._predict(payload))
                except (ValueError, KeyError) as e:
                    self._send(400, {"error": str(e)})
                except Exception as e:   # noqa: BLE001 — report, keep serving
                    self._send(500, {"error": f"{type(e).__name__}: {e}"})

        return Handler

    # ------------------------------------------------------------------
    def start(self, background: bool = True):
        if background:
            self._thread = threading.Thread(
                target=self.httpd.serve_forever, daemon=True)
            self._thread.start()
        else:
            self.httpd.serve_forever()
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Serve a saved paddle_tpu model over HTTP")
    ap.add_argument("--model", required=True,
                    help="path to the saved .pdmodel")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8866)
    args = ap.parse_args(argv)
    srv = PredictorServer(args.model, args.host, args.port)
    print(f"serving {args.model} on http://{srv.host}:{srv.port}",
          flush=True)
    srv.start(background=False)


if __name__ == "__main__":
    main()
