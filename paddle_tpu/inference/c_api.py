"""Build helper for the C inference API (native/c_api.cc).

Reference role: paddle/fluid/inference/capi_exp/ — a C surface consumable
from C/Go. `build_c_api()` compiles libpaddle_capi.so on demand with the
embedding flags of the CURRENT interpreter (python3-config --embed), the
same on-demand pattern as the TCPStore/shm-ring natives.
"""
from __future__ import annotations

import os
import subprocess
import sysconfig
from typing import Optional

__all__ = ["build_c_api", "c_api_path"]

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native", "c_api.cc")
_CACHE_DIR = os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu")
_SO = os.path.join(_CACHE_DIR, "libpaddle_capi.so")


def build_c_api(force: bool = False) -> Optional[str]:
    """Compile (if stale) and return the path of libpaddle_capi.so, or
    None when the toolchain is unavailable."""
    if not os.path.exists(_SRC):
        return None
    if not force and os.path.exists(_SO) and \
            os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return _SO
    os.makedirs(_CACHE_DIR, exist_ok=True)
    inc = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR")
    ver = sysconfig.get_config_var("LDVERSION") or sysconfig.get_config_var(
        "VERSION")
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", _SRC,
           f"-I{inc}", f"-L{libdir}", f"-lpython{ver}",
           f"-Wl,-rpath,{libdir}", "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=180)
        os.replace(tmp, _SO)
        return _SO
    except (subprocess.SubprocessError, OSError):
        return None


def c_api_path() -> Optional[str]:
    # build_c_api already returns the cached .so when it is fresh and
    # rebuilds when the source is newer — no extra existence check here
    return build_c_api()
