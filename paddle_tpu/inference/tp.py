"""Tensor-parallel serving slice: replica = N-chip slice (ISSUE 20).

The training side has owned the mesh machinery since PR 4 — pjit over
named axes, Megatron TP layouts on every Column/RowParallelLinear, the
8/64-virtual-device harness — while serving stayed single-device end to
end. This module is the bridge: a :class:`TPContext` wraps ONE engine's
slice of ``tp`` devices as a dedicated ``("mp",)`` mesh and activates it
around that engine's program traces only (``distributed.mesh.use_mesh``
is thread-local — a TP engine and a single-chip engine, or a training
thread, coexist in one process without leaking "mp" constraints into
each other's traces).

What gets sharded (the Megatron serving layout):

====================  =========================  =====================
tensor                shape                      PartitionSpec
====================  =========================  =====================
Column weights        [in, out]                  (None, "mp")
Column bias           [out]                      ("mp",)
Row weights           [in, out]                  ("mp", None)
vocab embedding       [V, H]                     ("mp", None)
everything else       —                          replicated
KV data/pages         [..., nkv, hd]             nkv axis -> "mp"
int8 scale planes     [..., nkv]                 nkv axis -> "mp"
block tables / masks  host int32/bool            replicated
====================  =========================  =====================

The param specs are not decided here — they are read off each
parameter's ``sharding_axes`` annotation (mp_layers set them at model
construction; GPT and Llama both build their blocks from the parallel
layers), so the engine shards EXACTLY the layout training would. KV
pools shard on the head axis because column-parallel QKV already
computes only the local heads per chip; block tables stay replicated so
``paging.py``'s host-side allocator/trie/COW logic is untouched.

Per-block wire traffic is one all-reduce after attention out-proj and
one after the MLP down-proj (GSPMD derives them from the
replicated-output constraint in RowParallelLinear). Under
``comm_precision="int8"|"bf16"`` the engine traces its programs inside
``mp_layers.tp_comm_precision(...)``, routing those reductions through
the PR 17 EQuARX bodies (quantized wire, f32 accumulate) instead.

Correctness oracle (tests/test_tp_engine.py, tools/bench_tp_decode.py):
greedy token IDs from a tp>1 engine are identical to the single-chip
engine — slot and paged, f32 and int8 caches, speculative verify
included — with zero recompiles under prompt-length drift.
"""
from __future__ import annotations

import contextlib
from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..distributed import mesh as mesh_mod
from ..distributed.meta_parallel.mp_layers import tp_comm_precision
from ..framework.env import int_env as _env_int

__all__ = ["TPContext", "build_tp_mesh", "resolve_tp",
           "validate_tp_model", "TP_AXIS"]

# the serving slice reuses the training mesh's innermost (fastest-ICI)
# axis name, so every mp_layers ``sharding_axes`` annotation and
# ``_constrain`` call resolves against it unchanged
TP_AXIS = "mp"


def resolve_tp(tp: Optional[int]) -> int:
    """Effective tensor-parallel degree: explicit arg wins, then
    PADDLE_TPU_SERVE_TP, default 1 (the single-chip engine)."""
    if tp is None:
        tp = _env_int("PADDLE_TPU_SERVE_TP", 1)
    tp = int(tp)
    if tp < 1:
        raise ValueError(f"tp degree must be >= 1, got {tp}")
    return tp


def build_tp_mesh(tp: int, devices: Optional[Sequence] = None) -> Mesh:
    """A dedicated ``(tp,)`` mesh over the leading ``tp`` devices with
    the single axis "mp" — the serving slice. Built directly (not via
    ``init_mesh``) so it NEVER installs itself process-globally; the
    engine activates it thread-locally around its own traces."""
    devices = list(devices if devices is not None else jax.devices())
    if tp > len(devices):
        raise ValueError(
            f"tp={tp} needs {tp} devices, have {len(devices)} "
            f"(virtual-mesh runs: XLA_FLAGS="
            f"--xla_force_host_platform_device_count={tp})")
    return Mesh(np.asarray(devices[:tp]), (TP_AXIS,))


def validate_tp_model(model, tp: int) -> None:
    """Loud divisibility gate: head counts (the KV pools shard on the
    kv-head axis) and every sharded weight dimension must divide by tp.
    An uneven split would make GSPMD pad shards — correct-looking but
    silently different layouts per chip, and the KV head/scale planes
    would no longer align with the column-parallel heads."""
    cfg = getattr(model, "cfg", None)
    nh = getattr(cfg, "num_heads", None)
    if nh is not None and nh % tp:
        raise ValueError(
            f"tp={tp} does not divide num_heads={nh}: attention heads "
            "shard per-head (Megatron convention)")
    nkv = getattr(cfg, "kv_heads", None)
    if nkv is None:
        nkv = getattr(cfg, "num_kv_heads", None) or nh
    if nkv is not None and nkv % tp:
        raise ValueError(
            f"tp={tp} does not divide kv_heads={nkv}: the KV pools "
            "shard on the kv-head axis")
    for name, p in model.named_parameters():
        axes = getattr(p, "sharding_axes", None)
        if not axes:
            continue
        for dim, ax in enumerate(axes):
            names = (ax,) if isinstance(ax, str) else tuple(ax or ())
            if TP_AXIS in names and p.shape[dim] % tp:
                raise ValueError(
                    f"tp={tp} does not divide dim {dim} "
                    f"({p.shape[dim]}) of sharded parameter {name!r}")


class TPContext:
    """One engine's tensor-parallel slice: the mesh, the trace-time
    activation scope, and the device_put helpers that land params /
    buffers / KV caches in the Megatron layout."""

    def __init__(self, tp: int, devices: Optional[Sequence] = None,
                 comm_precision: Optional[str] = None,
                 mesh: Optional[Mesh] = None):
        self.tp = int(tp)
        if mesh is not None:
            if TP_AXIS not in mesh.shape:
                raise ValueError(
                    f"engine mesh needs a {TP_AXIS!r} axis, has "
                    f"{tuple(mesh.shape)}")
            if mesh.shape[TP_AXIS] != self.tp:
                raise ValueError(
                    f"mesh {TP_AXIS} degree {mesh.shape[TP_AXIS]} != "
                    f"tp {self.tp}")
            self.mesh = mesh
        else:
            self.mesh = build_tp_mesh(self.tp, devices)
        if comm_precision not in (None, "fp32", "bf16", "int8"):
            raise ValueError(
                f"comm_precision {comm_precision!r}: "
                "expected fp32|bf16|int8")
        self.comm_precision = (None if comm_precision == "fp32"
                               else comm_precision)
        self._replicated = NamedSharding(self.mesh, P())

    # -- trace-time activation ------------------------------------------
    @contextlib.contextmanager
    def activate(self):
        """Thread-locally make this slice THE mesh (mp_layers'
        ``_constrain`` emits real "mp" constraints) and route the
        per-block all-reduce through the quantized wire bodies when
        configured. Wraps every engine trace/dispatch site; a no-op for
        the math on re-execution, but kept on the call path so lazy
        (non-warmup) first calls trace correctly too."""
        with mesh_mod.use_mesh(self.mesh):
            with tp_comm_precision(self.comm_precision):
                yield self

    # -- placement helpers ----------------------------------------------
    def replicate(self, tree):
        """device_put a pytree fully replicated over the slice."""
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, self._replicated), tree)

    def shard_state(self, model, params: dict, buffers: dict):
        """Land ``raw_state(model)``'s params/buffers on the slice:
        each parameter by its own ``sharding_axes`` annotation (the
        layout mp_layers declared at construction), buffers (and
        un-annotated params) replicated."""
        axes = {n: getattr(p, "sharding_axes", None)
                for n, p in model.named_parameters()}
        out_p = {}
        for name, value in params.items():
            spec = axes.get(name)
            sh = (mesh_mod.named_sharding(*spec, mesh=self.mesh)
                  if spec else self._replicated)
            out_p[name] = jax.device_put(value, sh)
        out_b = {n: jax.device_put(v, self._replicated)
                 for n, v in buffers.items()}
        return out_p, out_b

    def cache_sharding(self, key: Optional[str], ndim: int):
        """The ONE rule for every KV-cache leaf shape this repo has:
        int8 scale planes ([..., nkv]) shard on their LAST axis, data
        leaves ([..., nkv, hd]) on their second-to-last — covering slot
        rows, paged pools, int8 dict halves and the scan-stacked
        (leading-L) variants of each without enumeration."""
        axes = [None] * ndim
        axes[ndim - 1 if key == "scale" else ndim - 2] = TP_AXIS
        return mesh_mod.named_sharding(*axes, mesh=self.mesh)

    def shard_caches(self, caches):
        """device_put a cache pytree (any engine form) head-sharded."""
        def put(path, leaf):
            key = None
            for entry in reversed(path):
                if isinstance(entry, jax.tree_util.DictKey):
                    key = entry.key
                    break
            return jax.device_put(
                leaf, self.cache_sharding(key, leaf.ndim))
        return jax.tree_util.tree_map_with_path(put, caches)

    # -- accounting / reporting -----------------------------------------
    def modeled_tick_comm_bytes(self, num_layers: int, hidden: int,
                                slots: int, tick_tokens: int) -> int:
        """Analytic PER-CHIP all-reduce bytes one decode tick moves:
        tick_tokens micro-steps, each forwarding [slots, 1, hidden]
        through num_layers blocks with TWO replicated-output reductions
        per block (attention out-proj + MLP down-proj), priced at the
        ring all-reduce's 2*(tp-1)/tp per-chip wire factor and the
        configured wire precision's bytes/element. The same formula the
        obs tick span reports and bench_tp_decode tabulates — tpucost's
        comm_bytes anchor measures the real HLO bytes this models."""
        if self.tp == 1:
            return 0
        wire = {"int8": 1.0 + 4.0 / 256.0,   # int8 payload + f32 block
                "bf16": 2.0}.get(self.comm_precision, 4.0)  # scales
        payload = slots * hidden * wire
        ring = 2.0 * (self.tp - 1) / self.tp
        return int(tick_tokens * num_layers * 2 * payload * ring)

    def describe(self) -> dict:
        """Mesh geometry for stats()/healthz — JSON-safe."""
        return {"tp": self.tp, "mesh_axis": TP_AXIS,
                "mesh_devices": int(np.prod(self.mesh.devices.shape)),
                "comm_precision": self.comm_precision or "fp32",
                "devices": [str(d) for d in self.mesh.devices.flat]}
