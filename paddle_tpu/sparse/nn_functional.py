"""paddle.sparse.nn.functional parity (reference:
python/paddle/sparse/nn/functional/__init__.py — conv/pooling/
activation/transformer over phi sparse kernels).

Values-only ops act on the stored nnz values; conv/pooling reuse the
gather-matmul plan in sparse/nn.py; attention computes the CSR-masked
softmax(QK^T)V densely (batched MXU matmuls with the sparse pattern as
mask — on TPU the dense masked form IS the fast path; the reference's
CUDA kernel exists to exploit gpu sparse formats).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..autograd.tape import apply
from ..core.tensor import Tensor
from . import SparseCooTensor, SparseCsrTensor, _raw, is_sparse
from . import relu  # noqa: F401  (re-export, same values-only op)
from . import sparse_coo_tensor

__all__ = ["conv3d", "subm_conv3d", "max_pool3d", "relu", "relu6",
           "leaky_relu", "softmax", "attention"]


def _values_map(x, fn, name):
    from jax.experimental import sparse as jsparse
    out_vals = apply(lambda v: fn(v), x.values(), _op_name=name)
    st = SparseCooTensor(jsparse.BCOO((out_vals.value, x.value.indices),
                                      shape=x.value.shape))
    st._values_tensor = out_vals
    return st


def relu6(x, name=None):
    if is_sparse(x):
        return _values_map(x, lambda v: jnp.clip(v, 0, 6), "sparse_relu6")
    return Tensor(jnp.clip(_raw(x), 0, 6))


def leaky_relu(x, negative_slope=0.01, name=None):
    if is_sparse(x):
        return _values_map(
            x, lambda v: jnp.where(v >= 0, v, negative_slope * v),
            "sparse_leaky_relu")
    v = _raw(x)
    return Tensor(jnp.where(v >= 0, v, negative_slope * v))


def softmax(x, axis=-1, name=None):
    """Softmax over the stored values of each row (last axis), the
    reference's sparse softmax semantics: zeros stay zero, nonzeros of a
    row normalize among themselves."""
    if axis not in (-1, len(x.shape) - 1):
        raise ValueError("sparse softmax supports the last axis only "
                         "(reference restriction)")
    import jax
    b = x.value
    idx = np.asarray(b.indices)                     # (nnz, ndim)
    shape = x.shape
    # segment id = flattened index of all dims except the last
    seg = np.zeros(idx.shape[0], np.int64)
    mul = 1
    for d in range(len(shape) - 2, -1, -1):
        seg += idx[:, d] * mul
        mul *= shape[d]
    n_seg = int(np.prod(shape[:-1])) if len(shape) > 1 else 1
    seg = jnp.asarray(seg)

    def f(vals):
        vmax = jax.ops.segment_max(vals, seg, num_segments=n_seg)
        vmax = jnp.where(jnp.isneginf(vmax), 0.0, vmax)
        e = jnp.exp(vals - vmax[seg])
        z = jax.ops.segment_sum(e, seg, num_segments=n_seg)
        return e / jnp.maximum(z[seg], 1e-38)

    from jax.experimental import sparse as jsparse
    out_vals = apply(f, x.values(), _op_name="sparse_softmax")
    st = SparseCooTensor(jsparse.BCOO((out_vals.value, b.indices),
                                      shape=b.shape))
    st._values_tensor = out_vals
    return st


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NDHWC", name=None):
    """Functional sparse conv3d over the gather-matmul plan."""
    from .nn import _sparse_conv3d
    if dilation not in (1, (1, 1, 1)) or groups != 1:
        raise NotImplementedError("sparse conv3d: dilation/groups != 1")
    w = weight if isinstance(weight, Tensor) else Tensor(jnp.asarray(weight))
    b = bias if (bias is None or isinstance(bias, Tensor)) else \
        Tensor(jnp.asarray(bias))
    return _sparse_conv3d(x, w, b, tuple(w.shape[:3]), stride, padding,
                          subm=False)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    from .nn import _sparse_conv3d
    if dilation not in (1, (1, 1, 1)) or groups != 1:
        raise NotImplementedError("sparse subm_conv3d: dilation/groups != 1")
    w = weight if isinstance(weight, Tensor) else Tensor(jnp.asarray(weight))
    b = bias if (bias is None or isinstance(bias, Tensor)) else \
        Tensor(jnp.asarray(bias))
    return _sparse_conv3d(x, w, b, tuple(w.shape[:3]), stride, padding,
                          subm=True)


def max_pool3d(x, kernel_size, stride=None, padding=0,
               data_format="NDHWC", name=None):
    """Sparse max pooling over EXISTING points (reference semantics:
    missing positions do not participate). The output pattern — which
    pooling windows contain at least one point — is computed on the host
    from the concrete indices; the max itself runs inside one tape op
    (dense -inf scatter + reduce_window + gather), so gradients flow
    back to the input values."""
    import jax.lax as lax
    if data_format != "NDHWC":
        raise NotImplementedError("sparse max_pool3d supports NDHWC only")
    k = (kernel_size,) * 3 if isinstance(kernel_size, int) else tuple(kernel_size)
    s = k if stride is None else (
        (stride,) * 3 if isinstance(stride, int) else tuple(stride))
    p = (padding,) * 3 if isinstance(padding, int) else tuple(padding)

    b = x.value
    idx = np.asarray(b.indices)                       # (nnz, 4) b,z,y,x
    shape = x.shape                                   # (B, D, H, W, C)
    out_sp = [(shape[1 + d] + 2 * p[d] - k[d]) // s[d] + 1
              for d in range(3)]
    # windows each point contributes to, per spatial dim
    wins = set()
    for row in idx:
        ranges = []
        for d in range(3):
            z = int(row[1 + d])
            lo = max(0, -(-(z + p[d] - k[d] + 1) // s[d]))
            hi = min(out_sp[d] - 1, (z + p[d]) // s[d])
            ranges.append(range(lo, hi + 1))
        for wz in ranges[0]:
            for wy in ranges[1]:
                for wx in ranges[2]:
                    wins.add((int(row[0]), wz, wy, wx))
    out_coords = np.asarray(sorted(wins), np.int64)   # (n_out, 4)

    scatter_idx = tuple(jnp.asarray(idx[:, i]) for i in range(4))
    gather_idx = tuple(jnp.asarray(out_coords[:, i]) for i in range(4))

    def f(vals):
        window = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        pads = [(0, 0)] + [(pi, pi) for pi in p] + [(0, 0)]
        dense = jnp.full(tuple(shape), -jnp.inf, vals.dtype)
        dense = dense.at[scatter_idx].set(vals)
        pooled = lax.reduce_window(dense, -jnp.inf, lax.max, window,
                                   strides, pads)
        return pooled[gather_idx]                     # (n_out, C)

    out_vals = apply(f, x.values(), _op_name="sparse_max_pool3d")
    out_shape = (shape[0], *out_sp, shape[-1])
    st = sparse_coo_tensor(jnp.asarray(out_coords.T), out_vals.value,
                           out_shape)
    st._values_tensor = out_vals
    return st


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Parity: sparse.nn.functional.attention — attention restricted to
    a CSR sparsity pattern: out = softmax(mask(QK^T/sqrt(d))) V.
    q/k/v: [B, H, S, D] dense; sparse_mask: SparseCsrTensor [B*H, S, S]
    whose nonzero pattern marks allowed positions."""
    q = query if isinstance(query, Tensor) else Tensor(jnp.asarray(query))
    k = key if isinstance(key, Tensor) else Tensor(jnp.asarray(key))
    v = value if isinstance(value, Tensor) else Tensor(jnp.asarray(value))
    B, H, S, D = q.shape
    mask_dense = (sparse_mask.to_dense().value != 0).reshape(B, H, S, S)

    def f(qv, kv, vv, *extra):
        s = jnp.einsum("bhqd,bhkd->bhqk", qv.astype(jnp.float32),
                       kv.astype(jnp.float32)) / jnp.sqrt(float(D))
        i = 0
        m = mask_dense
        if key_padding_mask is not None:
            kp = extra[i]; i += 1
            m = m & (kp[:, None, None, :] != 0)
        if attn_mask is not None:
            am = extra[i]; i += 1
            m = m & (am[None, None] != 0)
        s = jnp.where(m, s, -jnp.inf)
        p = jnp.exp(s - jnp.max(jnp.where(m, s, -jnp.inf), -1,
                                keepdims=True, initial=-jnp.inf))
        p = jnp.where(m, p, 0.0)
        z = jnp.sum(p, -1, keepdims=True)
        p = p / jnp.maximum(z, 1e-38)
        return jnp.einsum("bhqk,bhkd->bhqd", p, vv.astype(jnp.float32)
                          ).astype(qv.dtype)

    extra = []
    if key_padding_mask is not None:
        extra.append(key_padding_mask)
    if attn_mask is not None:
        extra.append(attn_mask)
    return apply(f, q, k, v, *extra, _op_name="sparse_attention")
