"""paddle.sparse.nn parity — sparse conv / norm / activation layers for
point-cloud style COO tensors.

Reference: python/paddle/sparse/nn/ (Conv3D/SubmConv3D over
phi sparse conv kernels, BatchNorm, ReLU). TPU-native design: the
geometry (which input point contributes to which output point per
kernel offset) is data-dependent, so the gather/scatter *plan* is built
host-side from the concrete COO indices; the FLOPs — per-offset
(matched_values @ weight[k]) matmuls and the segment reductions — run
on device. Values stay differentiable; a fixed plan per coordinate set
is exactly the "rulebook" construction the reference's GPU kernels do.
"""
from __future__ import annotations

import itertools

import numpy as np

import jax.numpy as jnp

from ..autograd.tape import apply
from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from ..nn import initializer as I
from . import SparseCooTensor, sparse_coo_tensor

__all__ = ["Conv3D", "SubmConv3D", "BatchNorm", "ReLU", "LeakyReLU",
           "ReLU6", "Softmax", "MaxPool3D", "SyncBatchNorm", "functional"]


def _tuple3(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (int(v),) * 3


def _conv3d_plan(coords, spatial, kernel, stride, padding, subm):
    """Build the rulebook: output coords + per-kernel-offset (in, out)
    index pairs. coords: (nnz, 4) [b, z, y, x] host ints."""
    k = _tuple3(kernel)
    s = _tuple3(stride)
    p = _tuple3(padding)
    in_map = {tuple(c): i for i, c in enumerate(coords)}
    if subm:
        out_coords = coords
        out_map = in_map
        out_spatial = spatial
    else:
        out_spatial = tuple((spatial[i] + 2 * p[i] - k[i]) // s[i] + 1
                            for i in range(3))
        out_map = {}
        out_list = []
        for c in coords:
            b = c[0]
            for off in itertools.product(*[range(ki) for ki in k]):
                oz = [(c[1 + i] + p[i] - off[i]) for i in range(3)]
                if any(o % s[i] for i, o in enumerate(oz)):
                    continue
                oz = [o // s[i] for i, o in enumerate(oz)]
                if any(o < 0 or o >= out_spatial[i]
                       for i, o in enumerate(oz)):
                    continue
                key = (b, *oz)
                if key not in out_map:
                    out_map[key] = len(out_list)
                    out_list.append(key)
        out_coords = np.asarray(out_list, np.int64).reshape(-1, 4)
    rules = []  # per kernel offset: (in_idx array, out_idx array)
    offsets = list(itertools.product(*[range(ki) for ki in k]))
    for off in offsets:
        ins, outs = [], []
        for key, oi in (out_map.items() if not subm else
                        ((tuple(c), i) for i, c in enumerate(coords))):
            b = key[0]
            src = tuple(key[1 + i] * s[i] - p[i] + off[i]
                        for i in range(3))
            ii = in_map.get((b, *src))
            if ii is not None:
                ins.append(ii)
                outs.append(oi)
        rules.append((np.asarray(ins, np.int64),
                      np.asarray(outs, np.int64)))
    return out_coords, out_spatial, offsets, rules


def _sparse_conv3d(x: SparseCooTensor, weight, bias, kernel, stride,
                   padding, subm):
    bcoo = x.value
    coords = np.asarray(bcoo.indices)        # (nnz, 5) [b, z, y, x, c]?
    # layout: (B, D, H, W, C) with dense channel dim — values (nnz, C)
    if coords.shape[1] == 5:
        raise ValueError(
            "sparse conv expects channel-dense COO: build with "
            "sparse_coo_tensor(indices[b,z,y,x], values[nnz, C])")
    spatial = tuple(x.shape[1:4])
    n_out_c = weight.shape[-1]
    out_coords, out_spatial, offsets, rules = _conv3d_plan(
        coords, spatial, kernel, stride, padding, subm)
    n_out = len(out_coords)
    k = _tuple3(kernel)

    def f(vals, w, *b):
        # w: (kd, kh, kw, in_c, out_c) — paddle sparse conv layout
        out = jnp.zeros((n_out, n_out_c), vals.dtype)
        for (off, (ins, outs)) in zip(offsets, rules):
            if len(ins) == 0:
                continue
            wk = w[off[0], off[1], off[2]]          # (in_c, out_c)
            contrib = vals[jnp.asarray(ins)] @ wk   # MXU matmul
            out = out.at[jnp.asarray(outs)].add(contrib)
        if b:
            out = out + b[0]
        return out

    vals = x.values()  # autograd-linked when produced by sparse.nn
    args = [vals, weight] + ([bias] if bias is not None else [])
    out_vals = apply(f, *args, _op_name="sparse_conv3d")
    out_shape = (x.shape[0], *out_spatial, n_out_c)
    st = sparse_coo_tensor(jnp.asarray(out_coords.T), out_vals.value,
                           out_shape)
    st._values_tensor = out_vals
    return st


class _SparseConvBase(Layer):
    _subm = False

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__()
        assert dilation == 1 and groups == 1, (
            "sparse conv supports dilation=1, groups=1")
        k = _tuple3(kernel_size)
        self._attrs = (kernel_size, stride, padding)
        fan_in = in_channels * int(np.prod(k))
        bound = 1.0 / np.sqrt(fan_in)
        self.weight = self.create_parameter(
            list(k) + [in_channels, out_channels], attr=weight_attr,
            default_initializer=I.Uniform(-bound, bound))
        self.bias = self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True,
            default_initializer=I.Uniform(-bound, bound))

    def forward(self, x):
        ks, st, pd = self._attrs
        return _sparse_conv3d(x, self.weight, self.bias, ks, st, pd,
                              type(self)._subm)


class Conv3D(_SparseConvBase):
    """Parity: sparse/nn/layer/conv.py Conv3D (NDHWC COO input)."""
    _subm = False


class SubmConv3D(_SparseConvBase):
    """Parity: sparse/nn/layer/conv.py SubmConv3D — output coordinates
    identical to input (submanifold convolution)."""
    _subm = True


class BatchNorm(Layer):
    """Parity: sparse/nn/layer/norm.py BatchNorm — normalizes the nnz
    values per channel (the dense batch dim of a point cloud)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        self.momentum = momentum
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0))
        self.register_buffer("_mean", jnp.zeros((num_features,)))
        self.register_buffer("_variance", jnp.ones((num_features,)))

    def forward(self, x: SparseCooTensor):
        bcoo = x.value
        mom = self.momentum

        def f(vals, w, b):
            if self.training:
                mean = vals.mean(0)
                var = vals.var(0)
            else:
                mean, var = self._mean, self._variance
            out = (vals - mean) / jnp.sqrt(var + self.epsilon) * w + b
            return out

        vals = x.values()
        out_vals = apply(f, vals, self.weight, self.bias,
                         _op_name="sparse_batch_norm")
        if self.training:
            import jax
            with jax.default_device(bcoo.data.devices().pop()):
                m = bcoo.data.mean(0)
                v = bcoo.data.var(0)
            self._mean = mom * self._mean + (1 - mom) * m
            self._variance = mom * self._variance + (1 - mom) * v
        st = sparse_coo_tensor(Tensor(bcoo.indices.T), out_vals.value,
                               x.shape)
        st._values_tensor = out_vals
        return st


class ReLU(Layer):
    """Parity: sparse/nn/layer/activation.py ReLU."""

    def forward(self, x: SparseCooTensor):
        from . import relu
        return relu(x)


class LeakyReLU(Layer):
    """Parity: sparse/nn/layer/activation.py LeakyReLU."""

    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        from .nn_functional import leaky_relu
        return leaky_relu(x, self.negative_slope)


class ReLU6(Layer):
    """Parity: sparse/nn/layer/activation.py ReLU6."""

    def forward(self, x):
        from .nn_functional import relu6
        return relu6(x)


class Softmax(Layer):
    """Parity: sparse/nn/layer/activation.py Softmax (last axis)."""

    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        from .nn_functional import softmax
        return softmax(x, self.axis)


class MaxPool3D(Layer):
    """Parity: sparse/nn/layer/pooling.py MaxPool3D (NDHWC)."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 ceil_mode=False, return_mask=False, data_format="NDHWC",
                 name=None):
        super().__init__()
        if return_mask:
            raise NotImplementedError("sparse MaxPool3D return_mask")
        if ceil_mode:
            raise NotImplementedError(
                "sparse MaxPool3D ceil_mode=True (floor-mode output "
                "shapes only; pad the input instead)")
        if data_format != "NDHWC":
            raise NotImplementedError("sparse MaxPool3D supports NDHWC")
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding

    def forward(self, x):
        from .nn_functional import max_pool3d
        return max_pool3d(x, self.kernel_size, self.stride, self.padding)


class SyncBatchNorm(BatchNorm):
    """Parity: sparse/nn/layer/norm.py SyncBatchNorm — inside one
    compiled mesh program the batch statistics are already global
    (GSPMD reduces them), so the sync variant IS BatchNorm here."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        """Parity: SyncBatchNorm.convert_sync_batchnorm — swap BatchNorm
        sublayers for SyncBatchNorm in place."""
        for name, sub in list(layer._sub_layers.items()):
            if type(sub) is BatchNorm:
                sbn = SyncBatchNorm.__new__(SyncBatchNorm)
                sbn.__dict__ = sub.__dict__
                layer._sub_layers[name] = sbn
            else:
                cls.convert_sync_batchnorm(sub)
        return layer


from . import nn_functional as functional  # noqa: E402,F401
