"""paddle.sparse parity (SURVEY.md §2.8 sparse row).

Reference: python/paddle/sparse/ over phi sparse kernels — SparseCooTensor/
SparseCsrTensor (paddle/phi/core/sparse_coo_tensor.h, sparse_csr_tensor.h),
creation ops, elementwise/matmul, sparse nn. TPU-native: the payload is
jax.experimental.sparse BCOO (XLA-lowered COO); CSR views convert through
COO. Dense<->sparse round trips, values/indices accessors, add/matmul/
relu and a masked variant match the reference API names.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor

__all__ = ["nn", "sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "SparseCsrTensor", "is_sparse", "add", "matmul", "masked_matmul",
           "relu", "to_dense", "to_sparse_coo", "sin", "sinh", "tan", "tanh", "asin", "asinh", "atan", "atanh", "sqrt", "square", "log1p", "abs", "expm1", "neg", "deg2rad", "rad2deg", "pow", "cast", "subtract", "multiply", "divide", "mv", "addmm", "reshape", "transpose", "coalesce", "is_same_shape"]


class SparseCooTensor(Tensor):
    """COO tensor; `.value` holds a BCOO (parity:
    phi::SparseCooTensor)."""

    def __init__(self, bcoo, stop_gradient=True):
        # bypass Tensor.__init__: BCOO is not a jax.Array and must not go
        # through jnp.asarray; fields are set directly (__slots__ layout)
        self.value = bcoo
        self.stop_gradient = stop_gradient
        self.name = "sparse_coo"
        self._grad = None
        self._node = None
        self._out_index = 0
        self._retain_grads = False
        self.persistable = False

    # -- accessors (reference: sparse_coo_tensor.h) ---------------------
    def indices(self) -> Tensor:
        return Tensor(self.value.indices.T)   # paddle layout [ndim, nnz]

    def values(self) -> Tensor:
        # sparse.nn layers attach the autograd-linked values Tensor so
        # gradients flow through sparse pipelines
        vt = getattr(self, "_values_tensor", None)
        if vt is not None:
            return vt
        return Tensor(self.value.data)

    def nnz(self) -> int:
        return int(self.value.nse)

    @property
    def shape(self):
        return list(self.value.shape)

    def to_dense(self) -> Tensor:
        return Tensor(self.value.todense())

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.value.dtype})")


class SparseCsrTensor(SparseCooTensor):
    """CSR view (parity: phi::SparseCsrTensor). Stored as BCOO internally
    (XLA has no native CSR); crows/cols are derived on access."""

    def __init__(self, bcoo, crows=None, cols=None, stop_gradient=True):
        super().__init__(bcoo, stop_gradient)
        self.name = "sparse_csr"
        self._crows = crows
        self._cols = cols

    def crows(self) -> Tensor:
        if self._crows is None:
            rows = np.asarray(self.value.indices[:, 0])
            n_rows = self.value.shape[0]
            counts = np.bincount(rows, minlength=n_rows)
            self._crows = jnp.asarray(
                np.concatenate([[0], np.cumsum(counts)]).astype(np.int64))
        return Tensor(self._crows)

    def cols(self) -> Tensor:
        if self._cols is None:
            self._cols = jnp.asarray(
                np.asarray(self.value.indices[:, 1]).astype(np.int64))
        return Tensor(self._cols)

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True


def _raw(x):
    return x.value if isinstance(x, Tensor) else jnp.asarray(x)


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      stop_gradient=True):
    """Parity: paddle.sparse.sparse_coo_tensor(indices [ndim, nnz],
    values [nnz], shape)."""
    idx = np.asarray(_raw(indices)).T          # BCOO wants [nnz, ndim]
    vals = _raw(values)
    if dtype is not None:
        from ..framework.dtype import convert_dtype
        vals = vals.astype(convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(idx[:, d].max()) + 1 for d in range(idx.shape[1]))
    bcoo = jsparse.BCOO((vals, jnp.asarray(idx)), shape=tuple(shape))
    return SparseCooTensor(bcoo, stop_gradient=stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      stop_gradient=True):
    """Parity: paddle.sparse.sparse_csr_tensor."""
    crows_np = np.asarray(_raw(crows))
    cols_np = np.asarray(_raw(cols))
    vals = _raw(values)
    if dtype is not None:
        from ..framework.dtype import convert_dtype
        vals = vals.astype(convert_dtype(dtype))
    rows = np.repeat(np.arange(len(crows_np) - 1),
                     np.diff(crows_np).astype(np.int64))
    idx = jnp.asarray(np.stack([rows, cols_np], axis=1))
    bcoo = jsparse.BCOO((vals, idx), shape=tuple(shape))
    return SparseCsrTensor(bcoo, crows=jnp.asarray(crows_np),
                           cols=jnp.asarray(cols_np),
                           stop_gradient=stop_gradient)


def is_sparse(x) -> bool:
    return isinstance(x, SparseCooTensor)


def to_dense(x) -> Tensor:
    return x.to_dense() if is_sparse(x) else x


def to_sparse_coo(x, sparse_dim=None) -> SparseCooTensor:
    """Parity: Tensor.to_sparse_coo."""
    dense = _raw(x)
    return SparseCooTensor(jsparse.BCOO.fromdense(dense))


def add(x, y):
    """Sparse+sparse or sparse+dense elementwise add."""
    if is_sparse(x) and is_sparse(y):
        # O(nnz): concatenate coordinates and merge duplicates — never
        # densify (the operands may be astronomically larger than nnz)
        merged = jsparse.BCOO(
            (jnp.concatenate([x.value.data, y.value.data]),
             jnp.concatenate([x.value.indices, y.value.indices])),
            shape=x.value.shape).sum_duplicates()
        return SparseCooTensor(merged)
    if is_sparse(x):
        return Tensor(x.value.todense() + _raw(y))
    return Tensor(_raw(x) + y.value.todense())


def matmul(x, y):
    """Sparse @ dense via BCOO dot (XLA lowers to gather/scatter matmul).
    Parity: paddle.sparse.matmul."""
    if is_sparse(x):
        out = x.value @ _raw(y)
        return Tensor(out)
    if is_sparse(y):
        return Tensor(_raw(x) @ y.value.todense())
    return Tensor(_raw(x) @ _raw(y))


def masked_matmul(x, y, mask: SparseCooTensor):
    """Dense@dense sampled at mask's sparsity (parity:
    paddle.sparse.masked_matmul)."""
    dense = _raw(x) @ _raw(y)
    idx = mask.value.indices
    vals = dense[idx[:, 0], idx[:, 1]]
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=dense.shape))


def relu(x):
    """Parity: paddle.sparse.nn.functional.relu — applies to stored
    values only (autograd threads through the values Tensor)."""
    if is_sparse(x):
        from ..autograd.tape import apply as _apply
        b = x.value
        out_vals = _apply(lambda v: jnp.maximum(v, 0), x.values(),
                          _op_name="sparse_relu")
        st = SparseCooTensor(jsparse.BCOO((out_vals.value, b.indices),
                                          shape=b.shape))
        st._values_tensor = out_vals
        return st
    return Tensor(jnp.maximum(_raw(x), 0))


from . import nn  # noqa: E402,F401  (sparse layer library)


# ---------------------------------------------------------------------------
# elementwise / unary / linalg surface (reference: python/paddle/sparse/
# unary.py, binary.py, multiary.py — phi sparse kernels). Unary ops that
# preserve zero (sin, sqrt of 0, ...) act on stored values only;
# value-pair binary ops align coordinates through the O(nnz) merge in
# `add`.
# ---------------------------------------------------------------------------

def _unary(fn, name, int_to_float=False):
    def op(x, *args, **kwargs):
        if is_sparse(x):
            from ..autograd.tape import apply as _apply
            b = x.value
            vals = x.values()
            out_vals = _apply(lambda v: fn(v, *args, **kwargs), vals,
                              _op_name=f"sparse_{name}")
            st = SparseCooTensor(jsparse.BCOO((out_vals.value, b.indices),
                                              shape=b.shape))
            st._values_tensor = out_vals
            return st
        return Tensor(fn(_raw(x), *args, **kwargs))

    op.__name__ = name
    op.__doc__ = f"Parity: paddle.sparse.{name} (values-only, zero-preserving)."
    return op


sin = _unary(jnp.sin, "sin")
sinh = _unary(jnp.sinh, "sinh")
tan = _unary(jnp.tan, "tan")
tanh = _unary(jnp.tanh, "tanh")
asin = _unary(jnp.arcsin, "asin")
asinh = _unary(jnp.arcsinh, "asinh")
atan = _unary(jnp.arctan, "atan")
atanh = _unary(jnp.arctanh, "atanh")
sqrt = _unary(jnp.sqrt, "sqrt")
square = _unary(jnp.square, "square")
log1p = _unary(jnp.log1p, "log1p")
abs = _unary(jnp.abs, "abs")
expm1 = _unary(jnp.expm1, "expm1")
neg = _unary(jnp.negative, "neg")
deg2rad = _unary(jnp.deg2rad, "deg2rad")
rad2deg = _unary(jnp.rad2deg, "rad2deg")


def pow(x, factor, name=None):
    """Parity: paddle.sparse.pow."""
    return _unary(lambda v: jnp.power(v, factor), "pow")(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    """Parity: paddle.sparse.cast."""
    from ..framework.dtype import convert_dtype
    b = x.value
    idx = b.indices
    vals = b.data
    if index_dtype is not None:
        idx = idx.astype(convert_dtype(index_dtype))
    if value_dtype is not None:
        vals = vals.astype(convert_dtype(value_dtype))
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=b.shape))


def subtract(x, y, name=None):
    """Parity: paddle.sparse.subtract."""
    return add(x, neg(y))


def multiply(x, y, name=None):
    """Parity: paddle.sparse.multiply — elementwise; scalar or matching
    sparse pattern."""
    if not is_sparse(y):
        return _unary(lambda v: v * _raw(y), "multiply")(x)
    # same-coordinate fast path; general intersection via dense fallback
    import numpy as np
    if np.array_equal(np.asarray(x.value.indices),
                      np.asarray(y.value.indices)):
        b = x.value
        return SparseCooTensor(jsparse.BCOO(
            (b.data * y.value.data, b.indices), shape=b.shape))
    return to_sparse_coo(Tensor(x.value.todense() * y.value.todense()))


def divide(x, y, name=None):
    """Parity: paddle.sparse.divide."""
    if not is_sparse(y):
        return _unary(lambda v: v / _raw(y), "divide")(x)
    import numpy as np
    if np.array_equal(np.asarray(x.value.indices),
                      np.asarray(y.value.indices)):
        b = x.value
        return SparseCooTensor(jsparse.BCOO(
            (b.data / y.value.data, b.indices), shape=b.shape))
    return Tensor(x.value.todense() / y.value.todense())


def mv(x, vec, name=None):
    """Parity: paddle.sparse.mv — sparse matrix x dense vector."""
    return matmul(x, vec)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """Parity: paddle.sparse.addmm — beta*input + alpha*(x @ y)."""
    prod = matmul(x, y)
    return Tensor(beta * _raw(input) + alpha * _raw(prod))


def reshape(x, shape, name=None):
    """Parity: paddle.sparse.reshape — re-derive COO coords for the new
    shape (host index math on nnz entries)."""
    import numpy as np
    b = x.value
    old_shape = b.shape
    flat = np.ravel_multi_index(
        tuple(np.asarray(b.indices).T), old_shape)
    new_idx = np.stack(np.unravel_index(flat, tuple(
        int(s) for s in shape)), 1)
    return SparseCooTensor(jsparse.BCOO(
        (b.data, jnp.asarray(new_idx)), shape=tuple(int(s) for s in shape)))


def transpose(x, perm, name=None):
    """Parity: paddle.sparse.transpose."""
    b = x.value
    idx = b.indices[:, jnp.asarray(list(perm))]
    shape = tuple(b.shape[p] for p in perm)
    return SparseCooTensor(jsparse.BCOO((b.data, idx), shape=shape))


def coalesce(x, name=None):
    """Parity: paddle.sparse.coalesce — merge duplicate coordinates."""
    import numpy as np
    b = x.value
    idx = np.asarray(b.indices)
    flat = np.ravel_multi_index(tuple(idx.T), b.shape)
    uniq, inv = np.unique(flat, return_inverse=True)
    merged = jax.ops.segment_sum(b.data, jnp.asarray(inv),
                                 num_segments=len(uniq))
    new_idx = np.stack(np.unravel_index(uniq, b.shape), 1)
    return SparseCooTensor(jsparse.BCOO(
        (merged, jnp.asarray(new_idx)), shape=b.shape))


def is_same_shape(x, y):
    """Parity: paddle.sparse.is_same_shape."""
    return list(x.shape) == list(y.shape)
