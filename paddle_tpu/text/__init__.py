"""paddle.text parity — viterbi decoding + classic NLP dataset parsers.

Reference: python/paddle/text/ (viterbi_decode.py:25, datasets/). The
reference's viterbi_decode is a CUDA kernel; here it is a `lax.scan`
over time with per-sequence length masking — one compiled program,
batch-parallel on the VPU.
"""
from . import datasets  # noqa: F401
from .viterbi_decode import ViterbiDecoder, viterbi_decode

__all__ = ["viterbi_decode", "ViterbiDecoder", "datasets"]
