"""paddle.text parity — viterbi decoding + classic NLP dataset parsers.

Reference: python/paddle/text/ (viterbi_decode.py:25, datasets/). The
reference's viterbi_decode is a CUDA kernel; here it is a `lax.scan`
over time with per-sequence length masking — one compiled program,
batch-parallel on the VPU.
"""
from . import datasets  # noqa: F401
from .datasets import Imdb, Imikolov, Movielens, UCIHousing  # noqa: F401
from .viterbi_decode import ViterbiDecoder, viterbi_decode


class _Undownloadable:
    """Reference datasets whose sources are multi-file downloads the
    zero-egress build cannot fetch; constructing raises with guidance."""

    _name = ""

    def __init__(self, *a, **kw):
        raise RuntimeError(
            f"{self._name}: automatic download is unavailable in this "
            f"build (no network egress) and no local-file parser is "
            f"provided yet; use UCIHousing/Imdb/Imikolov/Movielens or "
            f"load the corpus manually")


class Conll05st(_Undownloadable):
    _name = "Conll05st"


class WMT14(_Undownloadable):
    _name = "WMT14"


class WMT16(_Undownloadable):
    _name = "WMT16"


__all__ = ["viterbi_decode", "ViterbiDecoder", "datasets", "Imdb",
           "Imikolov", "Movielens", "UCIHousing", "Conll05st", "WMT14",
           "WMT16"]
