"""Viterbi decoding. Parity: python/paddle/text/viterbi_decode.py:25
(viterbi_decode) and :101 (ViterbiDecoder layer).

include_bos_eos_tag=True treats the LAST row/column of the transition
matrix as the start tag and the second-to-last as the stop tag (the
reference's convention): the start row is added at t=0 and the stop
column at each sequence's final step.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..autograd.tape import apply
from ..core.tensor import Tensor
from ..nn.layer_base import Layer

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def _decode(pot, trans, lengths, include_tag):
    B, S, T = pot.shape
    lengths = lengths.astype(jnp.int32)
    alpha = pot[:, 0]
    if include_tag:
        alpha = alpha + trans[-1][None, :]
    # stop contribution for length-1 sequences
    stop = trans[:, -2][None, :] if include_tag else jnp.zeros((1, T),
                                                               pot.dtype)
    alpha = jnp.where((lengths == 1)[:, None], alpha + stop, alpha)

    def step(carry, t):
        alpha = carry
        # scores[b, j, k] = alpha[b, j] + trans[j, k]
        scores = alpha[:, :, None] + trans[None]
        best_prev = jnp.argmax(scores, axis=1)            # (B, T)
        new_alpha = jnp.max(scores, axis=1) + pot[:, t]
        is_last = (t == lengths - 1)[:, None]
        new_alpha = jnp.where(is_last, new_alpha + stop, new_alpha)
        active = (t < lengths)[:, None]
        alpha = jnp.where(active, new_alpha, alpha)
        bp = jnp.where(active, best_prev,
                       jnp.broadcast_to(jnp.arange(T)[None], (B, T)))
        return alpha, bp

    alpha, bps = lax.scan(step, alpha, jnp.arange(1, S))
    scores = jnp.max(alpha, axis=1)
    last_tag = jnp.argmax(alpha, axis=1)

    # backtrack from each sequence's end through the backpointers
    def back(carry, bp_t):
        tag, t = carry
        # bp_t corresponds to timestep t+1; only follow when t+1 < length
        prev = jnp.take_along_axis(bp_t, tag[:, None], 1)[:, 0]
        follow = (t + 1) <= (lengths - 1)
        new_tag = jnp.where(follow, prev, tag)
        return (new_tag, t - 1), new_tag

    if S > 1:
        # reverse scan: rev_tags[i] = tag at step i (bps[i] maps step
        # i+1 tags to their best step-i predecessor; frozen steps carry
        # identity backpointers so short sequences stay fixed)
        (_, _), rev_tags = lax.scan(
            back, (last_tag, jnp.full((), S - 2)), bps, reverse=True)
        path = jnp.concatenate(
            [jnp.moveaxis(rev_tags, 0, 1), last_tag[:, None]],
            axis=1).astype(jnp.int32)
    else:
        path = last_tag[:, None].astype(jnp.int32)
    mask = jnp.arange(S)[None, :] < lengths[:, None]
    path = jnp.where(mask, path, 0)
    return scores, path


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    def f(pot, trans, lens):
        return _decode(pot, trans, lens, include_bos_eos_tag)

    scores, path = apply(f, potentials, transition_params, lengths,
                         _op_name="viterbi_decode")
    # reference trims the path to max(lengths)
    lens = lengths.value if isinstance(lengths, Tensor) \
        else jnp.asarray(lengths)
    max_len = int(jax.device_get(jnp.max(lens)))
    path = Tensor(path.value[:, :max_len], stop_gradient=True)
    return scores, path


class ViterbiDecoder(Layer):
    """Parity: text/viterbi_decode.py:101."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
